"""NDArray: the imperative n-dimensional array.

Reference parity: python/mxnet/ndarray/ndarray.py (class NDArray ~L1-2000)
over src/ndarray/ndarray.cc (Chunk ~L80, CopyFromTo ~L600).

TPU-native design: an NDArray owns an immutable ``jax.Array`` (or a jax
tracer inside a HybridBlock trace).  MXNet's mutation semantics (``x += y``,
``x[1:3] = v``, kvstore writing into parameter buffers) are provided by
*buffer swap*: every mutation computes a new device array and swaps it in,
bumping a version counter.  Because the underlying buffers never change,
autograd tape residuals and async readers stay valid with no engine
write-hazard tracking — the role of the reference's var-version bookkeeping
(threaded_engine.cc ~L300) is played by immutability itself.

Async semantics come from PjRt: dispatch returns immediately;
``wait_to_read``/``asnumpy`` block, matching Engine::WaitForVar.
"""
from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple, Union

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from .. import engine
from ..ops import registry as _reg

__all__ = ["NDArray", "array", "from_jax"]


def _jnp():
    import jax.numpy as jnp

    return jnp


class NDArray:
    __slots__ = ("_data", "_ctx", "_version", "_grad", "_grad_req", "_detached",
                 "__weakref__")

    # numpy should defer to our reflected dunders
    __array_priority__ = 1000.0

    def __init__(self, data, ctx: Optional[Context] = None):
        self._data = data
        self._ctx = ctx or current_context()
        self._version = 0
        self._grad = None
        self._grad_req = "write"
        self._detached = False
        engine.track(self)

    # ------------------------------------------------------------------
    # core properties
    # ------------------------------------------------------------------
    @property
    def data(self):
        return self._data

    @property
    def shape(self) -> Tuple[int, ...]:
        return tuple(self._data.shape)

    @property
    def dtype(self):
        return np.dtype(self._data.dtype).type

    @property
    def size(self) -> int:
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self) -> int:
        return self._data.ndim

    @property
    def context(self) -> Context:
        return self._ctx

    ctx = context

    @property
    def stype(self) -> str:
        return "default"

    @property
    def T(self) -> "NDArray":
        return self.transpose()

    @property
    def grad(self) -> Optional["NDArray"]:
        return self._grad

    @property
    def version(self) -> int:
        return self._version

    def __len__(self) -> int:
        if not self.shape:
            raise TypeError("len() of unsized object")
        return self.shape[0]

    def __repr__(self):
        try:
            body = str(self.asnumpy())
        except Exception:
            body = f"<unrealized {self._data}>"
        return f"\n{body}\n<NDArray {'x'.join(map(str, self.shape))} @{self._ctx}>"

    def __bool__(self):
        if self.size != 1:
            raise ValueError("ambiguous truth value of multi-element NDArray")
        return bool(self.asnumpy().item())

    # ------------------------------------------------------------------
    # mutation (buffer swap)
    # ------------------------------------------------------------------
    def _set_data(self, new_data) -> None:
        self._data = new_data
        self._version += 1

    # ------------------------------------------------------------------
    # host transfer / sync
    # ------------------------------------------------------------------
    def asnumpy(self) -> np.ndarray:
        """Blocking copy to host (reference: NDArray.asnumpy ~L2000)."""
        return np.asarray(self._data)

    def asscalar(self):
        if self.size != 1:
            raise ValueError("The current array is not a scalar")
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __index__(self):
        return int(self.asscalar())

    def wait_to_read(self) -> None:
        data = self._data
        if hasattr(data, "block_until_ready"):
            data.block_until_ready()

    def wait_to_write(self) -> None:
        self.wait_to_read()

    # ------------------------------------------------------------------
    # dtype / device movement
    # ------------------------------------------------------------------
    def astype(self, dtype, copy: bool = True) -> "NDArray":
        tgt = dtype_np(dtype)
        if not copy and np.dtype(self._data.dtype) == tgt:
            return self
        return _reg.invoke_fn(lambda x: x.astype(tgt), [self])

    def copy(self) -> "NDArray":
        return NDArray(self._data, ctx=self._ctx)

    def copyto(self, other: Union["NDArray", Context]) -> "NDArray":
        import jax

        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device), ctx=other)
        other._set_data(jax.device_put(self._data, other.context.jax_device))
        return other

    def as_in_context(self, ctx: Context) -> "NDArray":
        if ctx == self._ctx:
            return self
        import jax

        return NDArray(jax.device_put(self._data, ctx.jax_device), ctx=ctx)

    as_in_ctx = as_in_context

    def as_nd_ndarray(self):
        return self

    def tostype(self, stype: str):
        if stype == "default":
            return self
        from . import sparse as _sparse

        if stype == "row_sparse":
            return _sparse.row_sparse_array(self)
        if stype == "csr":
            return _sparse.csr_matrix(self)
        raise MXNetError(f"unknown storage type {stype!r}")

    # ------------------------------------------------------------------
    # autograd hooks
    # ------------------------------------------------------------------
    def attach_grad(self, grad_req: str = "write", stype: Optional[str] = None) -> None:
        from .. import autograd

        jnp = _jnp()
        self._grad = NDArray(jnp.zeros_like(self._data), ctx=self._ctx)
        self._grad_req = grad_req
        autograd.register_leaf(self)

    def detach(self) -> "NDArray":
        """Return an array sharing this buffer but excluded from gradient
        flow.  Zero-copy: ops.registry applies stop_gradient at use-site."""
        out = NDArray(self._data, ctx=self._ctx)
        out._detached = True
        return out

    def backward(self, out_grad=None, retain_graph: bool = False,
                 train_mode: bool = True) -> None:
        from .. import autograd

        autograd.backward([self], [out_grad] if out_grad is not None else None,
                          retain_graph=retain_graph, train_mode=train_mode)

    # ------------------------------------------------------------------
    # indexing
    # ------------------------------------------------------------------
    @staticmethod
    def _convert_key(key):
        if isinstance(key, NDArray):
            return key._data.astype("int32")
        if isinstance(key, tuple):
            return tuple(
                k._data.astype("int32") if isinstance(k, NDArray) else k for k in key
            )
        return key

    def _check_int_bounds(self, key) -> None:
        """Bounds-check int indices (bare or inside a tuple): jax clamps
        out-of-range gathers, which would make Python's legacy iteration
        protocol spin forever and silently alias OOB element access
        (reference: ndarray.py __getitem__ raises IndexError)."""
        def is_int(k):
            return (isinstance(k, (int, np.integer))
                    and not isinstance(k, (bool, np.bool_)))

        if is_int(key):
            entries = [(0, key)]
        elif (isinstance(key, tuple) and Ellipsis not in key
                and not any(k is None for k in key)):  # None shifts axes
            entries = [(ax, k) for ax, k in enumerate(key) if is_int(k)]
        else:
            return
        for ax, k in entries:
            n = self.shape[ax] if ax < len(self.shape) else 0
            if not -n <= k < n:
                raise IndexError(f"index {k} is out of bounds for axis "
                                 f"{ax} with size {n}")

    def __getitem__(self, key) -> "NDArray":
        self._check_int_bounds(key)
        k = self._convert_key(key)
        return _reg.invoke_fn(lambda x: x[k], [self])

    def __setitem__(self, key, value) -> None:
        k = self._convert_key(key)
        jnp = _jnp()
        if isinstance(value, NDArray):
            v = value._data
        elif isinstance(value, (np.ndarray, list, tuple, int, float)):
            v = jnp.asarray(value, dtype=self._data.dtype)
        else:
            v = value
        if isinstance(k, slice) and k == slice(None):
            new = jnp.broadcast_to(
                jnp.asarray(v, dtype=self._data.dtype), self.shape
            )
        else:
            new = self._data.at[k].set(v)
        self._set_data(new)

    # ------------------------------------------------------------------
    # arithmetic sugar (reference: broadcast_* dispatch in ndarray.py)
    # ------------------------------------------------------------------
    def _binary(self, other, opname, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            return _reg.invoke_by_name(opname, [a, b])
        if isinstance(other, (int, float, bool, np.generic)):
            jnp = _jnp()
            scalar = NDArray(
                jnp.asarray(other, dtype=self._data.dtype), ctx=self._ctx
            )
            a, b = (scalar, self) if reverse else (self, scalar)
            return _reg.invoke_by_name(opname, [a, b])
        return NotImplemented

    def __add__(self, other):
        return self._binary(other, "broadcast_add")

    __radd__ = __add__

    def __sub__(self, other):
        return self._binary(other, "broadcast_sub")

    def __rsub__(self, other):
        return self._binary(other, "broadcast_sub", reverse=True)

    def __mul__(self, other):
        return self._binary(other, "broadcast_mul")

    __rmul__ = __mul__

    def __truediv__(self, other):
        return self._binary(other, "broadcast_div")

    def __rtruediv__(self, other):
        return self._binary(other, "broadcast_div", reverse=True)

    def __mod__(self, other):
        return self._binary(other, "broadcast_mod")

    def __rmod__(self, other):
        return self._binary(other, "broadcast_mod", reverse=True)

    def __pow__(self, other):
        # Python scalars stay STATIC attrs on float arrays (reference
        # _power_scalar): an exponent materialized as an array input would
        # add a d/d(exponent) = x^b*log(x) gradient path — NaN for x < 0
        # even under a zero cotangent in second-order backward.  Integer
        # arrays keep the _binary path (scalar cast to the array dtype, no
        # gradients to protect).
        from ..base import is_float_dtype

        if isinstance(other, (int, float, np.generic)):
            if is_float_dtype(self._data.dtype):
                return _reg.invoke_by_name("_power_scalar", [self],
                                           scalar=float(other))
            if not float(other).is_integer():
                # int array ** fractional exponent: promote (the _binary
                # path would truncate the exponent to the int dtype)
                return _reg.invoke_by_name(
                    "_power_scalar", [self.astype("float32")],
                    scalar=float(other))
        return self._binary(other, "broadcast_power")

    def __rpow__(self, other):
        from ..base import is_float_dtype

        if isinstance(other, (int, float, np.generic)):
            if is_float_dtype(self._data.dtype):
                return _reg.invoke_by_name("_rpower_scalar", [self],
                                           scalar=float(other))
            if not float(other).is_integer():
                return _reg.invoke_by_name(
                    "_rpower_scalar", [self.astype("float32")],
                    scalar=float(other))
        return self._binary(other, "broadcast_power", reverse=True)

    def __neg__(self):
        return _reg.invoke_by_name("negative", [self])

    def __abs__(self):
        return _reg.invoke_by_name("abs", [self])

    def __eq__(self, other):
        if other is None:
            return False
        return self._binary(other, "broadcast_equal")

    def __ne__(self, other):
        if other is None:
            return True
        return self._binary(other, "broadcast_not_equal")

    def __gt__(self, other):
        return self._binary(other, "broadcast_greater")

    def __ge__(self, other):
        return self._binary(other, "broadcast_greater_equal")

    def __lt__(self, other):
        return self._binary(other, "broadcast_lesser")

    def __le__(self, other):
        return self._binary(other, "broadcast_lesser_equal")

    def __hash__(self):
        return id(self)

    # in-place: buffer swap
    def _inplace(self, other, opname):
        res = self._binary(other, opname)
        if res is NotImplemented:
            return res
        self._set_data(res._data)
        return self

    def __iadd__(self, other):
        return self._inplace(other, "broadcast_add")

    def __isub__(self, other):
        return self._inplace(other, "broadcast_sub")

    def __imul__(self, other):
        return self._inplace(other, "broadcast_mul")

    def __itruediv__(self, other):
        return self._inplace(other, "broadcast_div")

    # ------------------------------------------------------------------
    # op methods: any registered op name is available as a method with
    # `self` as first input (parity with MXNet's autogenerated methods).
    # ------------------------------------------------------------------
    def __getattr__(self, name):
        # Resolve through the nd-namespace stubs so positional attrs map to
        # op kwargs identically whether called as nd.op(x, ...) or x.op(...).
        import sys

        stub = sys.modules[__package__].__dict__.get(name)
        if stub is None or not callable(stub):
            raise AttributeError(
                f"'NDArray' object has no attribute {name!r}"
            )
        nd = self

        def method(*args, **kwargs):
            return stub(nd, *args, **kwargs)

        method.__name__ = name
        return method

    # explicit common methods (avoid __getattr__ for the hot ones and for
    # those whose python-level signature differs from the raw op)
    def reshape(self, *shape, **kwargs):
        if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
            shape = tuple(shape[0])
        shape = kwargs.get("shape", shape)
        return _reg.invoke_by_name("reshape", [self], shape=tuple(shape),
                                   reverse=kwargs.get("reverse", False))

    def reshape_like(self, other):
        return _reg.invoke_fn(lambda x, y: x.reshape(y.shape), [self, other])

    def transpose(self, *axes, **kwargs):
        if "axes" in kwargs:
            axes = kwargs["axes"]  # reference spelling: x.transpose(axes=(...))
        elif len(axes) == 1 and isinstance(axes[0], (tuple, list)):
            axes = axes[0]
        return _reg.invoke_by_name("transpose", [self], axes=tuple(axes))

    def flatten(self):
        return _reg.invoke_by_name("Flatten", [self])

    def expand_dims(self, axis):
        return _reg.invoke_by_name("expand_dims", [self], axis=axis)

    def squeeze(self, axis=None):
        return _reg.invoke_by_name("squeeze", [self], axis=axis)

    def sum(self, axis=None, keepdims=False, **kw):
        return _reg.invoke_by_name("sum", [self], axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False, **kw):
        return _reg.invoke_by_name("mean", [self], axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False):
        return _reg.invoke_by_name("max", [self], axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False):
        return _reg.invoke_by_name("min", [self], axis=axis, keepdims=keepdims)

    def argmax(self, axis=None, keepdims=False):
        return _reg.invoke_by_name("argmax", [self], axis=axis, keepdims=keepdims)

    def argmin(self, axis=None, keepdims=False):
        return _reg.invoke_by_name("argmin", [self], axis=axis, keepdims=keepdims)

    def clip(self, a_min=None, a_max=None):
        return _reg.invoke_by_name("clip", [self], a_min=a_min, a_max=a_max)

    def abs(self):
        return _reg.invoke_by_name("abs", [self])

    def slice_axis(self, axis, begin, end):
        return _reg.invoke_by_name("slice_axis", [self], axis=axis, begin=begin,
                                   end=end)

    def zeros_like(self):
        return _reg.invoke_by_name("zeros_like", [self])

    def ones_like(self):
        return _reg.invoke_by_name("ones_like", [self])


def array(source, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    """Create an NDArray from array-like (reference: mx.nd.array)."""
    import jax

    ctx = ctx or current_context()
    if isinstance(source, NDArray):
        src = source.asnumpy()
    else:
        src = np.asarray(source)
    if dtype is None:
        dtype = np.float32 if src.dtype == np.float64 else src.dtype
    src = src.astype(dtype_np(dtype), copy=False)
    return NDArray(jax.device_put(src, ctx.jax_device), ctx=ctx)


def from_jax(arr, ctx: Optional[Context] = None) -> NDArray:
    return NDArray(arr, ctx=ctx)
