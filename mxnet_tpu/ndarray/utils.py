"""NDArray serialization: ``mx.nd.save`` / ``mx.nd.load``.

Reference parity: src/ndarray/ndarray.cc NDArray::Save/Load (~L1500) and
python mx.nd.save/load — a single file holding either a list of arrays or a
str->array map.

Two formats:

  * native:  magic 'MXTPND01' | u64 header_len | header JSON | raw buffers
    (bfloat16 stored as raw uint16 payload, dtype in the header);
  * legacy MXNet 1.x (READ + WRITE, for ecosystem checkpoint compat — the
    format of src/ndarray/ndarray.cc NDArray::Save and c_api.cc
    MXNDArraySave):
        u64 0x112 (kMXAPINDListMagic) | u64 reserved
        u64 count | count * NDArray records
        u64 name_count | name_count * (u64 len | bytes)
    each dense NDArray record being
        u32 0xF993FAC9 (V2 magic) | i32 stype(=0 dense)
        u32 ndim | i64 dims[ndim]          (V1 files: u32 dims)
        i32 dev_type | i32 dev_id | i32 type_flag | raw data

``load`` dispatches on the leading magic, so reference-produced .params /
nd.save files open transparently.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, List, Union

import numpy as np

from ..base import MXNetError, dtype_np

_MAGIC = b"MXTPND01"

# legacy constants (reference: src/ndarray/ndarray.cc ~L1500,
# c_api.cc MXNDArraySave)
_LEGACY_LIST_MAGIC = 0x112
_LEGACY_V1_MAGIC = 0xF993FAC8
_LEGACY_V2_MAGIC = 0xF993FAC9
# mshadow type flags (3rdparty/mshadow/mshadow/base.h TypeFlag)
_LEGACY_DTYPES = {0: "float32", 1: "float64", 2: "float16", 3: "uint8",
                  4: "int32", 5: "int8", 6: "int64", 12: "bfloat16"}
_LEGACY_FLAGS = {v: k for k, v in _LEGACY_DTYPES.items()}


def _to_bytes(arr: np.ndarray):
    dtype = np.dtype(arr.dtype)
    name = dtype.name if dtype.kind != "V" else "bfloat16"
    if name == "bfloat16":
        raw = arr.view(np.uint16)
        return name, raw.tobytes()
    return name, np.ascontiguousarray(arr).tobytes()


def _from_bytes(buf: bytes, dtype_name: str, shape):
    if dtype_name == "bfloat16":
        arr = np.frombuffer(buf, dtype=np.uint16).reshape(shape)
        return arr.view(dtype_np("bfloat16"))
    return np.frombuffer(buf, dtype=np.dtype(dtype_name)).reshape(shape)


def save(fname: str, data) -> None:
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        names = [str(i) for i in range(len(data))]
        arrays = list(data)
        keyed = False
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
        keyed = True
    else:
        raise MXNetError("save expects NDArray, list, or dict of NDArrays")

    entries = []
    payloads = []
    for name, nd in zip(names, arrays):
        arr = nd.asnumpy()
        dtname, raw = _to_bytes(arr)
        entries.append({"name": name, "dtype": dtname, "shape": list(arr.shape),
                        "nbytes": len(raw)})
        payloads.append(raw)
    header = json.dumps({"keyed": keyed, "entries": entries}).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for p in payloads:
            f.write(p)


# ---------------------------------------------------------------------------
# legacy MXNet 1.x format
# ---------------------------------------------------------------------------
class _Reader:
    def __init__(self, buf: bytes):
        self.buf = buf
        self.pos = 0

    def read(self, fmt: str):
        vals = struct.unpack_from("<" + fmt, self.buf, self.pos)
        self.pos += struct.calcsize("<" + fmt)
        return vals if len(vals) > 1 else vals[0]

    def raw(self, n: int) -> bytes:
        out = self.buf[self.pos:self.pos + n]
        if len(out) != n:
            raise MXNetError("legacy NDArray file truncated")
        self.pos += n
        return out


def _legacy_read_ndarray(r: _Reader) -> np.ndarray:
    magic = r.read("I")
    if magic == _LEGACY_V2_MAGIC:
        stype = r.read("i")
        if stype != 0:
            raise MXNetError(
                "legacy sparse NDArray records are not supported; re-save "
                "densely (kDefaultStorage)")
        dim_fmts = ("q", "I")  # 1.5+ int64 TShape dims; pre-1.5 uint32
    elif magic == _LEGACY_V1_MAGIC:
        dim_fmts = ("I",)
    else:
        raise MXNetError(f"bad legacy NDArray magic {magic:#x}")
    ndim = r.read("I")
    if ndim > 32:
        raise MXNetError(f"implausible legacy ndim {ndim}")

    # The dim width is not recorded in the file, so validate each candidate
    # parse against everything that follows it: plausible dims, a plausible
    # (dev_type, dev_id, type_flag) triple, and a payload that fits in the
    # remaining buffer.  (A wrong-width parse passes none of these: e.g.
    # uint32 dims (3,4) read as one int64 is ~1.7e10 elements.)
    start = r.pos
    parses = []
    for fmt in dim_fmts:
        r.pos = start
        try:
            dims = [r.read(fmt) for _ in range(ndim)] if ndim else []
            dev_type, dev_id = r.read("ii")
            type_flag = r.read("i")
        except struct.error:
            continue
        name = _LEGACY_DTYPES.get(type_flag)
        count = int(np.prod(dims)) if dims else 1
        itemsize = 2 if name == "bfloat16" else (
            np.dtype(name).itemsize if name else 0)
        ok = (name is not None
              and all(0 <= d < (1 << 40) for d in dims)
              and 1 <= dev_type <= 16 and 0 <= dev_id < 4096
              and r.pos + count * itemsize <= len(r.buf))
        parses.append((ok, dims, name, count, itemsize, r.pos))
    for ok, dims, name, count, itemsize, pos in parses:
        if ok:
            r.pos = pos
            break
    else:
        raise MXNetError(
            "cannot parse legacy NDArray record (unknown dim width / "
            "type flag)")
    if name == "bfloat16":
        raw = r.raw(count * 2)
        return np.frombuffer(raw, np.uint16).reshape(dims).view(
            dtype_np("bfloat16"))
    dt = np.dtype(name)
    raw = r.raw(count * dt.itemsize)
    return np.frombuffer(raw, dt).reshape(dims)


def _load_legacy(buf: bytes):
    from . import array

    r = _Reader(buf)
    magic, _reserved = r.read("QQ")
    assert magic == _LEGACY_LIST_MAGIC
    n = r.read("Q")
    arrays = [_legacy_read_ndarray(r) for _ in range(n)]
    n_names = r.read("Q")
    names = []
    for _ in range(n_names):
        ln = r.read("Q")
        names.append(r.raw(ln).decode())
    nds = [array(a, dtype=a.dtype) for a in arrays]
    if names:
        if len(names) != len(nds):
            raise MXNetError("legacy file: name/array count mismatch")
        return dict(zip(names, nds))
    return nds


def save_legacy(fname: str, data) -> None:
    """Write the MXNet 1.x binary format so checkpoints round-trip into
    reference tooling (same layout _load_legacy reads)."""
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    with open(fname, "wb") as f:
        f.write(struct.pack("<QQ", _LEGACY_LIST_MAGIC, 0))
        f.write(struct.pack("<Q", len(arrays)))
        for nd_ in arrays:
            arr = nd_.asnumpy()
            dtname, raw = _to_bytes(arr)
            if dtname not in _LEGACY_FLAGS:
                raise MXNetError(f"dtype {dtname} has no legacy type flag")
            f.write(struct.pack("<Ii", _LEGACY_V2_MAGIC, 0))
            f.write(struct.pack("<I", arr.ndim))
            for d in arr.shape:
                f.write(struct.pack("<q", d))
            f.write(struct.pack("<iii", 1, 0, _LEGACY_FLAGS[dtname]))
            f.write(raw)
        f.write(struct.pack("<Q", len(names)))
        for name in names:
            b = name.encode()
            f.write(struct.pack("<Q", len(b)))
            f.write(b)


def load(fname: str):
    from . import array
    from .ndarray import NDArray

    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            if (len(magic) == 8
                    and struct.unpack("<Q", magic)[0] == _LEGACY_LIST_MAGIC):
                return _load_legacy(magic + f.read())
            raise MXNetError(f"{fname}: not an mxnet_tpu NDArray file")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        out = []
        for e in header["entries"]:
            raw = f.read(e["nbytes"])
            np_arr = _from_bytes(raw, e["dtype"], tuple(e["shape"]))
            out.append((e["name"], array(np_arr, dtype=np_arr.dtype)))
    if header["keyed"]:
        return dict(out)
    return [nd for _, nd in out]
