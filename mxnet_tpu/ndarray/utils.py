"""NDArray serialization: ``mx.nd.save`` / ``mx.nd.load``.

Reference parity: src/ndarray/ndarray.cc NDArray::Save/Load (~L1500) and
python mx.nd.save/load — a single file holding either a list of arrays or a
str->array map.  We use our own container format (the reference's binary
layout embeds mshadow TBlob internals that have no meaning here):

    magic 'MXTPND01' | u64 header_len | header JSON | raw little-endian buffers

bfloat16 is stored as raw uint16 payload with dtype recorded in the header.
"""
from __future__ import annotations

import json
import struct
from typing import Dict, List, Union

import numpy as np

from ..base import MXNetError, dtype_np

_MAGIC = b"MXTPND01"


def _to_bytes(arr: np.ndarray):
    dtype = np.dtype(arr.dtype)
    name = dtype.name if dtype.kind != "V" else "bfloat16"
    if name == "bfloat16":
        raw = arr.view(np.uint16)
        return name, raw.tobytes()
    return name, np.ascontiguousarray(arr).tobytes()


def _from_bytes(buf: bytes, dtype_name: str, shape):
    if dtype_name == "bfloat16":
        arr = np.frombuffer(buf, dtype=np.uint16).reshape(shape)
        return arr.view(dtype_np("bfloat16"))
    return np.frombuffer(buf, dtype=np.dtype(dtype_name)).reshape(shape)


def save(fname: str, data) -> None:
    from .ndarray import NDArray

    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, (list, tuple)):
        names = [str(i) for i in range(len(data))]
        arrays = list(data)
        keyed = False
    elif isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
        keyed = True
    else:
        raise MXNetError("save expects NDArray, list, or dict of NDArrays")

    entries = []
    payloads = []
    for name, nd in zip(names, arrays):
        arr = nd.asnumpy()
        dtname, raw = _to_bytes(arr)
        entries.append({"name": name, "dtype": dtname, "shape": list(arr.shape),
                        "nbytes": len(raw)})
        payloads.append(raw)
    header = json.dumps({"keyed": keyed, "entries": entries}).encode()
    with open(fname, "wb") as f:
        f.write(_MAGIC)
        f.write(struct.pack("<Q", len(header)))
        f.write(header)
        for p in payloads:
            f.write(p)


def load(fname: str):
    from . import array
    from .ndarray import NDArray

    with open(fname, "rb") as f:
        magic = f.read(8)
        if magic != _MAGIC:
            raise MXNetError(f"{fname}: not an mxnet_tpu NDArray file")
        (hlen,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(hlen).decode())
        out = []
        for e in header["entries"]:
            raw = f.read(e["nbytes"])
            np_arr = _from_bytes(raw, e["dtype"], tuple(e["shape"]))
            out.append((e["name"], array(np_arr, dtype=np_arr.dtype)))
    if header["keyed"]:
        return dict(out)
    return [nd for _, nd in out]
