"""Sparse NDArray storage types: row_sparse and csr.

Reference parity: python/mxnet/ndarray/sparse.py (RowSparseNDArray,
CSRNDArray, row_sparse_array, csr_matrix) over src/ndarray/ndarray.cc
storage types (include/mxnet/ndarray.h NDArrayStorageType ~L60) and the
FComputeEx sparse kernels in src/operator/tensor/.

TPU-native design (SURVEY §7.3 #8): XLA has no sparse tensors, so sparse
storage lives at the NDArray layer as (values, indices[, indptr]) component
arrays; compute lowers to dense gathers/scatters and segment ops, which XLA
maps well onto the TPU's gather/scatter units.  row_sparse keeps its key
role from the reference — compact gradients for Embedding-style lookups and
the optimizers' lazy row-wise updates (optimizer sparse paths consume the
(indices, values) pair directly, exactly like the reference's
sgd_update(row_sparse) kernels).
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from .ndarray import NDArray, array as _dense_array

__all__ = ["BaseSparseNDArray", "RowSparseNDArray", "CSRNDArray",
           "row_sparse_array", "csr_matrix", "zeros", "array", "empty",
           "retain", "dot", "elemwise_add", "elemwise_sub", "elemwise_mul",
           "elemwise_div", "add", "subtract", "multiply", "divide",
           "zeros_like"]


class BaseSparseNDArray(NDArray):
    """Common behavior for the compressed storage types."""

    # NDArray.__slots__ covers _data/_ctx/...; sparse adds component arrays
    __slots__ = ("_aux", "_shape")

    def __init__(self, data, aux: dict, shape: Tuple[int, ...], ctx=None):
        super().__init__(data, ctx=ctx)
        self._aux = aux
        self._shape = tuple(int(s) for s in shape)

    @property
    def shape(self):
        return self._shape

    @property
    def _num_aux(self):
        return len(self._aux)

    @property
    def data(self):
        """The values component (reference: .data attribute)."""
        return NDArray(self._data, ctx=self._ctx)

    def __repr__(self):
        return (f"<{type(self).__name__} {self._shape} "
                f"ctx={self._ctx}>")

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self.todense()._data)

    def astype(self, dtype, copy: bool = True):
        out = self.todense().astype(dtype)
        return out

    def todense(self) -> NDArray:
        raise NotImplementedError

    def tostype(self, stype: str):
        if stype == "default":
            return self.todense()
        if stype == self.stype:
            return self
        return self.todense().tostype(stype)

    def copyto(self, other):
        if isinstance(other, Context):
            import jax

            aux = {k: jax.device_put(v, other.jax_device)
                   for k, v in self._aux.items()}
            return type(self)._from_components(
                jax.device_put(self._data, other.jax_device), aux,
                self._shape, other)
        return super().copyto(other)

    def __getitem__(self, key):
        return self.todense()[key]

    def __setitem__(self, key, value):
        raise MXNetError(f"{type(self).__name__} does not support "
                         "item assignment; convert with tostype('default')")


class RowSparseNDArray(BaseSparseNDArray):
    """Rows at `indices` hold `values`; all other rows are zero
    (reference: RowSparseNDArray — the gradient type of sparse Embedding)."""

    @property
    def stype(self) -> str:
        return "row_sparse"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"], ctx=self._ctx)

    @classmethod
    def _from_components(cls, values, aux, shape, ctx):
        return cls(values, dict(aux), shape, ctx=ctx)

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        def fn():
            out = jnp.zeros(self._shape, self._data.dtype)
            return out.at[self._aux["indices"]].set(self._data)

        return NDArray(fn(), ctx=self._ctx)

    def _set_sparse_components(self, values, indices) -> None:
        """In-place swap of (values, indices) — the sparse analog of
        NDArray._set_data, used by autograd to write row_sparse gradients
        into an attached grad buffer."""
        self._data = values
        self._aux["indices"] = indices

    def zero(self) -> None:
        """Reset to nnz=0 (Parameter.zero_grad on sparse grad buffers)."""
        import jax.numpy as jnp

        self._data = jnp.zeros((0,) + tuple(self._shape[1:]),
                               self._data.dtype)
        self._aux["indices"] = jnp.zeros((0,), self._aux["indices"].dtype)

    def retain(self, indices) -> "RowSparseNDArray":
        """Keep only the given rows (reference: sparse.retain)."""
        import jax.numpy as jnp

        keep = indices._data.astype(jnp.int64) if isinstance(indices, NDArray) \
            else jnp.asarray(indices, jnp.int64)
        mine = self._aux["indices"]
        # membership of my rows in `keep`
        hit = (mine[:, None] == keep[None, :]).any(axis=1)
        # gather values for keep-rows present in mine (zero rows otherwise)
        pos = jnp.argmax(mine[:, None] == keep[None, :], axis=0)
        present = (mine[pos] == keep)
        vals = jnp.where(present[:, None],
                         self._data[pos], jnp.zeros_like(self._data[pos]))
        del hit
        return RowSparseNDArray(vals, {"indices": keep.astype(mine.dtype)},
                                self._shape, ctx=self._ctx)


class CSRNDArray(BaseSparseNDArray):
    """Compressed sparse row matrix (reference: CSRNDArray)."""

    @property
    def stype(self) -> str:
        return "csr"

    @property
    def indices(self) -> NDArray:
        return NDArray(self._aux["indices"], ctx=self._ctx)

    @property
    def indptr(self) -> NDArray:
        return NDArray(self._aux["indptr"], ctx=self._ctx)

    @classmethod
    def _from_components(cls, values, aux, shape, ctx):
        return cls(values, dict(aux), shape, ctx=ctx)

    def todense(self) -> NDArray:
        import jax.numpy as jnp

        m, n = self._shape
        indptr = self._aux["indptr"]
        indices = self._aux["indices"]
        nnz = self._data.shape[0]
        # row id per nonzero: searchsorted over indptr
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        out = jnp.zeros((m, n), self._data.dtype)
        out = out.at[rows, indices].set(self._data)
        return NDArray(out, ctx=self._ctx)

    def dot(self, dense: NDArray, transpose_a: bool = False) -> NDArray:
        """csr @ dense via gather + segment-sum (reference: dot(csr, dense)
        FComputeEx; TPU mapping: segment_sum vectorizes on the VPU)."""
        import jax
        import jax.numpy as jnp

        m, n = self._shape
        indptr = self._aux["indptr"]
        indices = self._aux["indices"]
        nnz = self._data.shape[0]
        rows = jnp.searchsorted(indptr, jnp.arange(nnz), side="right") - 1
        gathered = dense._data[indices] * self._data[:, None]
        if transpose_a:
            # csr.T @ dense: scatter-add contributions into column slots
            out = jax.ops.segment_sum(
                dense._data[rows] * self._data[:, None], indices,
                num_segments=n)
            return NDArray(out, ctx=self._ctx)
        out = jax.ops.segment_sum(gathered, rows, num_segments=m)
        return NDArray(out, ctx=self._ctx)


# ---------------------------------------------------------------------------
# constructors (reference: sparse.row_sparse_array / csr_matrix)
# ---------------------------------------------------------------------------
def row_sparse_array(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, RowSparseNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 2:
        data, indices = arg1
        import jax.numpy as jnp

        values = _component(data, dtype)
        idx = _component(indices, "int64")
        if shape is None:
            raise MXNetError("row_sparse_array requires shape with "
                             "(data, indices)")
        return RowSparseNDArray(values, {"indices": idx}, tuple(shape),
                                ctx=ctx)
    # dense input -> compress
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(arg1, ctx=ctx)
    arr = np.asarray(dense.asnumpy())
    nz_rows = np.where(np.any(arr != 0, axis=tuple(range(1, arr.ndim))))[0]
    return RowSparseNDArray(
        _component(arr[nz_rows], dtype), {"indices": _component(nz_rows, "int64")},
        arr.shape, ctx=ctx)


def csr_matrix(arg1, shape=None, ctx=None, dtype=None):
    ctx = ctx or current_context()
    if isinstance(arg1, CSRNDArray):
        return arg1
    if isinstance(arg1, tuple) and len(arg1) == 3:
        data, indices, indptr = arg1
        if shape is None:
            raise MXNetError("csr_matrix requires shape with "
                             "(data, indices, indptr)")
        return CSRNDArray(
            _component(data, dtype),
            {"indices": _component(indices, "int64"),
             "indptr": _component(indptr, "int64")}, tuple(shape), ctx=ctx)
    dense = arg1 if isinstance(arg1, NDArray) else _dense_array(arg1, ctx=ctx)
    arr = np.asarray(dense.asnumpy())
    if arr.ndim != 2:
        raise MXNetError("csr_matrix requires a 2-D input")
    rows, cols = np.nonzero(arr)
    indptr = np.zeros(arr.shape[0] + 1, np.int64)
    np.add.at(indptr[1:], rows, 1)
    indptr = np.cumsum(indptr)
    return CSRNDArray(
        _component(arr[rows, cols], dtype),
        {"indices": _component(cols, "int64"),
         "indptr": _component(indptr, "int64")}, arr.shape, ctx=ctx)


def zeros(stype, shape, ctx=None, dtype=None):
    ctx = ctx or current_context()
    dtype = np.dtype(dtype_np(dtype)).name
    import jax.numpy as jnp

    if stype == "row_sparse":
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(shape[1:]), dtype),
            {"indices": jnp.zeros((0,), jnp.int64)}, tuple(shape), ctx=ctx)
    if stype == "csr":
        return CSRNDArray(
            jnp.zeros((0,), dtype),
            {"indices": jnp.zeros((0,), jnp.int64),
             "indptr": jnp.zeros((shape[0] + 1,), jnp.int64)},
            tuple(shape), ctx=ctx)
    if stype == "default":
        from . import zeros as dense_zeros

        return dense_zeros(shape, ctx=ctx, dtype=dtype)
    raise MXNetError(f"unknown storage type {stype!r}")


def empty(stype, shape, ctx=None, dtype=None):
    return zeros(stype, shape, ctx=ctx, dtype=dtype)


def array(source_array, ctx=None, dtype=None):
    """Sparse-preserving array(): scipy.sparse and sparse NDArrays keep
    their storage type (reference: sparse.array)."""
    if isinstance(source_array, BaseSparseNDArray):
        return source_array
    try:
        import scipy.sparse as sp

        if sp.issparse(source_array):
            csr = source_array.tocsr()
            return csr_matrix((csr.data, csr.indices, csr.indptr),
                              shape=csr.shape, ctx=ctx, dtype=dtype)
    except ImportError:
        pass
    raise MXNetError("sparse.array expects a scipy.sparse matrix or sparse "
                     "NDArray; use nd.array for dense inputs")


def aggregate_rows(indices, values):
    """Aggregate possibly-duplicated (indices, values) row pairs into
    sorted-unique indices with segment-summed values.

    EAGER-only (host-side unique gives the true dynamic row count — no
    zero padding, so no spurious \"row 0 touched\" artifacts downstream).
    Shared by autograd's sparse-cotangent leaf write and the row_sparse
    optimizer kernels' pre-aggregation.
    """
    import jax
    import jax.numpy as jnp

    ids_np = np.asarray(indices)
    uids, inv = np.unique(ids_np, return_inverse=True)
    vals = jax.ops.segment_sum(values, jnp.asarray(inv.reshape(-1)),
                               num_segments=len(uids))
    return jnp.asarray(uids), vals


def _component(x, dtype):
    import jax.numpy as jnp

    if isinstance(x, NDArray):
        arr = x._data
    else:
        arr = jnp.asarray(np.asarray(x))
    if dtype is not None:
        arr = arr.astype(dtype_np(dtype) if dtype != "int64" else np.int64)
    return arr


# ---------------------------------------------------------------------------
# functional namespace (reference: python/mxnet/ndarray/sparse.py module
# functions — mx.nd.sparse.dot/retain/elemwise_* etc.)
# ---------------------------------------------------------------------------
def retain(data, indices):
    """Keep only the given rows of a row_sparse array
    (reference sparse.retain)."""
    if not isinstance(data, RowSparseNDArray):
        raise MXNetError("retain expects a RowSparseNDArray")
    return data.retain(indices)


def dot(lhs, rhs, transpose_a=False, transpose_b=False):
    """Sparse-aware dot: csr x dense uses the compressed rows directly;
    every other combination contracts densely (reference sparse dot.cc)."""
    if (isinstance(lhs, CSRNDArray) and not transpose_b
            and not isinstance(rhs, BaseSparseNDArray)):
        return lhs.dot(rhs, transpose_a=transpose_a)
    from .. import ndarray as nd

    a = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return nd.dot(a, b, transpose_a=transpose_a, transpose_b=transpose_b)


def _rs_binary(lhs, rhs, dense_op):
    """row_sparse (+|-) row_sparse stays sparse via index union; any other
    combination falls back to the dense op (reference FComputeEx fallback
    semantics)."""
    import jax.numpy as jnp

    if (isinstance(lhs, RowSparseNDArray) and isinstance(rhs, RowSparseNDArray)
            and lhs.shape == rhs.shape and dense_op in ("add", "sub")):
        # negate in the native dtype: a python-float multiply would promote
        # int row values to f32 and lose precision above 2^24.  Bool has no
        # unary negative — do its arithmetic in int8 and cast back.
        dt = lhs._data.dtype
        work = jnp.int8 if dt == jnp.bool_ else dt
        lvals = lhs._data.astype(work)
        rvals = rhs._data.astype(work)
        if dense_op == "sub":
            rvals = -rvals
        idx = jnp.concatenate([lhs._aux["indices"], rhs._aux["indices"]])
        vals = jnp.concatenate([lvals, rvals])
        uids, summed = aggregate_rows(idx, vals)
        return RowSparseNDArray(summed.astype(lhs._data.dtype),
                                {"indices": uids}, lhs.shape, ctx=lhs._ctx)
    from .. import ndarray as nd

    a = lhs.todense() if isinstance(lhs, BaseSparseNDArray) else lhs
    b = rhs.todense() if isinstance(rhs, BaseSparseNDArray) else rhs
    return getattr(nd, f"elemwise_{dense_op}")(a, b)


def elemwise_add(lhs, rhs):
    return _rs_binary(lhs, rhs, "add")


def elemwise_sub(lhs, rhs):
    return _rs_binary(lhs, rhs, "sub")


def elemwise_mul(lhs, rhs):
    return _rs_binary(lhs, rhs, "mul")


def elemwise_div(lhs, rhs):
    return _rs_binary(lhs, rhs, "div")


add = elemwise_add
subtract = elemwise_sub
multiply = elemwise_mul
divide = elemwise_div


def zeros_like(data):
    import jax.numpy as jnp

    if isinstance(data, RowSparseNDArray):
        return RowSparseNDArray(
            jnp.zeros((0,) + tuple(data.shape[1:]), data._data.dtype),
            {"indices": jnp.zeros((0,), data._aux["indices"].dtype)},
            data.shape, ctx=data._ctx)
    if isinstance(data, CSRNDArray):
        # empty-component csr: stype is preserved, nothing densifies
        return CSRNDArray(
            jnp.zeros((0,), data._data.dtype),
            {"indices": jnp.zeros((0,), data._aux["indices"].dtype),
             "indptr": jnp.zeros((data.shape[0] + 1,),
                                 data._aux["indptr"].dtype)},
            data.shape, ctx=data._ctx)
    from .. import ndarray as nd

    return nd.zeros_like(data)
