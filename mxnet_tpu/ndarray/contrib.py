"""The ``mx.nd.contrib`` namespace.

Reference parity: python/mxnet/ndarray/contrib.py — short spellings of the
``_contrib_*`` registered ops plus the control-flow operators
(foreach/while_loop/cond, reference: src/operator/control_flow.cc ~L1-1500).

TPU-native design: control flow lowers to lax.scan / lax.while_loop /
lax.cond through the shared dispatch layer, so the loop body compiles into
the SAME XLA program as the surrounding graph — the reference executes
sub-CachedOps per iteration instead; scan is strictly better on TPU
(no per-iteration dispatch, full fusion across the loop boundary).
"""
from __future__ import annotations

from typing import Any, List, Sequence

from ..base import MXNetError
from ..ops import registry as _reg
from .ndarray import NDArray

__all__ = ["foreach", "while_loop", "cond"]


def _as_list(x):
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _eager_recording(arrays) -> bool:
    """True when autograd is recording outside any jit trace — the case
    where control flow must unroll in Python for tape visibility."""
    import jax

    from .. import autograd

    if not autograd.is_recording():
        return False
    return not any(isinstance(a._data, jax.core.Tracer) for a in arrays)


def foreach(body, data, init_states):
    """Iterate body over axis 0 of data, threading states (reference:
    control_flow.cc Foreach).  body(data_i, states) -> (outputs, states).

    While autograd is recording eagerly, the loop runs in Python so the tape
    sees every op (gradients flow to closure-captured parameters, like the
    reference's imperative path); otherwise it lowers to one lax.scan.
    """
    data_list = _as_list(data)
    data_multi = isinstance(data, (list, tuple))
    states = _as_list(init_states)
    states_multi = isinstance(init_states, (list, tuple))
    ctx = data_list[0].context
    n_data, n_state = len(data_list), len(states)
    out_multi = [None]  # filled at trace time

    if _eager_recording(data_list + states):
        from . import stack as nd_stack

        cur = init_states
        collected = None
        for i in range(data_list[0].shape[0]):
            xs = [d[i] for d in data_list]
            outs, cur = body(xs if data_multi else xs[0], cur)
            outs_l = _as_list(outs)
            if collected is None:
                collected = [[] for _ in outs_l]
                out_multi[0] = isinstance(outs, (list, tuple))
            for lst, o in zip(collected, outs_l):
                lst.append(o)
        stacked = [nd_stack(*lst, axis=0) for lst in collected]
        return (stacked if out_multi[0] else stacked[0]), cur

    def fn(*arrays):
        import jax

        xs = arrays[:n_data]
        carry0 = arrays[n_data:]

        def step(carry, x):
            d_nds = [NDArray(v, ctx=ctx) for v in x]
            s_nds = [NDArray(c, ctx=ctx) for c in carry]
            outs, new_s = body(d_nds if data_multi else d_nds[0],
                               s_nds if states_multi else s_nds[0])
            outs_l = _as_list(outs)
            out_multi[0] = isinstance(outs, (list, tuple))
            new_l = _as_list(new_s)
            if len(new_l) != n_state:
                raise MXNetError("foreach body must return the same number "
                                 "of states as init_states")
            return (tuple(s._data for s in new_l),
                    tuple(o._data for o in outs_l))

        final, stacked = jax.lax.scan(step, tuple(carry0), tuple(xs))
        return tuple(stacked) + tuple(final)

    results = _reg.invoke_fn(fn, data_list + states)
    results = _as_list(results)
    n_out = len(results) - n_state
    outputs = results[:n_out]
    out_states = results[n_out:]
    outputs = outputs if out_multi[0] else outputs[0]
    return outputs, (out_states if states_multi else out_states[0])


def while_loop(cond, func, loop_vars, max_iterations=None):
    """Bounded while loop (reference: control_flow.cc WhileLoop).

    func(*loop_vars) -> (step_output(s), new_loop_vars); returns
    (outputs stacked over max_iterations with zero padding, final vars).
    Static upper bound keeps XLA shapes fixed (the reference pads too).
    """
    if max_iterations is None:
        raise MXNetError("while_loop requires max_iterations")
    loop_list = _as_list(loop_vars)
    vars_multi = isinstance(loop_vars, (list, tuple))
    ctx = loop_list[0].context
    n_vars = len(loop_list)
    out_multi = [None]

    if _eager_recording(loop_list):
        from . import stack as nd_stack
        from . import zeros as nd_zeros

        cur = list(loop_list)
        collected = None
        steps = 0
        for _ in range(int(max_iterations)):
            p = cond(*cur)
            if not bool(p.asnumpy().reshape(())):
                break
            outs, new_vars = func(*cur)
            outs_l = _as_list(outs)
            if collected is None:
                collected = [[] for _ in outs_l]
                out_multi[0] = isinstance(outs, (list, tuple))
            for lst, o in zip(collected, outs_l):
                lst.append(o)
            cur = _as_list(new_vars)
            steps += 1
        if collected is None:
            raise MXNetError("while_loop body never ran; cannot infer "
                             "output shapes")
        stacked = []
        for lst in collected:
            pad = [nd_zeros(lst[0].shape, ctx=ctx, dtype=lst[0].dtype)
                   for _ in range(int(max_iterations) - steps)]
            stacked.append(nd_stack(*(lst + pad), axis=0))
        outputs = stacked if out_multi[0] else stacked[0]
        return outputs, (cur if vars_multi else cur[0])

    def fn(*arrays):
        import jax
        import jax.numpy as jnp

        def step(carry, _):
            done, count, vs = carry
            v_nds = [NDArray(v, ctx=ctx) for v in vs]
            pred = cond(*v_nds)
            pred_v = (pred._data if isinstance(pred, NDArray)
                      else jnp.asarray(pred)).reshape(()).astype(bool)
            active = (~done) & pred_v
            outs, new_vs = func(*v_nds)
            outs_l = _as_list(outs)
            out_multi[0] = isinstance(outs, (list, tuple))
            new_l = [v._data for v in _as_list(new_vs)]
            kept = tuple(jnp.where(active, nv, ov)
                         for nv, ov in zip(new_l, vs))
            step_out = tuple(
                jnp.where(active, o._data, jnp.zeros_like(o._data))
                for o in outs_l)
            return ((done | ~pred_v, count + active.astype(jnp.int32), kept),
                    step_out)

        carry0 = (jnp.asarray(False), jnp.asarray(0, jnp.int32),
                  tuple(arrays))
        (done, count, final), stacked = jax.lax.scan(
            step, carry0, None, length=int(max_iterations))
        return tuple(stacked) + tuple(final)

    results = _as_list(_reg.invoke_fn(fn, loop_list))
    n_out = len(results) - n_vars
    outputs = results[:n_out]
    final_vars = results[n_out:]
    outputs = outputs if out_multi[0] else outputs[0]
    return outputs, (final_vars if vars_multi else final_vars[0])


def cond(pred, then_func, else_func, inputs=None):
    """Conditional (reference: control_flow.cc Cond) -> lax.cond.
    pred: scalar NDArray or callable(*inputs); branches take `inputs`
    (or are nullary); both must return the same structure."""
    in_list = _as_list(inputs) if inputs is not None else []
    pred_is_nd = isinstance(pred, NDArray)
    op_inputs = in_list + ([pred] if pred_is_nd else [])
    if not op_inputs:
        raise MXNetError("cond needs `inputs` and/or an NDArray pred")
    ctx = op_inputs[0].context

    if _eager_recording(op_inputs):
        p = pred if pred_is_nd else pred(*in_list)
        branch = (then_func if bool(p.asnumpy().reshape(()))
                  else else_func)
        return branch(*in_list) if in_list else branch()

    def fn(*arrays):
        import jax
        import jax.numpy as jnp

        nds = [NDArray(a, ctx=ctx) for a in arrays[:len(in_list)]]
        if pred_is_nd:
            p_v = arrays[len(in_list)]
        else:
            p = pred(*nds)
            p_v = p._data if isinstance(p, NDArray) else jnp.asarray(p)
        p_v = jnp.reshape(p_v, ()).astype(bool)

        def run(branch):
            out = branch(*nds) if in_list else branch()
            out_multi[0] = isinstance(out, (list, tuple))
            outs = _as_list(out)
            return tuple(o._data if isinstance(o, NDArray) else o
                         for o in outs)

        return jax.lax.cond(p_v, lambda _: run(then_func),
                            lambda _: run(else_func), operand=None)

    out_multi = [None]
    results = _as_list(_reg.invoke_fn(fn, op_inputs))
    return results if out_multi[0] else results[0]


def _populate():
    g = globals()
    for name in _reg.list_ops():
        if name.startswith("_contrib_"):
            short = name[len("_contrib_"):]
            op = _reg.get_op(name)

            def make(op):
                def stub(*args, **kwargs):
                    out = kwargs.pop("out", None)
                    kwargs.pop("name", None)
                    from .ndarray import array

                    inputs = [a if isinstance(a, NDArray) else array(a)
                              for a in args]
                    return _reg.invoke(op, inputs, out=out, **kwargs)

                stub.__name__ = op.name
                stub.__doc__ = op.__doc__
                return stub

            g[short] = make(op)
            __all__.append(short)


_populate()
