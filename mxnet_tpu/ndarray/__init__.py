"""The ``mx.nd`` namespace.

Reference parity: python/mxnet/ndarray/ — the op namespace is *generated
from the registry at import time*, matching the reference's autogen from
MXSymbolListAtomicSymbolCreators (ndarray/register.py ~L100): every
registered operator becomes a module-level function here.
"""
from __future__ import annotations

import inspect
from typing import Optional

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..ops import registry as _reg
from .. import engine as _engine
from .ndarray import NDArray, array, from_jax
from . import random  # noqa: F401  (nd.random namespace)
from .utils import save, load, save_legacy
from . import contrib  # noqa: F401  (nd.contrib namespace)
from . import sparse  # noqa: F401  (nd.sparse namespace)
from .sparse import RowSparseNDArray, CSRNDArray
from ..operator import Custom  # noqa: F401  (mx.nd.Custom)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "eye", "linspace", "save", "load", "waitall", "concat", "stack",
           "from_jax"]


def waitall():
    _engine.wait_all()


# ---------------------------------------------------------------------------
# creation helpers with ctx/dtype signature parity
# ---------------------------------------------------------------------------
def zeros(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_zeros", [], ctx=ctx, shape=_tup(shape),
                               dtype=np.dtype(dtype_np(dtype)).name)


def ones(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_ones", [], ctx=ctx, shape=_tup(shape),
                               dtype=np.dtype(dtype_np(dtype)).name)


def full(shape, val, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_full", [], ctx=ctx, shape=_tup(shape),
                               value=float(val), dtype=np.dtype(dtype_np(dtype)).name)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx: Optional[Context] = None,
           dtype=None) -> NDArray:
    return _reg.invoke_by_name("_arange", [], ctx=ctx, start=start, stop=stop,
                               step=step, repeat=repeat,
                               dtype=np.dtype(dtype_np(dtype)).name)


def eye(N, M=0, k=0, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return _reg.invoke_by_name("_eye", [], ctx=ctx, N=N, M=M, k=k,
                               dtype=np.dtype(dtype_np(dtype)).name)


def linspace(start, stop, num, endpoint=True, ctx: Optional[Context] = None,
             dtype=None) -> NDArray:
    return _reg.invoke_by_name("_linspace", [], ctx=ctx, start=start, stop=stop,
                               num=num, endpoint=endpoint,
                               dtype=np.dtype(dtype_np(dtype)).name)


def _tup(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


# ---------------------------------------------------------------------------
# special stubs (train-mode / RNG injection)
# ---------------------------------------------------------------------------
def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, out=None,
            **kwargs):
    from .. import autograd
    from .. import random as _rng

    training = kwargs.pop("training", None)
    if training is None:
        training = autograd.is_training() or mode == "always"
    if not training or p <= 0.0:
        return _reg.invoke_by_name("identity", [data], out=out)
    key = NDArray(_rng.next_key(), ctx=data.context)
    return _reg.invoke_by_name("Dropout", [data, key], out=out, p=p, mode=mode,
                               axes=tuple(axes), training=True)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False, out=None,
              **kwargs):
    from .. import autograd

    training = kwargs.pop("training", None)
    if training is None:
        training = autograd.is_training()
    return _reg.invoke_by_name(
        "BatchNorm", [data, gamma, beta, moving_mean, moving_var], out=out,
        eps=eps, momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, output_mean_var=output_mean_var,
        axis=axis, training=training)


def shuffle(data, out=None):
    from .. import random as _rng

    key = NDArray(_rng.next_key(), ctx=data.context)
    return _reg.invoke_by_name("_shuffle", [key, data], out=out)


def onehot_encode(indices, out):
    """Legacy one-hot (reference src/ndarray/ndarray_function.cc
    OnehotEncode): writes the encoding INTO the second argument in place
    and returns it — legacy callers read `out` after a positional call
    (r3 advisor finding), so out= is mandatory here."""
    return _reg.invoke_by_name("onehot_encode", [indices, out], out=out)


def cast_storage(data, stype="default", out=None):
    """Convert between dense and sparse storage (reference:
    src/operator/tensor/cast_storage.cc).  Thin op-name facade over
    NDArray.tostype — the single conversion implementation."""
    res = data.tostype(stype)
    if res is data:  # tostype may return self; the op semantics copy
        res = data.copyto(data.context)
    if out is not None:
        if out.stype != stype:
            raise MXNetError(
                f"cast_storage: out has stype {out.stype!r}, "
                f"expected {stype!r}")
        out._set_data(res._data)
        if stype != "default":
            out._aux = dict(res._aux)
            out._shape = res._shape
        return out
    return res


_SPECIAL = {"Dropout": Dropout, "BatchNorm": BatchNorm, "_shuffle": shuffle,
            "onehot_encode": onehot_encode}
_SKIP_PREFIXES = ("_random_", "_sample_", "sample_")


# ---------------------------------------------------------------------------
# namespace autogen from the op registry
# ---------------------------------------------------------------------------
def _make_stub(op):
    sig = inspect.signature(op.fn)
    params = list(sig.parameters.values())
    # NB: builtins like sum/abs/max are shadowed by op stubs in this module's
    # globals, so avoid them in code that runs after _populate starts.
    n_arr = 0
    for p in params:
        if p.default is p.empty and p.kind == p.POSITIONAL_OR_KEYWORD:
            n_arr += 1
    kw_names = [p.name for p in params if p.default is not p.empty]

    def stub(*args, **kwargs):
        out = kwargs.pop("out", None)
        ctx = kwargs.pop("ctx", None)
        kwargs.pop("name", None)  # symbol-compat no-op
        inputs = []
        extra_kw = 0
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif len(inputs) < n_arr:
                # positional slot that must be an array input
                inputs.append(array(a, ctx=ctx))
            else:
                # positional attr: assign to next keyword param not given
                while extra_kw < len(kw_names) and kw_names[extra_kw] in kwargs:
                    extra_kw += 1
                if extra_kw >= len(kw_names):
                    raise MXNetError(
                        f"too many positional arguments for op {op.name}")
                kwargs[kw_names[extra_kw]] = a
                extra_kw += 1
        return _reg.invoke(op, inputs, out=out, ctx=ctx, **kwargs)

    stub.__name__ = op.name
    stub.__doc__ = op.__doc__
    return stub


def _populate():
    g = globals()
    for name in _reg.list_ops():
        if name in _SPECIAL:
            g[name] = _SPECIAL[name]
            continue
        if name.startswith(_SKIP_PREFIXES):
            continue
        op = _reg.get_op(name)
        g[name] = _make_stub(op)
        __all__.append(name)
    # common aliases
    g["concatenate"] = g["Concat"]
    g["concat"] = g["Concat"]
    g["flatten"] = g["Flatten"]
    g["cast"] = g["Cast"]
    def moveaxis(a, source, destination):
        import jax.numpy as jnp

        return _reg.invoke_fn(lambda x: jnp.moveaxis(x, source, destination), [a])

    g["moveaxis"] = moveaxis


_populate()
