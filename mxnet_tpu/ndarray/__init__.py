"""The ``mx.nd`` namespace.

Reference parity: python/mxnet/ndarray/ — the op namespace is *generated
from the registry at import time*, matching the reference's autogen from
MXSymbolListAtomicSymbolCreators (ndarray/register.py ~L100): every
registered operator becomes a module-level function here.
"""
from __future__ import annotations

import inspect
from typing import Optional

import numpy as np

from ..base import MXNetError, dtype_np
from ..context import Context, current_context
from ..ops import registry as _reg
from .. import engine as _engine
from .ndarray import NDArray, array, from_jax
from . import random  # noqa: F401  (nd.random namespace)
from .utils import save, load, save_legacy
from . import contrib  # noqa: F401  (nd.contrib namespace)
from . import linalg  # noqa: F401  (nd.linalg namespace)
from . import sparse  # noqa: F401  (nd.sparse namespace)
from .sparse import RowSparseNDArray, CSRNDArray
from ..operator import Custom  # noqa: F401  (mx.nd.Custom)

__all__ = ["NDArray", "array", "zeros", "ones", "full", "empty", "arange",
           "eye", "linspace", "save", "load", "waitall", "concat", "stack",
           "from_jax"]


def waitall():
    _engine.wait_all()


# ---------------------------------------------------------------------------
# creation helpers with ctx/dtype signature parity
# ---------------------------------------------------------------------------
def zeros(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_zeros", [], ctx=ctx, shape=_tup(shape),
                               dtype=np.dtype(dtype_np(dtype)).name)


def ones(shape, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_ones", [], ctx=ctx, shape=_tup(shape),
                               dtype=np.dtype(dtype_np(dtype)).name)


def full(shape, val, ctx: Optional[Context] = None, dtype=None, **kwargs) -> NDArray:
    return _reg.invoke_by_name("_full", [], ctx=ctx, shape=_tup(shape),
                               value=float(val), dtype=np.dtype(dtype_np(dtype)).name)


def empty(shape, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return zeros(shape, ctx=ctx, dtype=dtype)


def arange(start, stop=None, step=1.0, repeat=1, ctx: Optional[Context] = None,
           dtype=None) -> NDArray:
    return _reg.invoke_by_name("_arange", [], ctx=ctx, start=start, stop=stop,
                               step=step, repeat=repeat,
                               dtype=np.dtype(dtype_np(dtype)).name)


def eye(N, M=0, k=0, ctx: Optional[Context] = None, dtype=None) -> NDArray:
    return _reg.invoke_by_name("_eye", [], ctx=ctx, N=N, M=M, k=k,
                               dtype=np.dtype(dtype_np(dtype)).name)


def linspace(start, stop, num, endpoint=True, ctx: Optional[Context] = None,
             dtype=None) -> NDArray:
    return _reg.invoke_by_name("_linspace", [], ctx=ctx, start=start, stop=stop,
                               num=num, endpoint=endpoint,
                               dtype=np.dtype(dtype_np(dtype)).name)


def _tup(shape):
    return (shape,) if isinstance(shape, int) else tuple(shape)


# ---------------------------------------------------------------------------
# special stubs (train-mode / RNG injection)
# ---------------------------------------------------------------------------
def Dropout(data, p=0.5, mode="training", axes=(), cudnn_off=False, out=None,
            **kwargs):
    from .. import autograd
    from .. import random as _rng

    training = kwargs.pop("training", None)
    if training is None:
        training = autograd.is_training() or mode == "always"
    if not training or p <= 0.0:
        return _reg.invoke_by_name("identity", [data], out=out)
    key = NDArray(_rng.next_key(), ctx=data.context)
    return _reg.invoke_by_name("Dropout", [data, key], out=out, p=p, mode=mode,
                               axes=tuple(axes), training=True)


def BatchNorm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
              momentum=0.9, fix_gamma=True, use_global_stats=False,
              output_mean_var=False, axis=1, cudnn_off=False, out=None,
              **kwargs):
    from .. import autograd

    training = kwargs.pop("training", None)
    if training is None:
        training = autograd.is_training()
    return _reg.invoke_by_name(
        "BatchNorm", [data, gamma, beta, moving_mean, moving_var], out=out,
        eps=eps, momentum=momentum, fix_gamma=fix_gamma,
        use_global_stats=use_global_stats, output_mean_var=output_mean_var,
        axis=axis, training=training)


def shuffle(data, out=None):
    from .. import random as _rng

    key = NDArray(_rng.next_key(), ctx=data.context)
    return _reg.invoke_by_name("_shuffle", [key, data], out=out)


def onehot_encode(indices, out):
    """Legacy one-hot (reference src/ndarray/ndarray_function.cc
    OnehotEncode): writes the encoding INTO the second argument in place
    and returns it — legacy callers read `out` after a positional call
    (r3 advisor finding), so out= is mandatory here."""
    return _reg.invoke_by_name("onehot_encode", [indices, out], out=out)


def cast_storage(data, stype="default", out=None):
    """Convert between dense and sparse storage (reference:
    src/operator/tensor/cast_storage.cc).  Thin op-name facade over
    NDArray.tostype — the single conversion implementation."""
    res = data.tostype(stype)
    if res is data:  # tostype may return self; the op semantics copy
        res = data.copyto(data.context)
    if out is not None:
        if out.stype != stype:
            raise MXNetError(
                f"cast_storage: out has stype {out.stype!r}, "
                f"expected {stype!r}")
        out._set_data(res._data)
        if stype != "default":
            out._aux = dict(res._aux)
            out._shape = res._shape
        return out
    return res


_SPECIAL = {"Dropout": Dropout, "BatchNorm": BatchNorm, "_shuffle": shuffle,
            "onehot_encode": onehot_encode}
_SKIP_PREFIXES = ("_random_", "_sample_", "sample_")


# ---------------------------------------------------------------------------
# namespace autogen from the op registry
# ---------------------------------------------------------------------------
def _make_stub(op):
    sig = inspect.signature(op.fn)
    params = list(sig.parameters.values())
    # NB: builtins like sum/abs/max are shadowed by op stubs in this module's
    # globals, so avoid them in code that runs after _populate starts.
    n_arr = 0
    for p in params:
        if p.default is p.empty and p.kind == p.POSITIONAL_OR_KEYWORD:
            n_arr += 1
    kw_names = [p.name for p in params if p.default is not p.empty]

    def stub(*args, **kwargs):
        out = kwargs.pop("out", None)
        ctx = kwargs.pop("ctx", None)
        kwargs.pop("name", None)  # symbol-compat no-op
        inputs = []
        extra_kw = 0
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif len(inputs) < n_arr:
                # positional slot that must be an array input
                inputs.append(array(a, ctx=ctx))
            else:
                # positional attr: assign to next keyword param not given
                while extra_kw < len(kw_names) and kw_names[extra_kw] in kwargs:
                    extra_kw += 1
                if extra_kw >= len(kw_names):
                    raise MXNetError(
                        f"too many positional arguments for op {op.name}")
                kwargs[kw_names[extra_kw]] = a
                extra_kw += 1
        return _reg.invoke(op, inputs, out=out, ctx=ctx, **kwargs)

    stub.__name__ = op.name
    stub.__doc__ = op.__doc__
    return stub


# ---------------------------------------------------------------------------
# public ufunc wrappers (reference: ndarray.py _ufunc_helper — nd.add /
# nd.power / nd.equal ... dispatch on array-vs-scalar operands).  Scalar
# operands become STATIC attrs of the *_scalar ops (the reference's
# fn_scalar path), never array inputs: that keeps float-vs-int-array
# comparisons exact (1.5 is not truncated to the array dtype) and keeps
# power's exponent out of the gradient (see NDArray.__pow__).
# name -> (broadcast_op, np_fn, scalar_op, reversed_scalar_op)
# ---------------------------------------------------------------------------
_UFUNCS = {
    "add": ("broadcast_add", np.add, "_plus_scalar", "_plus_scalar"),
    "subtract": ("broadcast_sub", np.subtract, "_minus_scalar",
                 "_rminus_scalar"),
    "multiply": ("broadcast_mul", np.multiply, "_mul_scalar", "_mul_scalar"),
    "divide": ("broadcast_div", np.divide, "_div_scalar", "_rdiv_scalar"),
    "true_divide": ("broadcast_div", np.divide, "_div_scalar",
                    "_rdiv_scalar"),
    "mod": ("broadcast_mod", np.mod, "_mod_scalar", "_rmod_scalar"),
    "equal": ("broadcast_equal", np.equal, "_equal_scalar", "_equal_scalar"),
    "not_equal": ("broadcast_not_equal", np.not_equal, "_not_equal_scalar",
                  "_not_equal_scalar"),
    "greater": ("broadcast_greater", np.greater, "_greater_scalar",
                "_lesser_scalar"),
    "greater_equal": ("broadcast_greater_equal", np.greater_equal,
                      "_greater_equal_scalar", "_lesser_equal_scalar"),
    "lesser": ("broadcast_lesser", np.less, "_lesser_scalar",
               "_greater_scalar"),
    "lesser_equal": ("broadcast_lesser_equal", np.less_equal,
                     "_lesser_equal_scalar", "_greater_equal_scalar"),
    "logical_and": ("broadcast_logical_and", np.logical_and,
                    "_logical_and_scalar", "_logical_and_scalar"),
    "logical_or": ("broadcast_logical_or", np.logical_or,
                   "_logical_or_scalar", "_logical_or_scalar"),
    "logical_xor": ("broadcast_logical_xor", np.logical_xor,
                    "_logical_xor_scalar", "_logical_xor_scalar"),
}


def _make_ufunc(name, broadcast_op, np_fn, scalar_op, rscalar_op):
    def f(lhs, rhs):
        lnd, rnd = isinstance(lhs, NDArray), isinstance(rhs, NDArray)
        if lnd and rnd:
            return _reg.invoke_by_name(broadcast_op, [lhs, rhs])
        if lnd:
            return _reg.invoke_by_name(scalar_op, [lhs], scalar=float(rhs))
        if rnd:
            return _reg.invoke_by_name(rscalar_op, [rhs], scalar=float(lhs))
        # both python scalars: plain number out (reference behavior)
        return np_fn(lhs, rhs)

    f.__name__ = name
    f.__doc__ = (f"Element-wise {name} with scalar/array dispatch "
                 f"(maps to {broadcast_op} / {scalar_op}).")
    return f


def power(lhs, rhs):
    """Element-wise power; scalar exponents stay static attrs so no
    d/d(exponent) gradient path appears (see NDArray.__pow__)."""
    if isinstance(lhs, NDArray):
        return lhs.__pow__(rhs)
    if isinstance(rhs, NDArray):
        return rhs.__rpow__(lhs)
    return np.power(lhs, rhs)


def hypot(lhs, rhs):
    """Element-wise hypot with scalar/array dispatch."""
    import jax.numpy as jnp

    lnd, rnd = isinstance(lhs, NDArray), isinstance(rhs, NDArray)
    if lnd and rnd:
        return _reg.invoke_by_name("broadcast_hypot", [lhs, rhs])
    if lnd:
        return _reg.invoke_fn(lambda x: jnp.hypot(x, float(rhs)), [lhs])
    if rnd:
        return _reg.invoke_fn(lambda x: jnp.hypot(float(lhs), x), [rhs])
    return np.hypot(lhs, rhs)


def _populate():
    g = globals()
    for _name, (_bop, _np_fn, _sop, _rsop) in _UFUNCS.items():
        g[_name] = _make_ufunc(_name, _bop, _np_fn, _sop, _rsop)
        __all__.append(_name)
    __all__.extend(["power", "hypot"])
    for name in _reg.list_ops():
        if name in _SPECIAL:
            g[name] = _SPECIAL[name]
            continue
        if name.startswith(_SKIP_PREFIXES):
            continue
        op = _reg.get_op(name)
        g[name] = _make_stub(op)
        __all__.append(name)
    # nd.linalg.* short spellings alias the SAME stubs as the flat names
    for _opname in _reg.list_ops():
        if _opname.startswith("linalg_"):
            _short = _opname[len("linalg_"):]
            setattr(linalg, _short, g[_opname])
            linalg.__all__.append(_short)
    # common aliases
    g["concatenate"] = g["Concat"]
    g["concat"] = g["Concat"]
    g["flatten"] = g["Flatten"]
    g["cast"] = g["Cast"]
    def moveaxis(a, source, destination):
        import jax.numpy as jnp

        return _reg.invoke_fn(lambda x: jnp.moveaxis(x, source, destination), [a])

    g["moveaxis"] = moveaxis


_populate()
