"""``mx.nd.random`` namespace (reference: python/mxnet/ndarray/random.py).

Each function injects the next key from the stateful facade in
mxnet_tpu.random and dispatches to the pure keyed ops in ops/random_ops.py.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from ..base import dtype_np
from ..context import Context, current_context
from ..ops import registry as _reg

__all__ = ["uniform", "normal", "randn", "randint", "gamma", "exponential",
           "poisson", "negative_binomial", "generalized_negative_binomial",
           "multinomial", "shuffle"]


def _key_nd(ctx: Optional[Context]):
    from .. import random as _rng
    from .ndarray import NDArray

    ctx = ctx or current_context()
    return NDArray(_rng.next_key(), ctx=ctx), ctx


def _dtname(dtype, default="float32"):
    if dtype is None:
        return default
    return np.dtype(dtype_np(dtype)).name


def _shape(shape):
    if shape is None:
        return ()
    return (shape,) if isinstance(shape, int) else tuple(shape)


def uniform(low=0.0, high=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    from .ndarray import NDArray

    if isinstance(low, NDArray) or isinstance(high, NDArray):
        from . import array

        low = low if isinstance(low, NDArray) else array(low, ctx=high.context)
        high = high if isinstance(high, NDArray) else array(high, ctx=low.context)
        key, _ = _key_nd(ctx or low.context)
        return _reg.invoke_by_name("sample_uniform", [key, low, high], out=out,
                                   shape=_shape(shape), dtype=_dtname(dtype))
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_uniform", [key], out=out, low=low,
                               high=high, shape=_shape(shape),
                               dtype=_dtname(dtype))


def normal(loc=0.0, scale=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    from .ndarray import NDArray

    if isinstance(loc, NDArray) or isinstance(scale, NDArray):
        from . import array

        loc = loc if isinstance(loc, NDArray) else array(loc, ctx=scale.context)
        scale = scale if isinstance(scale, NDArray) else array(scale, ctx=loc.context)
        key, _ = _key_nd(ctx or loc.context)
        return _reg.invoke_by_name("sample_normal", [key, loc, scale], out=out,
                                   shape=_shape(shape), dtype=_dtname(dtype))
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_normal", [key], out=out, loc=loc,
                               scale=scale, shape=_shape(shape),
                               dtype=_dtname(dtype))


def randn(*shape, dtype=None, ctx=None, loc=0.0, scale=1.0, **kw):
    return normal(loc=loc, scale=scale, shape=shape or (1,), dtype=dtype, ctx=ctx)


def randint(low, high=None, shape=(), dtype=None, ctx=None, out=None, **kw):
    if high is None:
        low, high = 0, low
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_randint", [key], out=out, low=int(low),
                               high=int(high), shape=_shape(shape),
                               dtype=_dtname(dtype, "int32"))


def gamma(alpha=1.0, beta=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_gamma", [key], out=out, alpha=alpha,
                               beta=beta, shape=_shape(shape),
                               dtype=_dtname(dtype))


def exponential(lam=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_exponential", [key], out=out, lam=lam,
                               shape=_shape(shape), dtype=_dtname(dtype))


def poisson(lam=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_poisson", [key], out=out, lam=lam,
                               shape=_shape(shape), dtype=_dtname(dtype))


def negative_binomial(k=1, p=1.0, shape=(), dtype=None, ctx=None, out=None, **kw):
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_negative_binomial", [key], out=out, k=k,
                               p=p, shape=_shape(shape), dtype=_dtname(dtype))


def generalized_negative_binomial(mu=1.0, alpha=1.0, shape=(), dtype=None,
                                  ctx=None, out=None, **kw):
    key, _ = _key_nd(ctx)
    return _reg.invoke_by_name("_random_generalized_negative_binomial", [key],
                               out=out, mu=mu, alpha=alpha, shape=_shape(shape),
                               dtype=_dtname(dtype))


def multinomial(data, shape=(), get_prob=False, dtype="int32", **kw):
    key, _ = _key_nd(data.context)
    return _reg.invoke_by_name("_sample_multinomial", [key, data],
                               shape=_shape(shape) or (1,), get_prob=get_prob,
                               dtype=_dtname(dtype, "int32"))


def shuffle(data, **kw):
    key, _ = _key_nd(data.context)
    return _reg.invoke_by_name("_shuffle", [key, data])
