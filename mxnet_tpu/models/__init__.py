"""Flagship model families built on gluon (transformer/BERT here;
CNN zoo in gluon.model_zoo.vision)."""
from . import transformer
from . import bert
from . import ssd
from .bert import BERTModel, BERTForMLM, bert_base, bert_small
from .ssd import SSD, SSDTrainLoss, ssd_300
from .transformer import (TransformerEncoder, MultiHeadAttention,
                          Transformer, TransformerDecoder, transformer_base,
                          transformer_big, label_smoothed_ce)
