"""Flagship model families built on gluon (transformer/BERT here;
CNN zoo in gluon.model_zoo.vision)."""
from . import transformer
from . import bert
from . import ssd
from . import faster_rcnn
from . import bert_pp
from .bert import BERTModel, BERTForMLM, bert_base, bert_small
from .bert_pp import (BERTForMLMPipelined, StackedTransformerEncoder,
                      bert_pp_small, bert_pp_sharding_rules)
from .faster_rcnn import (FasterRCNN, FasterRCNNTrainLoss,
                          faster_rcnn_small)
from .ssd import SSD, SSDTrainLoss, ssd_300
from .transformer import (TransformerEncoder, MultiHeadAttention,
                          Transformer, TransformerDecoder, transformer_base,
                          transformer_big, label_smoothed_ce)
