"""Faster-RCNN two-stage detector (BASELINE config 5, second half).

Reference parity: example/rcnn/symbol/symbol_resnet.py ~L1-300 (RPN over a
conv body, Proposal, ROI pooling, cls+bbox heads) plus the contrib ops
proposal.cc / proposal_target.cc and the numpy AnchorLoader.

TPU-native shape: the ENTIRE training step — backbone, RPN, anchor
targets, proposal generation + NMS, proposal targets, ROIAlign, both
heads, all four losses — is static-shape and compiles to ONE XLA program
(the reference splits this across CUDA ops, host numpy target assignment,
and a special AnchorLoader data iter).  Random fg/bg subsampling is
replaced by deterministic balanced normalization (RPN) and IoU-ranked
selection (RCNN): see _contrib_RPNAnchorTarget / _contrib_ProposalTarget.
"""
from __future__ import annotations

import numpy as np

from ..gluon import HybridBlock, loss as gloss, nn

__all__ = ["FasterRCNN", "FasterRCNNTrainLoss", "faster_rcnn_small"]


def _conv_block(channels):
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels, 3, padding=1, use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"))
    return blk


def _down_sample(channels):
    blk = nn.HybridSequential()
    blk.add(_conv_block(channels), _conv_block(channels), nn.MaxPool2D(2))
    return blk


class FasterRCNN(HybridBlock):
    """Two-stage detector: conv body -> RPN -> proposals -> ROIAlign ->
    cls/bbox heads.

    forward(x) returns (feat, rpn_cls (B, 2A, H, W), rpn_bbox (B, 4A, H, W));
    `rcnn_head` runs stage two on a given roi set; `detect` is the
    end-to-end inference path.
    """

    def __init__(self, num_classes, base_channels=(16, 32, 64),
                 rpn_channels=128, scales=(2.0, 4.0), ratios=(0.5, 1.0, 2.0),
                 rpn_pre_nms=256, rpn_post_nms=64, rpn_min_size=4,
                 rois_per_image=32, fg_fraction=0.5, roi_size=(7, 7),
                 hidden=256, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.num_classes = num_classes
        self._scales = tuple(float(s) for s in scales)
        self._ratios = tuple(float(r) for r in ratios)
        self._num_anchors = len(self._scales) * len(self._ratios)
        self._stride = 2 ** len(base_channels)
        self._rpn_pre = rpn_pre_nms
        self._rpn_post = rpn_post_nms
        self._rpn_min = rpn_min_size
        self._rois_per_image = rois_per_image
        self._fg_fraction = fg_fraction
        self._roi_size = tuple(roi_size)
        a = self._num_anchors
        with self.name_scope():
            body = nn.HybridSequential()
            for c in base_channels:
                body.add(_down_sample(c))
            self.body = body
            self.rpn_conv = nn.Conv2D(rpn_channels, 3, padding=1,
                                      activation="relu", prefix="rpn_conv_")
            self.rpn_cls = nn.Conv2D(2 * a, 1, prefix="rpn_cls_")
            self.rpn_bbox = nn.Conv2D(4 * a, 1, prefix="rpn_bbox_")
            top = nn.HybridSequential(prefix="top_")
            top.add(nn.Dense(hidden, activation="relu", flatten=False),
                    nn.Dense(hidden, activation="relu", flatten=False))
            self.top = top
            self.cls_head = nn.Dense(num_classes + 1, flatten=False,
                                     prefix="cls_head_")
            self.bbox_head = nn.Dense(4 * (num_classes + 1), flatten=False,
                                      prefix="bbox_head_")

    def hybrid_forward(self, F, x):
        feat = self.body(x)
        r = self.rpn_conv(feat)
        return feat, self.rpn_cls(r), self.rpn_bbox(r)

    # ------------------------------------------------------------------
    def rpn_probs(self, F, rpn_cls):
        """(B, 2A, H, W) logits -> Proposal-format probs (first A channels
        bg, last A fg), via a pairwise sigmoid (== 2-way softmax)."""
        a = self._num_anchors
        bg = F.slice_axis(rpn_cls, axis=1, begin=0, end=a)
        fg = F.slice_axis(rpn_cls, axis=1, begin=a, end=2 * a)
        p = F.sigmoid(fg - bg)
        return F.concat(1.0 - p, p, dim=1)

    def proposals(self, F, rpn_cls, rpn_bbox, im_info):
        """Decoded + NMS'd rois (B*post, 5); gradients are blocked, as in
        the reference (proposals are inputs to stage 2, not a grad path)."""
        cp = self.rpn_probs(F, F.stop_gradient(rpn_cls))
        return F.contrib.Proposal(
            cp, F.stop_gradient(rpn_bbox), im_info,
            rpn_pre_nms_top_n=self._rpn_pre,
            rpn_post_nms_top_n=self._rpn_post,
            rpn_min_size=self._rpn_min, scales=self._scales,
            ratios=self._ratios, feature_stride=self._stride)

    def rcnn_head(self, F, feat, rois):
        """Stage two: ROIAlign -> 2 fc -> (cls (R, C+1), bbox (R, 4(C+1)))."""
        pooled = F.contrib.ROIAlign(feat, rois, pooled_size=self._roi_size,
                                    spatial_scale=1.0 / self._stride)
        h = self.top(pooled.reshape((pooled.shape[0], -1)))
        return self.cls_head(h), self.bbox_head(h)

    # ------------------------------------------------------------------
    def detect(self, x, im_info=None, threshold=0.05, nms_threshold=0.3,
               topk=-1):
        """End-to-end inference: (B, R, 6) rows [cls_id, score, x1, y1,
        x2, y2], cls_id = -1 for suppressed/below-threshold rows."""
        from .. import ndarray as F
        from ..ndarray import NDArray  # noqa: F401

        b = x.shape[0]
        if im_info is None:
            im_info = F.array(np.tile(
                np.array([[x.shape[2], x.shape[3], 1.0]], np.float32),
                (b, 1)), ctx=x.context)
        feat, rpn_cls, rpn_bbox = self(x)
        rois = self.proposals(F, rpn_cls, rpn_bbox, im_info)
        cls_pred, bbox_pred = self.rcnn_head(F, feat, rois)
        probs = F.softmax(cls_pred, axis=-1).asnumpy()      # (B*R, C+1)
        deltas = bbox_pred.asnumpy().reshape(
            -1, self.num_classes + 1, 4) * np.array(
                [0.1, 0.1, 0.2, 0.2], np.float32)
        rois_np = rois.asnumpy()
        r_per = self._rpn_post
        out = []
        for i in range(b):
            rows = []
            for j in range(r_per):
                k = i * r_per + j
                c = int(probs[k, 1:].argmax()) + 1
                score = float(probs[k, c])
                roi = rois_np[k, 1:]
                rw = roi[2] - roi[0] + 1.0
                rh = roi[3] - roi[1] + 1.0
                cx = roi[0] + rw / 2 + deltas[k, c, 0] * rw
                cy = roi[1] + rh / 2 + deltas[k, c, 1] * rh
                w = np.exp(np.clip(deltas[k, c, 2], -10, 10)) * rw
                h = np.exp(np.clip(deltas[k, c, 3], -10, 10)) * rh
                rows.append([c - 1, score, cx - w / 2, cy - h / 2,
                             cx + w / 2, cy + h / 2])
            out.append(rows)
        dets = F.array(np.asarray(out, np.float32), ctx=x.context)
        return F.contrib.box_nms(dets, overlap_thresh=nms_threshold,
                                 valid_thresh=threshold, topk=topk,
                                 coord_start=2, score_index=1, id_index=0,
                                 force_suppress=False)


class FasterRCNNTrainLoss(HybridBlock):
    """All four Faster-RCNN losses in one hybridizable block:
    RPN balanced sigmoid CE + RPN smooth-L1 (sigma=3) + RCNN softmax CE +
    RCNN per-class smooth-L1 (reference: example/rcnn train_end2end.py).
    """

    def __init__(self, net: FasterRCNN, rpn_fg_overlap=0.7,
                 rpn_bg_overlap=0.3, rcnn_fg_overlap=0.5,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.net = net
        self._rpn_fg = rpn_fg_overlap
        self._rpn_bg = rpn_bg_overlap
        self._rcnn_fg = rcnn_fg_overlap
        self._ce = gloss.SoftmaxCrossEntropyLoss()

    def hybrid_forward(self, F, x, gt_boxes, im_info):
        net = self.net
        a = net._num_anchors
        b = x.shape[0]
        feat, rpn_cls, rpn_bbox = net(x)

        # ---- RPN targets + losses
        labels, bt, bw = F.contrib.RPNAnchorTarget(
            rpn_cls, gt_boxes, scales=net._scales, ratios=net._ratios,
            feature_stride=net._stride, fg_overlap=self._rpn_fg,
            bg_overlap=self._rpn_bg)
        bg_l = F.slice_axis(rpn_cls, axis=1, begin=0, end=a) \
                .transpose((0, 2, 3, 1)).reshape((0, -1))
        fg_l = F.slice_axis(rpn_cls, axis=1, begin=a, end=2 * a) \
                .transpose((0, 2, 3, 1)).reshape((0, -1))
        logit = fg_l - bg_l                                   # (B, N)
        y = F.maximum(labels, 0.0)
        # stable sigmoid CE; fg and bg halves normalized separately — the
        # static equivalent of the reference's 256-anchor balanced sample
        ce = (F.relu(logit) - logit * y
              + F.Activation(-F.abs(logit), act_type="softrelu"))
        fg_m = (labels == 1.0).astype("float32")
        bg_m = (labels == 0.0).astype("float32")
        one = F.ones_like(fg_m.sum())
        rpn_cls_loss = ((ce * fg_m).sum() / F.maximum(fg_m.sum(), one)
                        + (ce * bg_m).sum() / F.maximum(bg_m.sum(), one))
        rb = rpn_bbox.transpose((0, 2, 3, 1)).reshape((0, -1, 4))
        rpn_box_loss = (F.smooth_l1((rb - bt) * bw, scalar=3.0).sum()
                        / F.maximum(fg_m.sum(), one))

        # ---- stage 2: proposals (grad-blocked), targets, head losses
        rois = net.proposals(F, rpn_cls, rpn_bbox, im_info)
        rois2, rlabels, rbt, rbw = F.contrib.ProposalTarget(
            rois, gt_boxes, num_classes=net.num_classes + 1,
            batch_images=b, batch_rois=b * net._rois_per_image,
            fg_fraction=net._fg_fraction, fg_overlap=self._rcnn_fg)
        cls_pred, bbox_pred = net.rcnn_head(F, feat, rois2)
        rcnn_cls_loss = self._ce(cls_pred, rlabels).mean()
        rfg = (rlabels > 0.0).astype("float32")
        rcnn_box_loss = (F.smooth_l1((bbox_pred - rbt) * rbw,
                                     scalar=1.0).sum()
                         / F.maximum(rfg.sum(), one))
        return rpn_cls_loss + rpn_box_loss + rcnn_cls_loss + rcnn_box_loss


def faster_rcnn_small(num_classes=2, **kwargs) -> FasterRCNN:
    """Small config for tests/smokes (stride-8 body, 6 anchors/cell)."""
    return FasterRCNN(num_classes=num_classes, **kwargs)
