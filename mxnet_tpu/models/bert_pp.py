"""Pipeline-parallel BERT: a stacked-parameter encoder that routes through
the compiled GPipe schedule (parallel/pipeline.py) over the 'pp' mesh axis.

Reference: none — the reference's nearest analog is group2ctx manual
placement with no microbatching (SURVEY §2.3); this is a novel capability
held to that row's target.

TPU-native design: every encoder layer shares ONE apply function; the L
per-layer parameter tensors are STACKED along a leading dim that shards
over 'pp' (each stage owns L/S layers).  Off the pp mesh the same stack
runs as a `lax.scan` — one compiled layer body instead of L inlined
copies, so even single-chip tracing/compile gets faster.  Embedding and
the MLM head run on every rank (replicated compute, activations stay
dp-sharded); stage placement of embed/head is unnecessary in the SPMD
formulation because XLA already overlaps them with the schedule.

Divergences from models/bert.py (documented): no dropout inside the
stacked encoder (a per-layer key chain through scan+ppermute buys nothing
for the pp parity/dryrun story), and no attention mask (full-sequence
pretraining batches).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..parallel.sharding import ShardingRules
from .transformer import PositionalEmbedding

__all__ = ["StackedTransformerEncoder", "BERTForMLMPipelined",
           "bert_pp_small", "bert_pp_sharding_rules"]


class StackedTransformerEncoder(HybridBlock):
    """L post-LN encoder layers with stacked (L, ...) parameters.

    Matches TransformerEncoderCell semantics (post-LN, gelu FFN, fused
    qkv) with dropout=0; see module docstring for the divergence note.
    """

    def __init__(self, num_layers, units, hidden_size, num_heads,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._L = num_layers
        self._units = units
        self._hidden = hidden_size
        self._heads = num_heads
        self._head_dim = units // num_heads
        L, U, H = num_layers, units, hidden_size
        with self.name_scope():
            g = self.params.get
            # qkv as (L, 3, U, U) — one PartitionSpec tp-splits the head
            # dim of q, k and v alike (a fused (3U, U) layout would chunk
            # contiguous rows across the q/k/v thirds)
            self.qkv_weight = g("qkv_weight", shape=(L, 3, U, U))
            self.qkv_bias = g("qkv_bias", shape=(L, 3, U))
            self.proj_weight = g("proj_weight", shape=(L, U, U))
            self.proj_bias = g("proj_bias", shape=(L, U))
            self.ffn1_weight = g("ffn1_weight", shape=(L, H, U))
            self.ffn1_bias = g("ffn1_bias", shape=(L, H))
            self.ffn2_weight = g("ffn2_weight", shape=(L, U, H))
            self.ffn2_bias = g("ffn2_bias", shape=(L, U))
            self.ln1_gamma = g("ln1_gamma", shape=(L, U), init="ones")
            self.ln1_beta = g("ln1_beta", shape=(L, U), init="zeros")
            self.ln2_gamma = g("ln2_gamma", shape=(L, U), init="ones")
            self.ln2_beta = g("ln2_beta", shape=(L, U), init="zeros")

    # -- pure jnp layer body shared by scan and pipeline paths ---------
    def _layer(self, p, x, tp_axis=None):
        """One post-LN encoder layer.

        tp_axis: set ('tp') ONLY inside the pipeline's shard_map when the
        mesh has tp>1 — weights arrive as Megatron column/row shards and
        the two row-parallel matmuls psum their partial outputs here.
        Outside shard_map (the lax.scan path) tp_axis stays None and
        GSPMD inserts the collectives from the parameter shardings.
        """
        hd = self._head_dim

        def ln(y, gamma, beta):
            mu = y.mean(-1, keepdims=True)
            var = ((y - mu) ** 2).mean(-1, keepdims=True)
            return (y - mu) / jnp.sqrt(var + 1e-5) * gamma + beta

        b, t, u = x.shape
        qw, qb = p["qkv_weight"], p["qkv_bias"]  # (3, Uloc, U), (3, Uloc)
        q = x @ qw[0].T + qb[0]
        k = x @ qw[1].T + qb[1]
        v = x @ qw[2].T + qb[2]
        nh_loc = q.shape[-1] // hd  # heads this shard owns (nh/tp)

        def heads(y):  # (B, T, Uloc) -> (B, nh_loc, T, hd)
            return y.reshape(b, t, nh_loc, hd).transpose(0, 2, 1, 3)

        q, k, v = heads(q), heads(k), heads(v)
        scores = (q @ k.transpose(0, 1, 3, 2)) / math.sqrt(hd)
        attn = jax.nn.softmax(scores, axis=-1)
        out = (attn @ v).transpose(0, 2, 1, 3).reshape(b, t, -1)
        out = out @ p["proj_weight"].T  # row-parallel: partial (B, T, U)
        if tp_axis is not None:
            out = jax.lax.psum(out, tp_axis)
        out = out + p["proj_bias"]
        x = ln(x + out, p["ln1_gamma"], p["ln1_beta"])
        h = x @ p["ffn1_weight"].T + p["ffn1_bias"]  # column-parallel
        h = jax.nn.gelu(h, approximate=False)
        h = h @ p["ffn2_weight"].T  # row-parallel: partial (B, T, U)
        if tp_axis is not None:
            h = jax.lax.psum(h, tp_axis)
        h = h + p["ffn2_bias"]
        return ln(x + h, p["ln2_gamma"], p["ln2_beta"])

    def hybrid_forward(self, F, x, **params):
        from ..base import MXNetError
        from ..ndarray import NDArray
        from ..parallel.scope import pipeline_scope

        stacked = {n: (p._data if isinstance(p, NDArray) else p)
                   for n, p in params.items()}
        xa = x._data if isinstance(x, NDArray) else x
        pp = pipeline_scope()
        if pp is None:
            def body(c, pl):
                return self._layer(pl, c), None

            out, _ = jax.lax.scan(body, xa, stacked)
        else:
            from ..parallel.pipeline import pipeline_apply

            mesh, batch_axes, m = pp
            bsz = xa.shape[0]
            if bsz % m:
                raise MXNetError(
                    f"batch {bsz} not divisible by pp microbatches {m}")
            dp_total = 1
            for a in batch_axes:
                dp_total *= mesh.shape[a]
            if (bsz // m) % dp_total:
                raise MXNetError(
                    f"per-microbatch batch {bsz // m} not divisible by the "
                    f"data-parallel extent {dp_total} ({batch_axes}); lower "
                    f"pp_microbatches or raise the batch size")
            # tensor parallelism inside the stage: Megatron shards over
            # 'tp' (activations replicated across tp; _layer psums the
            # row-parallel outputs)
            tp = mesh.shape.get("tp", 1) > 1
            if tp and self._heads % mesh.shape["tp"]:
                raise MXNetError(
                    f"{self._heads} heads not divisible by "
                    f"tp={mesh.shape['tp']}")
            layer_fn = ((lambda pl, c: self._layer(pl, c, tp_axis="tp"))
                        if tp else self._layer)
            # strided microbatches (rows i::m): a dp-sharded batch dim
            # stays dp-sharded per microbatch with zero data movement
            xm = xa.reshape(bsz // m, m, *xa.shape[1:]).transpose(
                1, 0, *range(2, xa.ndim + 1))
            ym = pipeline_apply(mesh, layer_fn, stacked, xm,
                                batch_axes=batch_axes,
                                param_specs=_pp_param_specs(
                                    stacked, tp=tp))
            out = ym.transpose(1, 0, *range(2, ym.ndim)).reshape(xa.shape)
            if not isinstance(out, jax.core.Tracer):
                # eager call: bring the mesh-sharded result back to the
                # input's device so downstream eager ops see one device
                out = jax.device_put(out, next(iter(xa.devices())))
        return NDArray(out, ctx=x.context) if isinstance(x, NDArray) else out


class BERTForMLMPipelined(HybridBlock):
    """BERT MLM with the stacked encoder; train with DataParallelStep over
    a mesh whose 'pp' axis is >1 (plus 'dp') and rules from
    bert_pp_sharding_rules()."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512,
                 dropout=0.1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.pos_embed = PositionalEmbedding(max_length, units,
                                                 prefix="pos_embed_")
            self.embed_ln = nn.LayerNorm(in_channels=units,
                                         prefix="embed_ln_")
            self.embed_drop = nn.Dropout(dropout)
            self.encoder = StackedTransformerEncoder(
                num_layers, units, hidden_size, num_heads,
                prefix="enc_stack_")
            self.mlm_dense = nn.Dense(units, flatten=False,
                                      prefix="mlm_dense_")
            self.mlm_ln = nn.LayerNorm(in_channels=units, prefix="mlm_ln_")
            self.decoder = nn.Dense(vocab_size, flatten=False,
                                    prefix="decoder_")

    def hybrid_forward(self, F, inputs):
        x = self.embed_drop(self.embed_ln(
            self.pos_embed(self.word_embed(inputs))))
        seq = self.encoder(x)
        h = self.mlm_ln(F.LeakyReLU(self.mlm_dense(seq), act_type="gelu"))
        return self.decoder(h)


# Megatron layout of the stacked encoder leaves: which non-layer dim (if
# any) carries the 'tp' shard.  qkv/ffn1 are column-parallel (output dim),
# proj/ffn2 row-parallel (input dim); ln/bias-after-psum replicate.
_TP_DIM = {
    "qkv_weight": 2, "qkv_bias": 2,      # (L, 3, U, U) / (L, 3, U)
    "ffn1_weight": 1, "ffn1_bias": 1,    # (L, H, U) / (L, H)
    "proj_weight": 2,                    # (L, U, U) input dim
    "ffn2_weight": 2,                    # (L, U, H) input dim
}


def _pp_param_specs(stacked, tp: bool):
    """PartitionSpec tree for pipeline_apply: layer dim over 'pp', plus
    the Megatron 'tp' dim per leaf when tp is active."""
    from jax.sharding import PartitionSpec as P

    specs = {}
    for name, arr in stacked.items():
        short = name.rsplit("_", 2)
        key = "_".join(short[-2:])
        dims = [None] * arr.ndim
        dims[0] = "pp"
        if tp and key in _TP_DIM:
            dims[_TP_DIM[key]] = "tp"
        specs[name] = P(*dims)
    return specs


def bert_pp_sharding_rules() -> ShardingRules:
    """Stacked encoder params shard their LAYER dim over 'pp' and (where
    the Megatron layout allows) a weight dim over 'tp'; embeddings and
    the MLM head stay replicated (they run on every rank).  Derived from
    the same _TP_DIM table as _pp_param_specs, so the GSPMD shardings
    MATCH the shard_map specs and entering the pipeline moves no data."""
    rules = [
        (rf".*enc_stack_{key}$",
         ("pp",) + (None,) * (dim - 1) + ("tp",))
        for key, dim in _TP_DIM.items()
    ]
    rules.append((r".*enc_stack_.*", ("pp",)))
    return ShardingRules(rules)


def bert_pp_small(vocab_size=512, units=64, hidden_size=128, num_layers=4,
                  num_heads=4, max_length=64, **kwargs) -> BERTForMLMPipelined:
    return BERTForMLMPipelined(vocab_size=vocab_size, units=units,
                               hidden_size=hidden_size,
                               num_layers=num_layers, num_heads=num_heads,
                               max_length=max_length, **kwargs)
