"""SSD single-shot detector (reference: example/ssd/symbol/symbol_builder.py
+ the GluonCV SSD recipe the reference docs point at).

TPU-native shape: every stage is static — a fixed anchor set per feature
scale from MultiBoxPrior, training targets from MultiBoxTarget, decode+NMS
from MultiBoxDetection — so the whole train step (features, heads, target
matching, losses) compiles to ONE XLA program, vs the reference's chain of
imperative CUDA kernels.
"""
from __future__ import annotations

from ..gluon import HybridBlock, loss as gloss, nn

__all__ = ["SSD", "SSDTrainLoss", "ssd_300"]


def _conv_block(channels, kernel=3, stride=1, pad=1):
    blk = nn.HybridSequential()
    blk.add(nn.Conv2D(channels, kernel, strides=stride, padding=pad,
                      use_bias=False),
            nn.BatchNorm(), nn.Activation("relu"))
    return blk


def _down_sample(channels):
    """conv-conv-pool halving the resolution (example/ssd body pattern)."""
    blk = nn.HybridSequential()
    blk.add(_conv_block(channels), _conv_block(channels),
            nn.MaxPool2D(2))
    return blk


class SSD(HybridBlock):
    """Multi-scale SSD head over a light conv body.

    Outputs (anchors (1, N, 4), cls_preds (B, N, num_classes+1),
    box_preds (B, N*4)).
    """

    def __init__(self, num_classes, sizes=None, ratios=None,
                 base_channels=(16, 32, 64), prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.num_classes = num_classes
        # per-scale anchor spec (5 scales, example/ssd defaults)
        self.sizes = sizes or [[0.2, 0.272], [0.37, 0.447], [0.54, 0.619],
                               [0.71, 0.79], [0.88, 0.961]]
        self.ratios = ratios or [[1, 2, 0.5]] * 5
        self._num_scales = len(self.sizes)
        with self.name_scope():
            body = nn.HybridSequential()
            for c in base_channels:
                body.add(_down_sample(c))
            self.body = body
            self.blocks = nn.HybridSequential()
            self.cls_heads = nn.HybridSequential()
            self.box_heads = nn.HybridSequential()
            for i in range(self._num_scales):
                if i == 0:
                    self.blocks.add(nn.HybridLambda(lambda F, x: x))
                elif i == self._num_scales - 1:
                    self.blocks.add(nn.GlobalMaxPool2D())
                else:
                    self.blocks.add(_down_sample(128))
                a = len(self.sizes[i]) + len(self.ratios[i]) - 1
                self.cls_heads.add(nn.Conv2D(a * (num_classes + 1), 3,
                                             padding=1))
                self.box_heads.add(nn.Conv2D(a * 4, 3, padding=1))

    def hybrid_forward(self, F, x):
        feat = self.body(x)
        anchors, cls_preds, box_preds = [], [], []
        for i in range(self._num_scales):
            feat = self.blocks[i](feat)
            anchors.append(F.contrib.MultiBoxPrior(
                feat, sizes=self.sizes[i], ratios=self.ratios[i]))
            cls = self.cls_heads[i](feat)
            # (B, aC, H, W) -> (B, H*W*a, C+1)
            cls = cls.transpose((0, 2, 3, 1)).reshape(
                (0, -1, self.num_classes + 1))
            cls_preds.append(cls)
            box = self.box_heads[i](feat)
            box_preds.append(box.transpose((0, 2, 3, 1)).reshape((0, -1)))
        return (F.concat(*anchors, dim=1),
                F.concat(*cls_preds, dim=1),
                F.concat(*box_preds, dim=1))

    def detect(self, x, threshold=0.01, nms_threshold=0.45, topk=400):
        """Decode + NMS: (B, N, 6) rows [cls_id, score, x0, y0, x1, y1]."""
        from .. import ndarray as F

        anchors, cls_preds, box_preds = self(x)
        cls_prob = F.softmax(cls_preds, axis=-1).transpose((0, 2, 1))
        return F.contrib.MultiBoxDetection(
            cls_prob, box_preds, anchors, threshold=threshold,
            nms_threshold=nms_threshold, nms_topk=topk)


class SSDTrainLoss(HybridBlock):
    """MultiBoxTarget matching + (softmax CE, smooth-L1) losses in one
    hybridizable block (reference example/ssd training loss)."""

    def __init__(self, negative_mining_ratio=3.0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._ce = gloss.SoftmaxCrossEntropyLoss()
        self._ratio = negative_mining_ratio

    def hybrid_forward(self, F, anchors, cls_preds, box_preds, labels):
        box_target, box_mask, cls_target = F.contrib.MultiBoxTarget(
            anchors, labels, cls_preds.transpose((0, 2, 1)),
            negative_mining_ratio=self._ratio)
        flat_t = cls_target.reshape((-1,))
        valid = flat_t >= 0.0  # hard-negative mining marks skips with -1
        ce = self._ce(cls_preds.reshape((-1, cls_preds.shape[-1])),
                      F.maximum(flat_t, flat_t * 0.0))
        vmask = valid.astype("float32")
        cls_loss = (ce * vmask).sum() / F.maximum(vmask.sum(),
                                                  vmask.sum() * 0.0 + 1.0)
        box_loss = F.smooth_l1((box_preds - box_target) * box_mask,
                               scalar=1.0).mean()
        return cls_loss + box_loss


def ssd_300(num_classes=20, **kwargs) -> SSD:
    """SSD-300-class detector with the default 5-scale anchor spec."""
    return SSD(num_classes=num_classes, **kwargs)
