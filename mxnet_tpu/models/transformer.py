"""Transformer building blocks (HybridBlocks).

Reference parity: GluonNLP's transformer encoder (BASELINE configs 3/4 use
BERT-base and Transformer-big built from these pieces) and the reference's
fused attention matmuls (src/operator/contrib/transformer.cc
interleaved_matmul_selfatt_* ~L1-300).

TPU-native: attention is expressed as batched matmuls + softmax that XLA
fuses and tiles onto the MXU; the qkv/out/ffn projection weights carry
tensor-parallel shardings via mxnet_tpu.parallel.sharding rules (head axis
split over the 'tp' mesh axis — collectives inserted by GSPMD).
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError, dtype_np
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["MultiHeadAttention", "MultiHeadCrossAttention", "PositionwiseFFN",
           "TransformerEncoderCell", "TransformerEncoder",
           "PositionalEmbedding", "TransformerDecoderCell",
           "TransformerDecoder", "Transformer", "DenseStepCache",
           "transformer_base", "transformer_big", "label_smoothed_ce"]


def _split_heads(t, num_heads, head_dim):
    # (B, T, C) -> (B*H, T, hd)
    t = t.reshape(0, 0, -4, num_heads, head_dim)
    t = t.transpose((0, 2, 1, 3))
    return t.reshape(-3, 0, 0)


def _merge_heads(t, num_heads):
    # (B*H, T, hd) -> (B, T, C)
    t = t.reshape(-4, -1, num_heads, 0, 0)
    return t.transpose((0, 2, 1, 3)).reshape(0, 0, -3)


def _mask_scores(F, scores, mask, num_heads):
    """mask: (B, Tq, Tk) with 1=keep, broadcast over heads of (B*H, Tq, Tk)
    scores; masked-out positions get the dtype-safe big negative."""
    # Symbols carry no host-side dtype — use the half-safe -3e4 for the
    # trace (exact-0 softmax weight in f32 too, and an fp16/bf16 export
    # of the traced graph stays finite where -1e9 would overflow to -inf)
    dt = getattr(scores, "dtype", None)
    big_neg = -1e9 if (dt is not None and "16" not in str(dt)) else -3e4
    m = mask.expand_dims(1)
    m = F.broadcast_like(m, scores.reshape(-4, -1, num_heads, 0, 0),
                         lhs_axes=(1,), rhs_axes=(1,))
    m = m.reshape(-3, 0, 0)
    return F.where(m, scores, F.ones_like(scores) * big_neg)


class MultiHeadAttention(HybridBlock):
    """Self/cross attention with fused qkv projection.

    Weight layout (3*units, in) for qkv — the head dimension is the leading
    axis so a 'tp' sharding of axis 0 splits heads across devices.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise MXNetError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._dropout = dropout
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 prefix="proj_")
            self.attn_drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None):
        # x: (B, T, C)
        qkv = self.qkv(x)  # (B, T, 3C)
        q, k, v = F.split(qkv, num_outputs=3, axis=-1)
        q = _split_heads(q, self._num_heads, self._head_dim)
        k = _split_heads(k, self._num_heads, self._head_dim)
        v = _split_heads(v, self._num_heads, self._head_dim)
        from .. import autograd as _ag

        if mask is None and (self._dropout == 0.0 or not _ag.is_training()):
            # fused flash-attention path (Pallas on TPU); only taken when
            # attention-prob dropout is inactive, so it is numerically
            # equivalent to the dense path
            out = F._contrib_flash_attention(q, k, v, causal=self._causal)
            return self.proj(_merge_heads(out, self._num_heads))
        scores = F.batch_dot(q, k, transpose_b=True) / math.sqrt(self._head_dim)
        if self._causal:
            if hasattr(scores, "shape"):  # eager / CachedOp tracer
                T = scores.shape[-1]
                neg = -1e9 if str(scores.dtype).find("16") < 0 else -3e4
                # constant built host-side IN the score dtype: an f32
                # addend would silently promote the whole bf16 attention
                # chain to f32
                addend = F.array(
                    np.triu(np.full((T, T), neg, dtype_np(scores.dtype)),
                            k=1),
                    ctx=scores.context, dtype=dtype_np(scores.dtype))
                scores = F.broadcast_add(scores, addend.expand_dims(0))
            else:
                # Symbol trace (export): no host-side T — build the tril
                # keep-mask from ops.  cumsum(identity, axis=0)[i, j] is
                # 1 iff i >= j, the causal rule (self-attention: Tq == Tk)
                ones_k = F.Reshape(
                    F.slice_axis(F.slice_axis(F.ones_like(scores), axis=0,
                                              begin=0, end=1),
                                 axis=1, begin=0, end=1), shape=(-1,))
                keep = F.cumsum(F.linalg_makediag(ones_k), axis=0)
                keep = F.broadcast_like(keep.expand_dims(0), scores,
                                        lhs_axes=(0,), rhs_axes=(0,))
                # -3e4, not -1e9: still exactly 0 after f32 softmax, and
                # finite if the traced graph is exported/cast to 16-bit
                scores = F.where(keep, scores,
                                 F.ones_like(scores) * -3e4)
        if mask is not None:
            scores = _mask_scores(F, scores, mask, self._num_heads)
        attn = F.softmax(scores, axis=-1)
        attn = self.attn_drop(attn)
        out = F.batch_dot(attn, v)  # (B*H, T, hd)
        return self.proj(_merge_heads(out, self._num_heads))


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout)
        self._activation = activation

    def hybrid_forward(self, F, x):
        h = self.ffn_1(x)
        h = (F.LeakyReLU(h, act_type="gelu") if self._activation == "gelu"
             else F.Activation(h, act_type=self._activation))
        return self.drop(self.ffn_2(h))


class TransformerEncoderCell(HybridBlock):
    """Pre/post-LN encoder layer."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout,
                                           prefix="attn_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout, activation,
                                       prefix="ffn_")
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None):
        if self._pre_norm:
            x = x + self.drop(self.attn(self.ln1(x), mask))
            return x + self.ffn(self.ln2(x))
        x = self.ln1(x + self.drop(self.attn(x, mask)))
        return self.ln2(x + self.ffn(x))


class PositionalEmbedding(HybridBlock):
    """Learned positional embedding (BERT-style)."""

    def __init__(self, max_length, units, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(max_length, units))

    def hybrid_forward(self, F, x, weight):
        # x: (B, T, C); add positions [0, T).  slice_like instead of
        # .shape keeps the block Symbol-traceable (export / SymbolBlock)
        pos = F.slice_like(F.expand_dims(weight, axis=0), x, axes=(1,))
        return F.broadcast_add(x, pos)


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_layers = num_layers
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout, pre_norm,
                    activation, prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask=None):
        for cell in self.layers:
            x = cell(x, mask)
        return x


# ---------------------------------------------------------------------------
# seq2seq (BASELINE config 4: Transformer-big WMT14; reference: GluonNLP
# scripts/machine_translation transformer encoder-decoder)
# ---------------------------------------------------------------------------
class MultiHeadCrossAttention(HybridBlock):
    """Decoder->encoder attention: q from x, k/v from the encoder memory.

    Weight layout mirrors MultiHeadAttention: q (units, in), kv (2*units,
    in) with heads on the leading axis, so 'tp' shardings split heads.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise MXNetError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        with self.name_scope():
            self.q_proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                   prefix="q_")
            self.kv = nn.Dense(2 * units, flatten=False, use_bias=use_bias,
                               prefix="kv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 prefix="proj_")
            self.attn_drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mem, mask=None):
        # x: (B, Tq, C); mem: (B, Tk, C); mask: (B, Tq, Tk) with 1=keep
        q = self.q_proj(x)
        kv = self.kv(mem)
        k, v = F.split(kv, num_outputs=2, axis=-1)
        q = _split_heads(q, self._num_heads, self._head_dim)
        k = _split_heads(k, self._num_heads, self._head_dim)
        v = _split_heads(v, self._num_heads, self._head_dim)
        scores = F.batch_dot(q, k, transpose_b=True) / math.sqrt(self._head_dim)
        if mask is not None:
            scores = _mask_scores(F, scores, mask, self._num_heads)
        attn = self.attn_drop(F.softmax(scores, axis=-1))
        out = F.batch_dot(attn, v)
        return self.proj(_merge_heads(out, self._num_heads))


class TransformerDecoderCell(HybridBlock):
    """Causal self-attention + cross-attention + FFN (post-LN, the WMT
    recipe; pre_norm=True for the deep-net variant)."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.self_attn = MultiHeadAttention(units, num_heads, dropout,
                                                causal=True, prefix="self_")
            self.cross_attn = MultiHeadCrossAttention(units, num_heads,
                                                      dropout, prefix="cross_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout, activation,
                                       prefix="ffn_")
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.ln3 = nn.LayerNorm(in_channels=units, prefix="ln3_")
            self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mem, self_mask=None, cross_mask=None):
        if self._pre_norm:
            x = x + self.drop(self.self_attn(self.ln1(x), self_mask))
            x = x + self.drop(self.cross_attn(self.ln2(x), mem, cross_mask))
            return x + self.ffn(self.ln3(x))
        x = self.ln1(x + self.drop(self.self_attn(x, self_mask)))
        x = self.ln2(x + self.drop(self.cross_attn(x, mem, cross_mask)))
        return self.ln3(x + self.ffn(x))

    def step(self, F, x_t, mem, cross_mask_t, cache):
        """Incremental decode of ONE position with cached self-attn K/V.

        x_t: (B, 1, C); ``cache`` is this layer's step-cache object
        (:class:`DenseStepCache`, or ``serving.paged_cache.
        PagedStepCache`` for the paged pool): it writes this position's
        k/v and attends the query over every row written so far.
        Returns y_t; the updated cache state stays on the cache object.
        Inference-only (dropout is identity outside autograd.record)."""
        sa = self.self_attn
        if self._pre_norm:
            h = self.ln1(x_t)
        else:
            h = x_t
        qkv = sa.qkv(h)
        q_t, k_t, v_t = F.split(qkv, num_outputs=3, axis=-1)
        a = sa.proj(cache.update_and_attend(F, sa, q_t, k_t, v_t))
        if self._pre_norm:
            x = x_t + a
            x = x + self.cross_attn(self.ln2(x), mem, cross_mask_t)
            return x + self.ffn(self.ln3(x))
        x = self.ln1(x_t + a)
        x = self.ln2(x + self.cross_attn(x, mem, cross_mask_t))
        return self.ln3(x + self.ffn(x))


class TransformerDecoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="relu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.layers.add(TransformerDecoderCell(
                    units, hidden_size, num_heads, dropout, pre_norm,
                    activation, prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mem, self_mask=None, cross_mask=None):
        for cell in self.layers:
            x = cell(x, mem, self_mask, cross_mask)
        return x


def _attend_cached(F, q_t, K, V, keep, num_heads, head_dim):
    """One-query attention over a fixed-size cache.

    q_t: (B, 1, C); K/V: (B, Lmax, C) with valid rows marked by keep
    (B, Lmax, 1 = attend).  Shape-stable across decode steps (the cache
    never grows), so XLA compiles the step scorer exactly once."""
    q = _split_heads(q_t, num_heads, head_dim)        # (B*H, 1, hd)
    k = _split_heads(K, num_heads, head_dim)          # (B*H, Lmax, hd)
    v = _split_heads(V, num_heads, head_dim)
    scores = F.batch_dot(q, k, transpose_b=True) / math.sqrt(head_dim)
    scores = _mask_scores(F, scores, keep.expand_dims(1), num_heads)
    attn = F.softmax(scores, axis=-1)
    out = F.batch_dot(attn, v)                        # (B*H, 1, hd)
    return _merge_heads(out, num_heads)               # (B, 1, C)


class DenseStepCache:
    """Per-layer dense (B, Lmax, C) K/V decode cache (the seed design):
    this position's k/v are written at the host-known row ``t``, and
    validity is the ``keep`` mask (B, Lmax), 1 = attend.

    Kept as the bitwise reference for the paged cache
    (``mxnet_tpu.serving.paged_cache``): the serving parity tests assert
    paged decode == dense decode for the same tokens, and anything that
    only needs a single fixed-length sequence can keep using it."""

    def __init__(self, K, V, keep, t):
        self.K, self.V, self.keep = K, V, keep
        self.t = int(t)

    def update_and_attend(self, F, attn, q_t, k_t, v_t):
        t = self.t
        self.K[:, t:t + 1] = k_t
        self.V[:, t:t + 1] = v_t
        return _attend_cached(F, q_t, self.K, self.V, self.keep,
                              attn._num_heads, attn._head_dim)


class Transformer(HybridBlock):
    """Encoder-decoder Transformer with shared source/target embedding and
    tied output projection (the WMT14 recipe; GluonNLP
    scripts/machine_translation/transformer.py analog, re-designed as one
    hybridizable block so the whole train step is a single XLA program).

    forward(src, tgt) -> logits (B, Tt, vocab).  Padding id 0 is masked
    out of both attention directions; the decoder self-attention is causal.
    """

    def __init__(self, vocab_size, units=512, hidden_size=2048, num_heads=8,
                 num_layers=6, max_length=1024, dropout=0.1, pad_id=0,
                 tie_embeddings=True, activation="relu", prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        self._pad_id = pad_id
        self._vocab = vocab_size
        self._tie = tie_embeddings
        with self.name_scope():
            self.embed = nn.Embedding(vocab_size, units, prefix="embed_")
            self.pos = PositionalEmbedding(max_length, units, prefix="pos_")
            self.enc_drop = nn.Dropout(dropout)
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              activation=activation,
                                              prefix="enc_")
            self.decoder = TransformerDecoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              activation=activation,
                                              prefix="dec_")
            if not tie_embeddings:
                self.out_proj = nn.Dense(vocab_size, flatten=False,
                                         prefix="out_")

    def _encode_h(self, F, src):
        """(memory, src_keep) — key-padding mask layout (B, Tq, Tk),
        1 = attend.  Causality is NOT folded into masks: the decoder's
        self-attention block is constructed causal=True and applies the
        tril itself."""
        # not_equal / broadcast_like instead of `!=` + .shape so the block
        # stays Symbol-traceable (export / ONNX); same numerics in eager
        src_keep = F.not_equal(src, self._pad_id)  # (B, Ts)
        enc_mask = F.broadcast_like(src_keep.expand_dims(1), src,
                                    lhs_axes=(1,), rhs_axes=(1,))
        mem = self.embed(src) * math.sqrt(self._units)
        mem = self.enc_drop(self.pos(mem))
        return self.encoder(mem, enc_mask), src_keep

    def _decode_h(self, F, tgt, mem, src_keep):
        cross_mask = F.broadcast_like(src_keep.expand_dims(1), tgt,
                                      lhs_axes=(1,), rhs_axes=(1,))
        self_mask = F.broadcast_like(
            F.not_equal(tgt, self._pad_id).expand_dims(1), tgt,
            lhs_axes=(1,), rhs_axes=(1,))  # (B, Tt, Tt)
        h = self.embed(tgt) * math.sqrt(self._units)
        h = self.enc_drop(self.pos(h))
        h = self.decoder(h, mem, self_mask, cross_mask)
        if self._tie:
            # tied softmax: logits = h E^T (shared embedding matrix);
            # flatten=False projects per position on the rank-3 input
            # directly.  Under Symbol tracing the shared weight enters as
            # its parameter variable (NDArrays cannot join a symbol graph)
            if hasattr(h, "context"):
                w = self.embed.weight.data(h.context)
            else:
                w = self.embed.weight.var()
            return F.FullyConnected(h, w, num_hidden=self._vocab,
                                    no_bias=True, flatten=False)
        return self.out_proj(h)

    def hybrid_forward(self, F, src, tgt):
        mem, src_keep = self._encode_h(F, src)
        return self._decode_h(F, tgt, mem, src_keep)

    def _decode_step(self, F, tok_t, pos, mem, src_keep, caches):
        """Logits (B, V) for one decode position using per-layer step
        caches (see TransformerDecoderCell.step).  Inference-only.

        ``pos`` is an int32 NDArray of per-row decode positions — (B,)
        for the serving engine's ragged slots, (1,) broadcasting one
        uniform position for ``translate``.  A device position (gather,
        not slice) keeps the step program shape-stable across positions:
        one executable decodes every t, the serving engine's
        no-per-length-retrace contract."""
        ctx = tok_t.context
        x = self.embed(tok_t) * math.sqrt(self._units)  # (B, 1, C)
        pos_rows = F.take(self.pos.weight.data(ctx), pos, axis=0)  # (n, C)
        x = F.broadcast_add(x, pos_rows.expand_dims(1))
        cross_mask_t = src_keep.expand_dims(1)  # (B, 1, Ts)
        for cell, cache in zip(self.decoder.layers, caches):
            x = cell.step(F, x, mem, cross_mask_t, cache)
        if self._tie:
            return F.FullyConnected(x.reshape(0, -1),
                                    self.embed.weight.data(ctx),
                                    num_hidden=self._vocab, no_bias=True)
        return self.out_proj(x).reshape(0, -1)

    # -- inference ---------------------------------------------------------
    def translate(self, src, bos_id, eos_id, max_len=32, beam_size=4,
                  alpha=0.6, incremental=True, sync_every=8,
                  page_size=None):
        """Beam-search decode (GNMT length penalty).

        src: NDArray (B, Ts) int.  Returns (B, max_len) numpy int32 of the
        best hypotheses (eos/pad-trimmed by the caller).  The encoder runs
        ONCE.  With incremental=True (default) the per-step scorer is a
        single-position decoder over the **paged KV cache**
        (mxnet_tpu.serving.paged_cache; beam slots own statically
        assigned page runs, beam reorders gather page contents) — O(L)
        per step, one executable family reused every step;
        incremental=False re-decodes the full padded prefix (O(L^2) per
        step, the cross-check path).

        Beam bookkeeping lives ON DEVICE (log-softmax, top-k, beam
        gather, EOS tracking are NDArray ops): no per-token host
        readback — the host reads one finished-count scalar every
        ``sync_every`` steps for early exit and the final state once at
        the end, so the dispatch pipeline never blocks per token (the
        serving-engine contract; docs/SERVING.md)."""
        from .. import autograd
        from .. import ndarray as F
        import numpy as _np

        B, Ts = src.shape
        K, V = beam_size, self._vocab
        BK = B * K
        ctx = src.context
        if max_len > self.pos._max_length:
            # the device position lookup is a gather (mode='clip'):
            # out-of-table positions would silently repeat the last
            # embedding row instead of failing
            raise MXNetError(
                f"max_len {max_len} > positional table "
                f"{self.pos._max_length}; build the model with a larger "
                "max_length")
        src_np = _np.asarray(src.asnumpy(), _np.int32)
        from ..ndarray import array as nd_array

        with autograd.pause():
            # encode the (B, Ts) batch ONCE, then tile memory for beams —
            # 1/K the encoder FLOPs of encoding the repeated batch
            src_1 = nd_array(src_np, ctx=ctx, dtype="int32")
            mem, src_keep = self._encode_h(F, src_1)
            mem = F.repeat(mem, repeats=K, axis=0)          # (B*K, Ts, C)
            src_keep = F.repeat(src_keep, repeats=K, axis=0)  # (B*K, Ts)

            # device-resident beam state
            tgt = nd_array(_np.full((BK, max_len), self._pad_id, _np.int32),
                           ctx=ctx, dtype="int32")
            tgt[:, 0] = bos_id
            last_tok = nd_array(_np.full((BK, 1), bos_id, _np.int32),
                                ctx=ctx, dtype="int32")
            s0 = _np.full((B, K), -_np.inf, _np.float32)
            s0[:, 0] = 0.0  # only beam 0 live at t=0 (all beams identical)
            scores = nd_array(s0, ctx=ctx)
            finished = nd_array(_np.zeros((B, K), _np.float32), ctx=ctx)
            # finished beams only extend with pad at zero cost
            lp0 = _np.full((1, 1, V), -_np.inf, _np.float32)
            lp0[..., self._pad_id] = 0.0
            lp_fin = nd_array(lp0, ctx=ctx)
            # constant index helpers, created once: every per-step update
            # below is value-only, so each eager op reuses ONE cached
            # executable instead of respecializing per position
            col_iota = nd_array(_np.arange(max_len, dtype=_np.int32)[None],
                                ctx=ctx, dtype="int32")
            b_off = nd_array((_np.arange(B, dtype=_np.int32) * K)[:, None],
                             ctx=ctx, dtype="int32")
            eos_nd = nd_array(_np.array([[eos_id]], _np.int32), ctx=ctx,
                              dtype="int32")
            pad_nd = nd_array(_np.array([[self._pad_id]], _np.int32),
                              ctx=ctx, dtype="int32")

            pools = None
            if incremental:
                from ..serving.paged_cache import (PagedKVCache,
                                                   PagedStepCache,
                                                   page_coords, pages_for)

                cell0 = self.decoder.layers[0].self_attn
                H, hd = cell0._num_heads, cell0._head_dim
                ps = int(page_size or min(16, max_len))
                P = pages_for(max_len, ps)
                cache = PagedKVCache(len(self.decoder.layers), BK * P + 1,
                                     ps, H, hd, ctx=ctx,
                                     dtype=_np.dtype(mem.dtype).name)
                # static CONTIGUOUS slot-per-beam page runs (beam s owns
                # pages [1+s*P, 1+(s+1)*P); page 0 stays the trash page):
                # beam reorders below gather page contents by this layout
                table = nd_array(
                    1 + _np.arange(BK * P, dtype=_np.int32).reshape(BK, P),
                    ctx=ctx, dtype="int32")
                pools = [list(kv) for kv in cache.pools]
                zero_page = nd_array(_np.zeros((1,), _np.int32), ctx=ctx,
                                     dtype="int32")
                Lp = P * ps
                row_iota = nd_array(
                    _np.broadcast_to(_np.arange(Lp, dtype=_np.float32),
                                     (BK, Lp)).copy(), ctx=ctx)
                page_off = nd_array(_np.arange(P, dtype=_np.int32)[None],
                                    ctx=ctx, dtype="int32")

            for t in range(1, max_len):
                pos_nd = nd_array(_np.array([t - 1], _np.int32), ctx=ctx,
                                  dtype="int32")
                if incremental:
                    keep = F.broadcast_lesser(
                        row_iota, nd_array(_np.array([[t]], _np.float32),
                                           ctx=ctx))
                    pages, rows = page_coords(table, pos_nd, ps)
                    caches = [PagedStepCache(kp, vp, table, pages, rows,
                                             keep)
                              for kp, vp in pools]
                    step_logits = self._decode_step(F, last_tok, pos_nd,
                                                    mem, src_keep, caches)
                    pools = [[c.k_pool, c.v_pool] for c in caches]
                else:
                    logits = self._decode_h(F, tgt, mem, src_keep)
                    # slice the one needed position on-device
                    step_logits = F.slice_axis(logits, axis=1, begin=t - 1,
                                               end=t).reshape(0, -1)
                lp = step_logits.log_softmax(axis=-1).reshape(B, K, V)
                fin3 = F.broadcast_like(finished.expand_dims(2), lp,
                                        lhs_axes=(2,), rhs_axes=(2,))
                lpf3 = F.broadcast_like(lp_fin, lp, lhs_axes=(0, 1),
                                        rhs_axes=(0, 1))
                lp = F.where(fin3, lpf3, lp)
                cand = F.broadcast_add(scores.expand_dims(2), lp)
                scores, top = F.topk(cand.reshape(B, K * V), axis=1, k=K,
                                     ret_typ="both", dtype="int32")
                # beam parent / token split of the flat top-k indices
                from ..ndarray import NDArray as _ND

                beam_idx = _ND(top._data // V, ctx=ctx)       # (B, K)
                tok = _ND((top._data % V).astype("int32"), ctx=ctx)
                if K > 1:
                    flat_parent = (b_off + beam_idx).reshape(-1)  # (BK,)
                    tgt = F.take(tgt, flat_parent, axis=0)
                    finished = F.take(finished.reshape(-1), flat_parent,
                                      axis=0).reshape(B, K)
                    if incremental:
                        # KV pages follow their beams: gather page
                        # CONTENTS over the FULL pool (tables are the
                        # static contiguous runs above; row 0 — the
                        # trash page — maps to itself)
                        idx_pages = F.concat(
                            zero_page,
                            (flat_parent.expand_dims(1) * P + page_off
                             + 1).reshape(-1), dim=0)
                        pools = [[F.take(kp, idx_pages, axis=0),
                                  F.take(vp, idx_pages, axis=0)]
                                 for kp, vp in pools]
                tok_col = tok.reshape(BK, 1)
                maskc = F.broadcast_equal(
                    col_iota, nd_array(_np.array([[t]], _np.int32), ctx=ctx,
                                       dtype="int32"))
                tgt = tgt * (1 - maskc) + tok_col * maskc
                fin_tok = F.broadcast_maximum(
                    F.broadcast_equal(tok, eos_nd),
                    F.broadcast_equal(tok, pad_nd))
                finished = F.broadcast_maximum(finished,
                                               F.cast(fin_tok, "float32"))
                last_tok = tok_col
                # early exit at sync cadence: ONE scalar readback per
                # `sync_every` steps, never per token
                if (sync_every and t % sync_every == 0
                        and t < max_len - 1
                        and float(finished.sum().asscalar()) >= BK):
                    break
            tgt_np = _np.asarray(tgt.asnumpy(), _np.int32)
            scores_np = _np.asarray(scores.asnumpy(), _np.float32)
        # GNMT length penalty: score / ((5+len)/6)^alpha
        lengths = (tgt_np.reshape(B, K, max_len) != self._pad_id).sum(-1)
        penal = ((5.0 + lengths) / 6.0) ** alpha
        best = _np.argmax(scores_np / penal, axis=1)
        out = tgt_np.reshape(B, K, max_len)[_np.arange(B), best]
        return out


def label_smoothed_ce(logits, labels, smoothing=0.1, pad_id=0):
    """Label-smoothed cross entropy over (B, T, V) logits, ignoring pad
    positions (reference: GluonNLP LabelSmoothing + SoftmaxCEMaskedLoss).
    Returns the scalar mean over non-pad tokens."""
    flat = logits.reshape(-3, 0)
    lab = labels.reshape(-1)
    logp = flat.log_softmax(axis=-1)
    nll = -logp.pick(lab, axis=-1)
    smooth = -logp.mean(axis=-1)
    loss = (1.0 - smoothing) * nll + smoothing * smooth
    keep = (lab != pad_id)
    return (loss * keep).sum() / keep.sum().maximum(1.0)


def transformer_base(vocab_size, **kwargs) -> Transformer:
    """Transformer-base (WMT14): 6 layers, 512/2048, 8 heads."""
    kwargs.setdefault("dropout", 0.1)
    return Transformer(vocab_size, units=512, hidden_size=2048, num_heads=8,
                       num_layers=6, **kwargs)


def transformer_big(vocab_size, **kwargs) -> Transformer:
    """Transformer-big (WMT14, BASELINE config 4): 6 layers, 1024/4096,
    16 heads, dropout 0.3."""
    kwargs.setdefault("dropout", 0.3)
    return Transformer(vocab_size, units=1024, hidden_size=4096,
                       num_heads=16, num_layers=6, **kwargs)
