"""Transformer building blocks (HybridBlocks).

Reference parity: GluonNLP's transformer encoder (BASELINE configs 3/4 use
BERT-base and Transformer-big built from these pieces) and the reference's
fused attention matmuls (src/operator/contrib/transformer.cc
interleaved_matmul_selfatt_* ~L1-300).

TPU-native: attention is expressed as batched matmuls + softmax that XLA
fuses and tiles onto the MXU; the qkv/out/ffn projection weights carry
tensor-parallel shardings via mxnet_tpu.parallel.sharding rules (head axis
split over the 'tp' mesh axis — collectives inserted by GSPMD).
"""
from __future__ import annotations

import math

import numpy as np

from ..base import MXNetError
from ..gluon import nn
from ..gluon.block import HybridBlock

__all__ = ["MultiHeadAttention", "PositionwiseFFN", "TransformerEncoderCell",
           "TransformerEncoder", "PositionalEmbedding"]


class MultiHeadAttention(HybridBlock):
    """Self/cross attention with fused qkv projection.

    Weight layout (3*units, in) for qkv — the head dimension is the leading
    axis so a 'tp' sharding of axis 0 splits heads across devices.
    """

    def __init__(self, units, num_heads, dropout=0.0, use_bias=True,
                 causal=False, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        if units % num_heads != 0:
            raise MXNetError(f"units {units} not divisible by heads {num_heads}")
        self._units = units
        self._num_heads = num_heads
        self._head_dim = units // num_heads
        self._dropout = dropout
        self._causal = causal
        with self.name_scope():
            self.qkv = nn.Dense(3 * units, flatten=False, use_bias=use_bias,
                                prefix="qkv_")
            self.proj = nn.Dense(units, flatten=False, use_bias=use_bias,
                                 prefix="proj_")
            self.attn_drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None):
        # x: (B, T, C)
        qkv = self.qkv(x)  # (B, T, 3C)
        q, k, v = F.split(qkv, num_outputs=3, axis=-1)

        def heads(t):
            # (B, T, C) -> (B*H, T, hd)
            t = t.reshape(0, 0, -4, self._num_heads, self._head_dim)
            t = t.transpose((0, 2, 1, 3))
            return t.reshape(-3, 0, 0)

        q, k, v = heads(q), heads(k), heads(v)
        from .. import autograd as _ag

        if mask is None and (self._dropout == 0.0 or not _ag.is_training()):
            # fused flash-attention path (Pallas on TPU); only taken when
            # attention-prob dropout is inactive, so it is numerically
            # equivalent to the dense path
            out = F._contrib_flash_attention(q, k, v, causal=self._causal)
            out = out.reshape(-4, -1, self._num_heads, 0, 0)
            out = out.transpose((0, 2, 1, 3)).reshape(0, 0, -3)
            return self.proj(out)
        scores = F.batch_dot(q, k, transpose_b=True) / math.sqrt(self._head_dim)
        if self._causal:
            T = scores.shape[-1]
            tril = F.array(np.tril(np.ones((T, T), np.float32)),
                           ctx=scores.context)
            neg = -1e9 if str(scores.dtype).find("16") < 0 else -3e4
            scores = F.broadcast_add(
                scores, (1.0 - tril).expand_dims(0) * neg)
        if mask is not None:
            # mask: (B, T, T) with 1=keep; broadcast over heads
            big_neg = -1e9 if str(scores.dtype).find("16") < 0 else -3e4
            m = mask.expand_dims(1)
            m = F.broadcast_like(m, scores.reshape(
                -4, -1, self._num_heads, 0, 0), lhs_axes=(1,), rhs_axes=(1,))
            m = m.reshape(-3, 0, 0)
            scores = F.where(m, scores, F.ones_like(scores) * big_neg)
        attn = F.softmax(scores, axis=-1)
        attn = self.attn_drop(attn)
        out = F.batch_dot(attn, v)  # (B*H, T, hd)
        out = out.reshape(-4, -1, self._num_heads, 0, 0)
        out = out.transpose((0, 2, 1, 3)).reshape(0, 0, -3)
        return self.proj(out)


class PositionwiseFFN(HybridBlock):
    def __init__(self, units, hidden_size, dropout=0.0, activation="gelu",
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        with self.name_scope():
            self.ffn_1 = nn.Dense(hidden_size, flatten=False, prefix="ffn1_")
            self.ffn_2 = nn.Dense(units, flatten=False, prefix="ffn2_")
            self.drop = nn.Dropout(dropout)
        self._activation = activation

    def hybrid_forward(self, F, x):
        h = self.ffn_1(x)
        h = (F.LeakyReLU(h, act_type="gelu") if self._activation == "gelu"
             else F.Activation(h, act_type=self._activation))
        return self.drop(self.ffn_2(h))


class TransformerEncoderCell(HybridBlock):
    """Pre/post-LN encoder layer."""

    def __init__(self, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._pre_norm = pre_norm
        with self.name_scope():
            self.attn = MultiHeadAttention(units, num_heads, dropout,
                                           prefix="attn_")
            self.ffn = PositionwiseFFN(units, hidden_size, dropout, activation,
                                       prefix="ffn_")
            self.ln1 = nn.LayerNorm(in_channels=units, prefix="ln1_")
            self.ln2 = nn.LayerNorm(in_channels=units, prefix="ln2_")
            self.drop = nn.Dropout(dropout)

    def hybrid_forward(self, F, x, mask=None):
        if self._pre_norm:
            x = x + self.drop(self.attn(self.ln1(x), mask))
            return x + self.ffn(self.ln2(x))
        x = self.ln1(x + self.drop(self.attn(x, mask)))
        return self.ln2(x + self.ffn(x))


class PositionalEmbedding(HybridBlock):
    """Learned positional embedding (BERT-style)."""

    def __init__(self, max_length, units, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._max_length = max_length
        with self.name_scope():
            self.weight = self.params.get("weight", shape=(max_length, units))

    def hybrid_forward(self, F, x, weight):
        # x: (B, T, C); add positions [0, T)
        T = x.shape[1]
        return x + F.slice_axis(weight, axis=0, begin=0, end=T).expand_dims(0)


class TransformerEncoder(HybridBlock):
    def __init__(self, num_layers, units, hidden_size, num_heads, dropout=0.0,
                 pre_norm=False, activation="gelu", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_layers = num_layers
        with self.name_scope():
            self.layers = nn.HybridSequential(prefix="")
            for i in range(num_layers):
                self.layers.add(TransformerEncoderCell(
                    units, hidden_size, num_heads, dropout, pre_norm,
                    activation, prefix=f"layer{i}_"))

    def hybrid_forward(self, F, x, mask=None):
        for cell in self.layers:
            x = cell(x, mask)
        return x
