"""BERT (BASELINE config 3 flagship: BERT-base MLM pretraining).

Reference parity: GluonNLP bert.py (BERTModel/BERTEncoder + MLM head, tied
embedding decoder).  Built from mxnet_tpu.models.transformer HybridBlocks.

Distributed story (SURVEY §2.3): data parallel over the 'dp' mesh axis and
tensor parallel over 'tp' via the sharding rules below — the Megatron
column/row split of qkv/proj/ffn weights, with GSPMD inserting the
all-reduces on ICI.
"""
from __future__ import annotations

import numpy as np

from ..gluon import nn
from ..gluon.block import HybridBlock
from ..parallel.sharding import ShardingRules
from .transformer import PositionalEmbedding, TransformerEncoder

__all__ = ["BERTModel", "BERTForMLM", "bert_base", "bert_small",
           "bert_sharding_rules"]


class BERTModel(HybridBlock):
    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, type_vocab=2,
                 dropout=0.1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._units = units
        with self.name_scope():
            self.word_embed = nn.Embedding(vocab_size, units,
                                           prefix="word_embed_")
            self.token_type_embed = nn.Embedding(type_vocab, units,
                                                 prefix="type_embed_")
            self.pos_embed = PositionalEmbedding(max_length, units,
                                                 prefix="pos_embed_")
            self.embed_ln = nn.LayerNorm(in_channels=units, prefix="embed_ln_")
            self.embed_drop = nn.Dropout(dropout)
            self.encoder = TransformerEncoder(num_layers, units, hidden_size,
                                              num_heads, dropout,
                                              activation="gelu",
                                              prefix="encoder_")
            self.pooler = nn.Dense(units, flatten=False, activation="tanh",
                                   prefix="pooler_")

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        x = self.word_embed(inputs)
        if token_types is not None:
            x = x + self.token_type_embed(token_types)
        x = self.pos_embed(x)
        x = self.embed_drop(self.embed_ln(x))
        mask = None
        if valid_length is not None:
            T = inputs.shape[1]
            steps = F.arange(0, T, ctx=inputs.context).reshape(1, -1)
            keep = F.broadcast_lesser(steps, valid_length.reshape(-1, 1))
            mask = F.batch_dot(keep.expand_dims(-1), keep.expand_dims(1))
        out = self.encoder(x, mask)
        pooled = self.pooler(F.slice_axis(out, axis=1, begin=0, end=1)
                             .reshape(0, -1))
        return out, pooled


class BERTForMLM(HybridBlock):
    """BERT with masked-LM head (decoder tied to word embedding would need
    shared-parameter plumbing; an independent decoder matches GluonNLP's
    non-tied option and keeps the vocab projection 'tp'-shardable)."""

    def __init__(self, vocab_size=30522, units=768, hidden_size=3072,
                 num_layers=12, num_heads=12, max_length=512, dropout=0.1,
                 prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._vocab_size = vocab_size
        with self.name_scope():
            self.bert = BERTModel(vocab_size, units, hidden_size, num_layers,
                                  num_heads, max_length, dropout=dropout,
                                  prefix="bert_")
            self.mlm_dense = nn.Dense(units, flatten=False, activation=None,
                                      prefix="mlm_dense_")
            self.mlm_ln = nn.LayerNorm(in_channels=units, prefix="mlm_ln_")
            self.decoder = nn.Dense(vocab_size, flatten=False,
                                    prefix="decoder_")

    def hybrid_forward(self, F, inputs, token_types=None, valid_length=None):
        seq, _ = self.bert(inputs, token_types, valid_length)
        h = self.mlm_ln(F.LeakyReLU(self.mlm_dense(seq), act_type="gelu"))
        return self.decoder(h)


def bert_sharding_rules() -> ShardingRules:
    """Megatron-style TP rules over the 'tp' mesh axis.

    Dense weights are (out, in): axis-0 split = column parallel, axis-1 =
    row parallel.  qkv and ffn1 are column-parallel; proj and ffn2 are
    row-parallel; embeddings and the MLM decoder split the vocab axis.
    """
    return ShardingRules([
        (r".*qkv_weight$", ("tp", None)),
        (r".*qkv_bias$", ("tp",)),
        (r".*proj_weight$", (None, "tp")),
        (r".*ffn1_weight$", ("tp", None)),
        (r".*ffn1_bias$", ("tp",)),
        (r".*ffn2_weight$", (None, "tp")),
        (r".*word_embed_weight$", ("tp", None)),
        (r".*decoder_weight$", ("tp", None)),
        (r".*decoder_bias$", ("tp",)),
    ])


def bert_base(vocab_size=30522, **kwargs) -> BERTForMLM:
    return BERTForMLM(vocab_size=vocab_size, units=768, hidden_size=3072,
                      num_layers=12, num_heads=12, **kwargs)


def bert_small(vocab_size=512, units=64, hidden_size=128, num_layers=2,
               num_heads=4, max_length=64, **kwargs) -> BERTForMLM:
    """Tiny config for dryruns and tests."""
    return BERTForMLM(vocab_size=vocab_size, units=units,
                      hidden_size=hidden_size, num_layers=num_layers,
                      num_heads=num_heads, max_length=max_length, **kwargs)
