"""Device-memory & compile-cost observability (docs/OBSERVABILITY.md §Memory).

PRs 2 and 5 made *time* observable (step events, spans, Perfetto traces);
this module makes *memory* and *compile cost* observable — the two inputs
the serving path (memory headroom is its binding constraint) and the AOT
executable cache / learned planner (per-executable cost records are their
feature set; *A Learned Performance Model for TPUs*, arXiv:2008.01040)
need.  Four pieces, all riding the PR 2/5 telemetry spine rather than
growing a second pipeline:

  * **sampler** — ``on_step()`` / ``on_checkpoint()`` are called at step
    boundaries and checkpoint save/load (never inside hot dispatch: the
    memory APIs below are on mxlint's hot-sync list precisely so nobody
    ever polls memory from ``_step_impl``).  Every ``MX_MEMWATCH_EVERY``
    (default 10) observations it snapshots per-device
    ``memory_stats()`` (normalized by ``context.normalize_memory_stats``)
    plus a categorized census of ``jax.live_arrays()`` and records one
    ``mem`` event with watermark tracking;
  * **category attribution** — components *weakly* register providers
    (``register(category, obj, fn)``): ``DataParallelStep`` (params /
    optimizer state), ``FusedUpdater`` (optimizer state),
    ``InflightRing`` + ``DevicePrefetchIter`` (in-flight batches and
    pending step buffers), ``AsyncCheckpointer`` (queued host snapshot
    buffers).  The census attributes each live array to its category by
    identity; everything unclaimed is ``other``.  Weak registration: a
    dropped step object must not be kept alive by the watchdog;
  * **leak detector** — a sliding window (``MX_MEMWATCH_LEAK_WINDOW``,
    default 12 samples) of census totals; strictly monotonic growth
    across the full window above a noise floor warns ONCE (re-armed when
    growth stops) naming the top-growing category, and records a
    ``mem_leak`` event;
  * **compile accounting** — every jit construction site
    (``data_parallel._build``, ``fused._jitted``, the kvstore
    ``_psum_cache``, ``CachedOp``) reports ``note_compile()``: one
    ``compile`` event per cache entry (deduped in-process) carrying
    compile wall time, a **stable executable fingerprint** (sha256 of
    structural identity — shapes/dtypes/static hypers, never object ids,
    so it survives a process restart: the key the AOT executable cache
    will use), and — where this jax exposes them — ``cost_analysis()``
    FLOPs/bytes-accessed from the (cached) retrace.  ``MX_MEMWATCH=full``
    additionally captures ``memory_analysis()`` temp/argument/output
    bytes at the cost of ONE duplicate XLA compile per executable;
  * **OOM post-mortem** — dispatch/readback paths that catch a
    RESOURCE_EXHAUSTED call ``emit_oom_report()``: one ``oom_report``
    event (last watermark, live-array census with the largest category
    named, top executables by temp/accessed bytes, in-flight depth) is
    recorded and flushed before the error re-raises, so the
    ``tools/launch.py`` supervisor can echo *why* the rank died next to
    its flight tail.

Enabled whenever the telemetry recorder is enabled; ``MX_MEMWATCH=0``
is the kill switch.  Like spans, sampling is bitwise-invisible to the
computation (asserted by ``tests/test_memwatch.py``) and the
``memwatch_overhead`` bench metric keeps the steady-state cost in the
noise floor.  ``tools/mem_report.py`` is the offline consumer;
``telemetry.export_prometheus`` exposes ``mx_mem_*`` gauges and
``export_chrome_trace`` renders ``mem`` events as per-rank counter
tracks under the span timeline.
"""
from __future__ import annotations

import hashlib
import logging
import os
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from . import telemetry
from .context import normalize_memory_stats

__all__ = ["enabled", "register", "census", "device_memory", "sample",
           "on_step", "on_checkpoint", "fingerprint", "note_compile",
           "shape_structs", "emit_oom_report", "is_resource_exhausted",
           "peak_bytes", "summary", "reset"]

_LOG = logging.getLogger("mxnet_tpu.memwatch")

_DEFAULT_EVERY = 10
_DEFAULT_LEAK_WINDOW = 12
# leak floor: total live bytes must grow by at least this much across the
# whole window before the monotonic trend is worth a warning — strictly
# increasing growth of a few KB is allocator jitter, not a leak
_LEAK_MIN_GROWTH = 1 << 16
# bounded registry of compile records (oom_report's "top executables" and
# summary() read it; mem_report reads the events instead)
_COMPILE_RECORDS_MAX = 512


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


def enabled() -> bool:
    """Memwatch rides the telemetry recorder: on whenever telemetry is on,
    unless ``MX_MEMWATCH=0`` kills it.  (``MX_MEMWATCH=full`` additionally
    enables the duplicate-compile ``memory_analysis()`` capture.)"""
    if not telemetry.enabled():
        return False
    return os.environ.get("MX_MEMWATCH", "1").lower() not in (
        "0", "false", "off")


def _full_analysis() -> bool:
    return os.environ.get("MX_MEMWATCH", "").lower() == "full"


def _every() -> int:
    return max(1, _env_int("MX_MEMWATCH_EVERY", _DEFAULT_EVERY))


def _leak_window() -> int:
    return max(2, _env_int("MX_MEMWATCH_LEAK_WINDOW", _DEFAULT_LEAK_WINDOW))


# ---------------------------------------------------------------------------
# state
# ---------------------------------------------------------------------------
class _State:
    def __init__(self):
        self.lock = threading.Lock()
        self.step_calls = 0
        self.samples = 0
        self.watermark = 0            # max observed device/live bytes
        self.window: List[Tuple[int, Dict[str, int]]] = []
        self.leak_active = False
        self.leak_category: Optional[str] = None
        self.leak_events = 0
        self.last_categories: Dict[str, int] = {}
        self.compile_seen: set = set()
        self.compiles: List[dict] = []
        self.compile_ms = 0.0
        self.compile_cache_hits = 0
        self.oom_reported = False


_state = _State()

# providers survive reset(): registration happens at object construction,
# and tests resetting aggregates must not blind the census to still-live
# steps/rings (dead weakrefs are pruned at census time)
_providers: List[Tuple[str, "weakref.ref", Callable]] = []
_providers_lock = threading.Lock()
# amortized dead-ref pruning for processes that never sample (telemetry
# off): register() prunes whenever the list doubles past this watermark,
# so churning short-lived steps/rings can't grow the registry forever
_providers_prune_at = 64


def reset() -> None:
    """Drop aggregates/window/compile registry (tests).  Registered
    providers are kept — their objects are still alive."""
    global _state
    _state = _State()


# ---------------------------------------------------------------------------
# category registration + census
# ---------------------------------------------------------------------------
def register(category: str, obj: Any, fn: Callable[[Any], Any]) -> None:
    """Weakly register ``fn(obj) -> iterable of arrays`` as the provider
    of ``category``'s live arrays.  ``fn`` runs at *sample* time (step
    boundaries, never hot dispatch) and may return jax arrays, NDArrays
    (their ``._data`` is used), or numpy arrays (counted as host bytes —
    e.g. queued checkpoint snapshots).  The registry holds only a weakref
    to ``obj``: dropping the object retires its provider."""
    global _providers_prune_at
    with _providers_lock:
        _providers.append((category, weakref.ref(obj), fn))
        if len(_providers) >= _providers_prune_at:
            # amortized O(1): census() also prunes, but a telemetry-off
            # process never runs a census and must still stay bounded
            _providers[:] = [(c, r, f) for c, r, f in _providers
                             if r() is not None]
            _providers_prune_at = max(64, 2 * len(_providers))


def _live_providers():
    with _providers_lock:
        alive = [(c, r, f) for c, r, f in _providers if r() is not None]
        _providers[:] = alive
        return list(alive)


def census() -> dict:
    """Categorized census of ``jax.live_arrays()``:
    ``{"total_bytes", "live_count", "categories": {cat: {count, nbytes}},
    "host_bytes": {cat: bytes}}``.  Attribution is by array identity
    against the registered providers; unclaimed arrays are ``other``.
    Never call this from a per-step dispatch body (mxlint hot-sync)."""
    import jax

    cat_of: Dict[int, str] = {}
    host_bytes: Dict[str, int] = {}
    for category, ref, fn in _live_providers():
        obj = ref()
        if obj is None:
            continue
        try:
            arrs = fn(obj)
        except Exception:  # a torn-down provider must not kill sampling
            continue
        for a in arrs or ():
            if a is None:
                continue
            data = getattr(a, "_data", a)  # NDArray -> backing jax array
            if isinstance(data, np.ndarray):
                host_bytes[category] = (host_bytes.get(category, 0)
                                        + int(data.nbytes))
            else:
                cat_of[id(data)] = category
    categories: Dict[str, Dict[str, int]] = {}
    total = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            nb = int(arr.nbytes)
        except Exception:
            continue
        cat = cat_of.get(id(arr), "other")
        row = categories.setdefault(cat, {"count": 0, "nbytes": 0})
        row["count"] += 1
        row["nbytes"] += nb
        total += nb
        count += 1
    return {"total_bytes": total, "live_count": count,
            "categories": categories, "host_bytes": host_bytes}


def device_memory() -> dict:
    """Aggregated normalized ``memory_stats()`` over the local devices:
    ``{"available", "bytes_in_use", "peak_bytes_in_use", "bytes_limit",
    "devices": n}``.  ``available=False`` on backends without allocator
    stats (XLA:CPU) — callers fall back to the live-array census."""
    out = {"available": False, "bytes_in_use": 0, "peak_bytes_in_use": 0,
           "bytes_limit": 0, "devices": 0}
    try:
        import jax

        devices = jax.local_devices()
    except Exception:
        return out
    for d in devices:
        stats_fn = getattr(d, "memory_stats", None)
        raw = None
        if stats_fn is not None:
            try:
                raw = stats_fn()
            except Exception:
                raw = None
        norm = normalize_memory_stats(raw)
        out["devices"] += 1
        if norm["available"]:
            out["available"] = True
            out["bytes_in_use"] += norm["bytes_in_use"]
            out["peak_bytes_in_use"] += norm["peak_bytes_in_use"]
            out["bytes_limit"] += norm["bytes_limit"]
    return out


# ---------------------------------------------------------------------------
# sampling + leak detection
# ---------------------------------------------------------------------------
def on_step(step: Optional[int] = None) -> None:
    """Step-boundary observation.  Samples every ``MX_MEMWATCH_EVERY``-th
    call; the off-cadence cost is one counter increment.  Called from the
    ``DataParallelStep.step``/``Trainer.step`` wrappers and
    ``AsyncCheckpointer.step`` — boundaries, never inside ``_step_impl``."""
    if not enabled():
        return
    with _state.lock:
        _state.step_calls += 1
        due = _state.step_calls % _every() == 0
    if due:
        sample("step", step=step)


def on_checkpoint(event: str, step: Optional[int] = None) -> None:
    """Checkpoint save/load boundary — always samples (rare, and the
    moment checkpoint buffers are actually resident)."""
    if not enabled():
        return
    sample(f"checkpoint_{event}", step=step)


def sample(site: str, step: Optional[int] = None) -> Optional[dict]:
    """Take one memory sample now: census + device stats -> one ``mem``
    telemetry event; feeds the watermark and the leak window.  Returns
    the event fields (None when disabled)."""
    if not enabled():
        return None
    try:
        c = census()
    except Exception as e:  # the watchdog must never kill training
        _LOG.warning("memwatch census failed: %s", e)
        return None
    dev = device_memory()
    in_use = dev["bytes_in_use"] if dev["available"] else c["total_bytes"]
    leak = None
    with _state.lock:
        _state.samples += 1
        _state.watermark = max(_state.watermark, in_use, c["total_bytes"])
        watermark = _state.watermark
        _state.last_categories = {
            cat: row["nbytes"] for cat, row in c["categories"].items()}
        win = _state.window
        win.append((c["total_bytes"],
                    dict(_state.last_categories)))
        w = _leak_window()
        if len(win) > w:
            del win[:-w]
        if len(win) == w:
            totals = [t for t, _cats in win]
            growing = all(b > a for a, b in zip(totals, totals[1:]))
            growth = totals[-1] - totals[0]
            if growing and growth > _LEAK_MIN_GROWTH:
                if not _state.leak_active:
                    _state.leak_active = True
                    _state.leak_events += 1
                    first_cats, last_cats = win[0][1], win[-1][1]
                    deltas = {cat: last_cats.get(cat, 0)
                              - first_cats.get(cat, 0)
                              for cat in set(first_cats) | set(last_cats)}
                    top = max(deltas, key=deltas.get) if deltas else "other"
                    _state.leak_category = top
                    leak = {"category": top, "growth_bytes": growth,
                            "window": w,
                            "category_growth_bytes": deltas.get(top, 0)}
            else:
                # growth stopped: re-arm so a later real leak warns again
                _state.leak_active = False
    ev: Dict[str, Any] = {
        "site": site,
        "live_bytes": c["total_bytes"],
        "live_count": c["live_count"],
        "watermark_bytes": watermark,
        "categories": c["categories"],
    }
    if step is not None:
        ev["step"] = int(step)
    if dev["available"]:
        ev["bytes_in_use"] = dev["bytes_in_use"]
        ev["peak_bytes_in_use"] = dev["peak_bytes_in_use"]
        ev["bytes_limit"] = dev["bytes_limit"]
    if c["host_bytes"]:
        ev["host_bytes"] = c["host_bytes"]
    telemetry.record("mem", **ev)
    if leak is not None:
        _LOG.warning(
            "memwatch: live device memory grew monotonically across the "
            "last %d samples (+%d bytes); top-growing category: %s "
            "(+%d bytes).  If this trend continues the run will hit "
            "RESOURCE_EXHAUSTED — check for accumulating references "
            "(un-drained AsyncLoss handles, growing python-side caches).",
            leak["window"], leak["growth_bytes"], leak["category"],
            leak["category_growth_bytes"])
        telemetry.record("mem_leak", total_bytes=c["total_bytes"], **leak)
    return ev


def peak_bytes() -> int:
    """Best-effort process peak device bytes: PjRt's summed
    ``peak_bytes_in_use`` where the backend exposes it, else the
    watchdog's live-array watermark (refreshed from a census total here,
    so the profiler's ``profile_memory`` plumb works even between
    samples).  Blocking-context callers only (mx.profiler.timed_call)."""
    dev = device_memory()
    if dev["available"]:
        with _state.lock:
            _state.watermark = max(_state.watermark,
                                   dev["peak_bytes_in_use"])
            return _state.watermark
    try:
        import jax

        total = sum(int(a.nbytes) for a in jax.live_arrays())
    except Exception:
        total = 0
    with _state.lock:
        _state.watermark = max(_state.watermark, total)
        return _state.watermark


# ---------------------------------------------------------------------------
# compiled-executable accounting
# ---------------------------------------------------------------------------
def fingerprint(parts: Any) -> str:
    """Stable executable fingerprint: sha256 over the repr of structural
    identity (optimizer/static hypers/shapes/dtypes) — deliberately no
    object ids or memory addresses, so the same program in a restarted
    process maps to the same fingerprint (the AOT-cache key contract,
    asserted by tests/test_memwatch.py)."""
    return hashlib.sha256(repr(parts).encode()).hexdigest()[:16]


def shape_structs(tree):
    """ShapeDtypeStruct mirror of a pytree of arrays (shardings kept
    where present): host metadata only, so a jit site can hand
    ``note_compile`` enough to retrace for analysis WITHOUT pinning the
    real parameter/batch buffers past the step that placed them."""
    import jax

    def one(a):
        try:
            return jax.ShapeDtypeStruct(
                np.shape(a), a.dtype, sharding=getattr(a, "sharding", None))
        except Exception:
            return a

    return jax.tree_util.tree_map(one, tree)


def _tree_bytes(tree) -> int:
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        try:
            total += int(np.prod(shape)) * np.dtype(dtype).itemsize
        except Exception:
            continue
    return total


def _analyze(jitted, args) -> dict:
    """Best-effort cost/memory analysis of one executable.  The retrace
    behind ``jitted.lower(*args)`` is cached after the real call (sub-ms);
    ``cost_analysis()`` is an HLO-level pass (no XLA compile).  Only
    ``MX_MEMWATCH=full`` pays the duplicate XLA compile that
    ``memory_analysis()`` (temp bytes) requires."""
    out: Dict[str, Any] = {}
    try:
        out["arg_bytes"] = _tree_bytes(args)
    except Exception:
        # analysis fields are best-effort garnish on the compile event
        pass
    try:
        import jax

        out_struct = jax.eval_shape(jitted, *args)
        out["out_bytes"] = _tree_bytes(out_struct)
    except Exception:
        # ragged call signatures (vjp-wrapped, scope-dependent lowering)
        # simply lose the out-bytes field
        pass
    try:
        lowered = jitted.lower(*args)
    except Exception:
        return out
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if ca.get("flops") is not None:
                out["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                out["bytes_accessed"] = float(ca["bytes accessed"])
    except Exception:
        # cost_analysis availability varies per jax/backend — optional
        pass
    if _full_analysis():
        try:
            ma = lowered.compile().memory_analysis()
            out["temp_bytes"] = int(ma.temp_size_in_bytes)
            out["arg_bytes"] = int(ma.argument_size_in_bytes)
            out["out_bytes"] = int(ma.output_size_in_bytes)
            out["generated_code_bytes"] = int(
                ma.generated_code_size_in_bytes)
        except Exception:
            # MX_MEMWATCH=full is explicitly best-effort (duplicate
            # compile may be unsupported for this program)
            pass
    return out


def note_compile(executor: str, parts: Any, wall_s: float, site: str = "",
                 jitted=None, args=None, **extra) -> Optional[str]:
    """Report one jit-site compilation.  Emits exactly ONE ``compile``
    event per (executor, fingerprint) — a steady-state step re-calling
    the cached executable never re-emits — carrying the compile wall
    (the traced first call's wall, per the record_step convention) and
    whatever analysis this jax exposes.  AOT-cache facts ride in
    ``extra``: ``cache_hit=True`` + ``deserialize_ms`` mark an
    executable loaded from the persistent cache (mxnet_tpu.aot_cache)
    instead of compiled — tools/mem_report.py's executable table shows
    them so a post-mortem distinguishes "loaded in 0.2s" from "compiled
    in 40s".  Returns the fingerprint (None when the watchdog is off —
    ``MX_MEMWATCH=0`` kills compile accounting, including the analysis
    retrace, along with sampling)."""
    if not enabled():
        return None
    fp = fingerprint(parts)
    with _state.lock:
        key = (executor, fp)
        if key in _state.compile_seen:
            return fp
        _state.compile_seen.add(key)
    ev: Dict[str, Any] = {"executor": executor, "fingerprint": fp,
                          "site": site, "wall_ms": round(wall_s * 1e3, 3)}
    ev.update(extra)
    if jitted is not None and args is not None:
        try:
            ev.update(_analyze(jitted, args))
        except Exception:  # analysis is garnish; the event is the record
            pass
    with _state.lock:
        _state.compile_ms += wall_s * 1e3
        if ev.get("cache_hit"):
            _state.compile_cache_hits += 1
        _state.compiles.append(dict(ev))
        if len(_state.compiles) > _COMPILE_RECORDS_MAX:
            del _state.compiles[:-_COMPILE_RECORDS_MAX]
    telemetry.record("compile", **ev)
    return fp


# ---------------------------------------------------------------------------
# OOM post-mortem
# ---------------------------------------------------------------------------
def is_resource_exhausted(exc: BaseException) -> bool:
    """Does this exception look like a device OOM?  Matches the
    RESOURCE_EXHAUSTED status text PjRt puts in XlaRuntimeError — and the
    synthetic ``oom:step=N`` fault (mxnet_tpu.fault), which spells it the
    same way so the post-mortem path is testable without real HBM
    exhaustion."""
    return "RESOURCE_EXHAUSTED" in str(exc)


def emit_oom_report(executor: str = "", step: Optional[int] = None,
                    inflight_depth: Optional[int] = None) -> None:
    """Record and FLUSH one ``oom_report`` event: last watermark, a fresh
    live-array census with the largest category named, the top
    executables by temp/accessed bytes, and the in-flight window depth —
    everything the supervisor needs to say *why* the rank died.  One per
    process (an OOM storm across the in-flight window is one fact);
    exception-safe: the report must never mask the original error.
    ``MX_MEMWATCH=0`` suppresses it (the census is exactly what that
    switch turns off) — the RESOURCE_EXHAUSTED itself still propagates
    normally."""
    try:
        if not enabled():
            return
        with _state.lock:
            if _state.oom_reported:
                return
            _state.oom_reported = True
            watermark = _state.watermark
            compiles = list(_state.compiles)
        try:
            c = census()
        except Exception:
            c = {"total_bytes": 0, "live_count": 0, "categories": {},
                 "host_bytes": {}}
        cats = {cat: row["nbytes"] for cat, row in c["categories"].items()}
        largest = max(cats, key=cats.get) if cats else None

        def _weight(rec):
            return rec.get("temp_bytes",
                           rec.get("bytes_accessed",
                                   rec.get("arg_bytes", 0)))

        top = sorted(compiles, key=_weight, reverse=True)[:3]
        ev: Dict[str, Any] = {
            "executor": executor,
            "watermark_bytes": max(watermark, c["total_bytes"]),
            "live_bytes": c["total_bytes"],
            "live_count": c["live_count"],
            "categories": cats,
            "largest_category": largest,
            "top_executables": [
                {"executor": r.get("executor"),
                 "fingerprint": r.get("fingerprint"),
                 "temp_bytes": r.get("temp_bytes"),
                 "bytes_accessed": r.get("bytes_accessed"),
                 "arg_bytes": r.get("arg_bytes")}
                for r in top],
        }
        if step is not None:
            ev["step"] = int(step)
        if inflight_depth is not None:
            ev["inflight_depth"] = int(inflight_depth)
        dev = device_memory()
        if dev["available"]:
            ev["bytes_in_use"] = dev["bytes_in_use"]
            ev["bytes_limit"] = dev["bytes_limit"]
        telemetry.record("oom_report", **ev)
        # the process is about to die on the re-raise: do not trust the
        # flusher thread's cadence (or atexit, under a supervisor's
        # SIGKILL escalation) to land the post-mortem on disk
        telemetry.flush()
    except Exception:
        # the post-mortem must never mask the original RESOURCE_EXHAUSTED
        pass


# ---------------------------------------------------------------------------
# rollup
# ---------------------------------------------------------------------------
def summary() -> dict:
    """JSON-serializable rollup (export_prometheus derives the
    ``mx_mem_*`` gauges from this)."""
    with _state.lock:
        return {
            "enabled": enabled(),
            "samples": _state.samples,
            "watermark_bytes": _state.watermark,
            "categories": dict(_state.last_categories),
            "leak": {"active": _state.leak_active,
                     "category": _state.leak_category,
                     "events": _state.leak_events},
            "compiles": {"count": len(_state.compile_seen),
                         "wall_ms": round(_state.compile_ms, 3),
                         "cache_hits": _state.compile_cache_hits},
            "oom_reported": _state.oom_reported,
        }
