"""Autograd: imperative differentiation on an immutable-array runtime.

Reference parity: python/mxnet/autograd.py (record/pause scopes ~L80,
backward ~L250, grad ~L350, Function) over src/imperative/imperative.cc
(Imperative::RecordOp ~L200, Imperative::Backward ~L300).

Design (TPU-native): the reference builds an nnvm graph of executed ops and
runs a Gradient pass.  Here every executed op is recorded as a tape node
holding the ``jax.vjp`` pullback captured at execution time — capturing the
pullback *is* the forward execution, so recording costs one forward, exactly
like the reference (residuals kept, no recompute at backward).  Because jax
arrays are immutable, a tape node's saved inputs can never be clobbered by
later in-place NDArray mutation (which swaps buffers) — the correctness
problem MXNet solves with version counters disappears by construction.

Gradient flow is keyed on the *identity* of the underlying jax arrays.
"""
from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from .base import MXNetError

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "mark_variables",
    "backward",
    "grad",
    "Function",
]


class _TapeNode:
    __slots__ = ("vjp_fn", "input_ids", "input_arrays", "output_ids",
                 "outputs", "fwd_fn")

    def __init__(self, vjp_fn, inputs, outputs, fwd_fn=None):
        self.vjp_fn = vjp_fn
        self.input_arrays = list(inputs)
        self.input_ids = [id(a) for a in inputs]
        self.outputs = list(outputs)
        self.output_ids = [id(o) for o in outputs]
        # pure forward fn(*input_arrays) -> outputs; kept so create_graph
        # backward can re-linearize the op differentiably (higher-order)
        self.fwd_fn = fwd_fn


class _RowSparseCT:
    """A row-sparse cotangent: rows `indices` of a (vocab, dim) gradient
    hold `values`; all other rows are zero.  Produced by ops recorded with
    sparse_grad=True (Embedding) so huge vocab gradients are never
    materialized densely on the tape (reference: row_sparse gradients,
    src/operator/tensor/indexing_op.h EmbeddingOpBackward)."""

    __slots__ = ("indices", "values", "shape")

    def __init__(self, indices, values, shape):
        self.indices = indices  # (nnz,) int
        self.values = values    # (nnz, *row_shape)
        self.shape = tuple(shape)

    def concat(self, other: "_RowSparseCT") -> "_RowSparseCT":
        import jax.numpy as jnp

        return _RowSparseCT(
            jnp.concatenate([self.indices, other.indices]),
            jnp.concatenate([self.values, other.values]), self.shape)

    def densify(self):
        import jax.numpy as jnp

        out = jnp.zeros(self.shape, self.values.dtype)
        return out.at[self.indices].add(self.values)

    def aggregated(self):
        """(unique_sorted_indices, summed_values) — true dynamic row count
        via the shared eager aggregation (sparse.aggregate_rows)."""
        from .ndarray.sparse import aggregate_rows

        return aggregate_rows(self.indices, self.values)


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape: List[_TapeNode] = []
        # id(jax array) -> weakref to the NDArray whose .grad should receive it
        self.leaves: Dict[int, Any] = {}


_state = _State()


# ---------------------------------------------------------------------------
# scopes
# ---------------------------------------------------------------------------
class _RecordingScope:
    def __init__(self, recording: Optional[bool], training: Optional[bool]):
        self._rec, self._train = recording, training
        self._prev = None

    def __enter__(self):
        self._prev = (_state.recording, _state.training)
        if self._rec is True and not _state.recording:
            # Entering a fresh outermost record scope: drop any stale graph
            # from a prior forward that never ran backward (MXNet drops the
            # recorded graph when a new recording starts).
            _state.tape = []
            _state.leaves = {}
        if self._rec is not None:
            _state.recording = self._rec
        if self._train is not None:
            _state.training = self._train
        return self

    def __exit__(self, *exc):
        _state.recording, _state.training = self._prev
        return False


def record(train_mode: bool = True):
    """Scope in which executed ops are recorded for backward()."""
    return _RecordingScope(True, train_mode)


def pause(train_mode: bool = False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


def is_recording() -> bool:
    return _state.recording


def is_training() -> bool:
    return _state.training


def set_recording(flag: bool) -> bool:
    prev = _state.recording
    _state.recording = flag
    return prev


def set_training(flag: bool) -> bool:
    prev = _state.training
    _state.training = flag
    return prev


# ---------------------------------------------------------------------------
# tape construction (called from ops.registry on every eager op)
# ---------------------------------------------------------------------------
def record_node(vjp_fn, inputs, outputs, input_nds=None, fwd_fn=None) -> None:
    _state.tape.append(_TapeNode(vjp_fn, inputs, outputs, fwd_fn=fwd_fn))
    if input_nds:
        for nd in input_nds:
            register_leaf(nd)


def register_leaf(nd) -> None:
    """If `nd` has an attached grad buffer, remember the data object identity
    under which it entered the graph (mutation swaps buffers, so identity at
    use-time is the correct key)."""
    if getattr(nd, "_grad", None) is not None:
        _state.leaves[id(nd._data)] = weakref.ref(nd)


def mark_variables(variables, gradients, grad_reqs="write") -> None:
    """Reference: autograd.mark_variables — associate arrays with grad buffers."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, g, req in zip(variables, gradients, grad_reqs):
        var._grad = g if req != "null" else None
        var._grad_req = req
        if var._grad is not None:
            _state.leaves[id(var._data)] = weakref.ref(var)


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------
def _is_float0(arr) -> bool:
    import jax

    return getattr(arr, "dtype", None) == jax.dtypes.float0


def _walk_tape(head_pairs, retain_graph=False):
    """Reverse-walk the tape accumulating cotangents.

    head_pairs: list of (jax array, cotangent jax array).
    Returns dict id(array) -> accumulated cotangent.
    """
    import jax.numpy as jnp

    grads: Dict[int, Any] = {}
    keep: Dict[int, Any] = {}  # strong refs so id() keys stay unique/alive
    for arr, ct in head_pairs:
        grads[id(arr)] = ct
        keep[id(arr)] = arr

    tape = _state.tape
    for node in reversed(tape):
        if not any(oid in grads for oid in node.output_ids):
            continue
        cts = []
        for out, oid in zip(node.outputs, node.output_ids):
            g = grads.get(oid)
            if g is None:
                g = jnp.zeros_like(out)
            elif isinstance(g, _RowSparseCT):
                # propagating a sparse cotangent THROUGH another op's vjp
                # needs the dense form (rare: the sparse-grad producer's
                # input is normally a leaf parameter)
                g = g.densify()
            cts.append(g)
        in_grads = node.vjp_fn(tuple(cts) if len(cts) > 1 else cts[0])
        for arr, aid, g in zip(node.input_arrays, node.input_ids, in_grads):
            if g is None or _is_float0(g):
                continue
            prev = grads.get(aid)
            if prev is None:
                grads[aid] = g
                keep[aid] = arr
            elif isinstance(prev, _RowSparseCT) and isinstance(g, _RowSparseCT):
                grads[aid] = prev.concat(g)
            elif isinstance(prev, _RowSparseCT):
                grads[aid] = prev.densify() + g
            elif isinstance(g, _RowSparseCT):
                grads[aid] = prev + g.densify()
            else:
                grads[aid] = prev + g
    if not retain_graph:
        _state.tape = []
    return grads


def _walk_tape_create_graph(head_pairs):
    """Create-graph reverse walk: every vjp application and cotangent
    accumulation is itself RECORDED on the tape (by re-linearizing each
    node's stored forward with jax.vjp), so the returned gradients support
    further backward passes — arbitrary-order eager gradients
    (reference: Imperative::Backward create_graph=True path).
    """
    import jax
    import jax.numpy as jnp

    grads: Dict[int, Any] = {}
    keep: Dict[int, Any] = {}
    for arr, ct in head_pairs:
        grads[id(arr)] = ct
        keep[id(arr)] = arr

    snapshot = list(_state.tape)
    for node in reversed(snapshot):
        if not any(oid in grads for oid in node.output_ids):
            continue
        if node.fwd_fn is None:
            raise MXNetError(
                "create_graph=True: a recorded op without a re-linearizable "
                "forward (a custom autograd.Function or a sparse-grad "
                "Embedding) is on the gradient path; higher-order gradients "
                "are unavailable through it")
        cts = []
        for out, oid in zip(node.outputs, node.output_ids):
            g = grads.get(oid)
            if g is None:
                g = jnp.zeros_like(out)
            elif isinstance(g, _RowSparseCT):
                g = g.densify()
            cts.append(g)
        n_in = len(node.input_arrays)
        fwd = node.fwd_fn

        def g_fn(*args, _fwd=fwd, _n=n_in):
            xs, cs = args[:_n], args[_n:]
            out, vjp = jax.vjp(_fwd, *xs)
            # cotangent tree must match _fwd's output tree exactly
            ct = tuple(cs) if isinstance(out, (tuple, list)) else cs[0]
            return vjp(ct)

        all_in = list(node.input_arrays) + cts
        in_grads, vjp2 = jax.vjp(g_fn, *all_in)
        # _walk_tape hands single-output nodes a bare array; vjp2 expects
        # g_fn's output tree (a tuple) — adapt when arities differ
        if len(in_grads) == 1:
            rec_vjp = (lambda ct, _v=vjp2: _v((ct,)))
        else:
            rec_vjp = vjp2
        record_node(rec_vjp, all_in, list(in_grads), fwd_fn=g_fn)
        for arr, aid, g in zip(node.input_arrays, node.input_ids, in_grads):
            if g is None or _is_float0(g):
                continue
            prev = grads.get(aid)
            if prev is None:
                grads[aid] = g
                keep[aid] = arr
            else:
                if isinstance(prev, _RowSparseCT):
                    prev = prev.densify()
                s = prev + g
                record_node(lambda ct: (ct, ct), [prev, g], [s],
                            fwd_fn=lambda a, b: a + b)
                grads[aid] = s
                keep[aid] = arr
    return grads


def backward(heads, head_grads=None, retain_graph=False, train_mode=True) -> None:
    """Compute gradients of `heads` w.r.t. all attach_grad()-ed arrays on the
    tape, writing into their .grad buffers per grad_req ('write'|'add').

    Reference: MXAutogradBackwardEx -> Imperative::Backward (~L300).
    """
    from .ndarray import NDArray

    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    import jax.numpy as jnp

    pairs = []
    for h, hg in zip(heads, head_grads):
        ct = hg._data if hg is not None else jnp.ones_like(h._data)
        pairs.append((h._data, ct))

    grads = _walk_tape(pairs, retain_graph=retain_graph)

    leaves, _state.leaves = _state.leaves, {}
    for aid, ref in leaves.items():
        nd = ref()
        if nd is None or nd._grad is None:
            continue
        g = grads.get(aid)
        if g is None:
            continue
        from .ndarray.sparse import RowSparseNDArray

        buf = nd._grad
        if isinstance(g, _RowSparseCT) and isinstance(buf, RowSparseNDArray):
            uids, vals = g.aggregated()
            if nd._grad_req == "add" and buf._data.shape[0]:
                merged = _RowSparseCT(
                    jnp.concatenate([buf._aux["indices"], uids]),
                    jnp.concatenate([buf._data,
                                     vals.astype(buf._data.dtype)]), g.shape)
                uids, vals = merged.aggregated()
            buf._set_sparse_components(vals.astype(buf._data.dtype), uids)
            continue
        if isinstance(g, _RowSparseCT):
            g = g.densify()
        if isinstance(buf, RowSparseNDArray):
            # dense cotangent into a sparse buffer: every row is touched
            g = g.astype(buf._data.dtype)
            if nd._grad_req == "add" and buf._data.shape[0]:
                g = g + buf.todense()._data
            idx = jnp.arange(g.shape[0])
            buf._set_sparse_components(g, idx)
            continue
        if nd._grad_req == "add":
            buf._set_data(buf._data + g.astype(buf._data.dtype))
        else:
            buf._set_data(g.astype(buf._data.dtype))
    if retain_graph:
        _state.leaves = leaves


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients of heads w.r.t. variables (reference: autograd.grad ~L350).

    ``create_graph=True`` records the gradient computation itself on the
    tape, so the returned arrays support further ``backward()``/``grad()``
    calls — higher-order eager derivatives (implies retain_graph).
    """
    from .ndarray import NDArray

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if isinstance(heads, NDArray):
        heads = [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif isinstance(head_grads, NDArray):
        head_grads = [head_grads]

    import jax.numpy as jnp

    pairs = []
    for h, hg in zip(heads, head_grads):
        ct = hg._data if hg is not None else jnp.ones_like(h._data)
        pairs.append((h._data, ct))
    if create_graph:
        grads = _walk_tape_create_graph(pairs)
    else:
        grads = _walk_tape(pairs, retain_graph=bool(retain_graph))

    out = []
    for v in variables:
        g = grads.get(id(v._data))
        if g is None:
            raise MXNetError(
                "one of the variables is not part of the recorded graph"
            )
        if isinstance(g, _RowSparseCT):
            from .ndarray.sparse import RowSparseNDArray

            uids, vals = g.aggregated()
            out.append(RowSparseNDArray(vals, {"indices": uids}, g.shape,
                                        ctx=v.context))
        else:
            out.append(NDArray(g, ctx=v.context))
    return out[0] if single else out


# ---------------------------------------------------------------------------
# custom Function
# ---------------------------------------------------------------------------
class Function:
    """User-defined differentiable function (reference: autograd.Function ~L350).

    Subclass and implement ``forward(self, *inputs)`` and
    ``backward(self, *output_grads)`` in terms of NDArrays; call the instance.
    """

    def __init__(self):
        self._saved = ()

    def save_for_backward(self, *arrays):
        self._saved = arrays

    @property
    def saved_tensors(self):
        return self._saved

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def __call__(self, *inputs):
        from .ndarray import NDArray

        with pause(train_mode=is_training()):
            outputs = self.forward(*inputs)
        single_out = isinstance(outputs, NDArray)
        outs = [outputs] if single_out else list(outputs)

        if is_recording():
            func = self
            in_arrays = [x._data for x in inputs]
            out_arrays = [o._data for o in outs]
            ctx = inputs[0].context if inputs else None

            def vjp_fn(cotangents):
                cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
                ct_nds = [NDArray(c, ctx=ctx) for c in cts]
                with pause(train_mode=is_training()):
                    in_grads = func.backward(*ct_nds)
                if isinstance(in_grads, NDArray):
                    in_grads = [in_grads]
                return [g._data if g is not None else None for g in in_grads]

            record_node(vjp_fn, in_arrays, out_arrays, input_nds=inputs)
        return outputs
