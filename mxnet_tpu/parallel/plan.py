"""The unified parallelism ``Plan``: every strategy in this package as DATA.

Before this module, ``parallel/`` was a zoo of five hand-wired strategies —
dp (``data_parallel.py`` over a ``local_mesh``), tp (``sharding.py``
``ShardingRules``), pipeline (``pipeline.py`` + ``pp_microbatches``), ring
and Ulysses sequence parallelism (``ring.py``/``ulysses.py`` behind
``ring_attention=``) — each selected by a different constructor knob, each
growing its own gating logic inside ``DataParallelStep``.  A ``Plan``
captures everything those knobs expressed as one serializable value:

    mesh axis names/sizes  +  per-param PartitionSpec rules
    +  per-input batch/sequence axes  +  the SP attention mechanism
    +  pipeline microbatching  +  gradient-accumulation microbatching

``compile_step_with_plan`` (data_parallel.py) consumes ANY Plan through
the one dispatch body, so superstep scan, AOT caching, async in-flight,
telemetry spans and elastic resharding are written once, not five times.
The legacy strategy entry points remain as thin shims that BUILD the
equivalent Plan (``dp_plan``/``tensor_parallel_plan``/``pipeline_plan``/
``ring_plan``/``ulysses_plan`` here, re-exported by their home modules),
and ``parallel/planner.py`` chooses a Plan analytically from model shape
and mesh (docs/PERFORMANCE.md §Plan & planner).

Serialization: ``to_json``/``from_json`` round-trip losslessly —
``DataParallelStep.layout()`` embeds the Plan in the checkpoint
``meta.json`` ``layout`` block, so an elastic restore knows not just
WHERE each shard lived but WHICH strategy produced that placement
(docs/FAULT_TOLERANCE.md §Elastic resize).
"""
from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Sequence, Tuple

from ..base import MXNetError
from ..precision.config import PrecisionConfig
from .sharding import ShardingRules

__all__ = ["Plan", "dp_plan", "tensor_parallel_plan", "pipeline_plan",
           "ring_plan", "ulysses_plan", "STRATEGY_NAMES"]

# canonical mesh axis order (mesh.make_mesh): tp innermost = adjacent on
# the ICI ring, the bandwidth-optimal layout for TP collectives
_AXIS_ORDER = ("dp", "pp", "sp", "tp", "ep")

# MX_PLAN / shim strategy vocabulary (planner.plan_for resolves these)
STRATEGY_NAMES = ("auto", "dp", "tp", "pp", "sp", "ring", "ulysses")

# sequence-parallel attention mechanisms: 'gspmd' lets the compiler
# insert the K/V collectives, 'ring'/'ulysses' route fused-attention ops
# through the hand-written kernels (parallel/ring.py, parallel/ulysses.py)
_SP_MODES = ("gspmd", "ring", "ulysses")


@dataclass(frozen=True)
class Plan:
    """One parallelism layout, strategy-agnostic and serializable.

    ``mesh_axes``: ordered (name, size) pairs; the product is the device
    count the plan targets.  ``rules``: per-param PartitionSpec patterns
    (the tensor-parallel payload; empty = every param replicated).
    ``batch_axes``: mesh axes the input batch dim shards over.
    ``seq_axis``: None (auto-detect), 1 (force SP on dim 1) or -1
    (disable) — the per-input sequence-dim contract of
    ``DataParallelStep._input_shardings``.  ``sp_attention``: which
    mechanism services attention over a sequence-sharded axis.
    ``pp_microbatches``: GPipe microbatch count when a pp>1 axis is
    present.  ``accum_steps``: gradient-accumulation microbatching
    inside the compiled step.  ``predicted``: the planner's cost
    breakdown when this plan was chosen analytically (rides into the
    ``plan`` telemetry event; never part of equality/serial identity of
    the layout itself)."""

    mesh_axes: Tuple[Tuple[str, int], ...]
    rules: ShardingRules = field(default_factory=ShardingRules)
    batch_axes: Tuple[str, ...] = ("dp", "sp")
    seq_axis: Optional[int] = None
    sp_attention: str = "gspmd"
    pp_microbatches: int = 4
    accum_steps: int = 1
    # the precision story travels WITH the layout (docs/PRECISION.md):
    # an elastic restore must rebuild not just where each shard lived but
    # what dtype program produced the checkpointed values
    precision: Optional[PrecisionConfig] = None
    predicted: Optional[dict] = field(default=None, compare=False)

    def __post_init__(self):
        object.__setattr__(self, "mesh_axes",
                           tuple((str(n), int(s)) for n, s in self.mesh_axes))
        object.__setattr__(self, "batch_axes", tuple(self.batch_axes))
        self.validate()

    # -- validation ----------------------------------------------------
    def validate(self) -> None:
        names = [n for n, _ in self.mesh_axes]
        if len(set(names)) != len(names):
            raise MXNetError(f"Plan: duplicate mesh axes {names}")
        for n, s in self.mesh_axes:
            if s < 1:
                raise MXNetError(f"Plan: axis {n!r} has size {s} < 1")
        unknown = [a for a in self.batch_axes if a not in names]
        if unknown:
            raise MXNetError(
                f"Plan: batch_axes {unknown} not among mesh axes {names}")
        if self.seq_axis not in (None, 1, -1):
            raise MXNetError("Plan: seq_axis must be None (auto), 1 "
                             "(force SP on dim 1) or -1 (disable)")
        if self.sp_attention not in _SP_MODES:
            raise MXNetError(f"Plan: sp_attention must be one of "
                             f"{_SP_MODES}, got {self.sp_attention!r}")
        if self.pp_microbatches < 1:
            raise MXNetError(f"Plan: pp_microbatches must be >= 1, got "
                             f"{self.pp_microbatches}")
        if self.accum_steps < 1:
            raise MXNetError(f"Plan: accum_steps must be >= 1, got "
                             f"{self.accum_steps}")
        if self.sp_attention != "gspmd" and self.axis_size("sp") < 2 \
                and self.seq_axis != 1:
            # a ring/ulysses plan with no sp axis would silently run the
            # plain GSPMD path — a mis-built plan, not a preference
            raise MXNetError(
                f"Plan: sp_attention={self.sp_attention!r} needs an sp "
                f"axis > 1 (mesh: {dict(self.mesh_axes)})")

    # -- accessors -----------------------------------------------------
    def axis_size(self, name: str) -> int:
        for n, s in self.mesh_axes:
            if n == name:
                return s
        return 1

    @property
    def n_devices(self) -> int:
        n = 1
        for _, s in self.mesh_axes:
            n *= s
        return n

    @property
    def strategy(self) -> str:
        """Derived dominant-strategy label (telemetry/bench attribution;
        composite layouts join with '+', pure dp is 'dp')."""
        parts = []
        if self.axis_size("tp") > 1:
            parts.append("tp")
        if self.axis_size("pp") > 1:
            parts.append("pp")
        if self.axis_size("sp") > 1 or self.seq_axis == 1:
            parts.append(self.sp_attention if self.sp_attention != "gspmd"
                         else "sp")
        if self.axis_size("dp") > 1 or not parts:
            parts.insert(0, "dp")
        return "+".join(parts)

    def describe(self) -> str:
        mesh = "x".join(f"{n}{s}" for n, s in self.mesh_axes if s > 1) \
            or "1dev"
        return (f"Plan[{self.strategy}] {mesh} accum={self.accum_steps} "
                f"pp_micro={self.pp_microbatches}")

    # -- mesh / step construction --------------------------------------
    def build_mesh(self, devices=None):
        """A jax Mesh realizing this plan's axes (canonical axis order,
        tp innermost).  ``devices`` defaults to all local devices; their
        count must equal the plan's axis product."""
        from .mesh import device_mesh

        import jax

        if devices is None:
            devices = jax.devices()
        if len(devices) != self.n_devices:
            raise MXNetError(
                f"Plan covers {self.n_devices} devices "
                f"({dict(self.mesh_axes)}) but {len(devices)} were given")
        names = [n for n, _ in self.mesh_axes]
        sizes = [s for _, s in self.mesh_axes]
        return device_mesh(tuple(names), tuple(sizes), devices)

    def matches_mesh(self, mesh) -> bool:
        """Whether ``mesh`` realizes this plan.  Size-1 axes are
        placement-neutral (a dp8 plan runs fine on a plain ("dp",)
        local mesh), so only the non-trivial axes must agree — in
        order, since axis order is the device-to-position mapping."""
        mine = tuple((n, s) for n, s in self.mesh_axes if s > 1)
        theirs = tuple((n, int(s)) for n, s in mesh.shape.items() if s > 1)
        return mine == theirs

    # -- serialization (the meta.json `layout.plan` block) -------------
    def to_json(self) -> dict:
        return {
            "mesh_axes": [[n, s] for n, s in self.mesh_axes],
            "rules": self.rules.to_json(),
            "batch_axes": list(self.batch_axes),
            "seq_axis": self.seq_axis,
            "sp_attention": self.sp_attention,
            "pp_microbatches": self.pp_microbatches,
            "accum_steps": self.accum_steps,
            "precision": (self.precision.to_json()
                          if self.precision is not None else None),
            "strategy": self.strategy,  # derived; informational on disk
        }

    @classmethod
    def from_json(cls, rec: dict) -> "Plan":
        ba = rec.get("batch_axes")
        return cls(
            mesh_axes=tuple((n, int(s)) for n, s in rec["mesh_axes"]),
            rules=ShardingRules.from_json(rec.get("rules") or []),
            # an explicitly-empty batch_axes (a mesh with no dp/sp axes)
            # must round-trip as empty, not regrow the default
            batch_axes=tuple(ba) if ba is not None else ("dp", "sp"),
            seq_axis=rec.get("seq_axis"),
            sp_attention=rec.get("sp_attention", "gspmd"),
            pp_microbatches=int(rec.get("pp_microbatches", 4)),
            accum_steps=int(rec.get("accum_steps", 1)),
            precision=PrecisionConfig.from_json(rec.get("precision")),
        )

    def with_predicted(self, predicted: dict) -> "Plan":
        return replace(self, predicted=dict(predicted))


def _axes(dp: int, tp: int = 1, pp: int = 1, sp: int = 1,
          ep: int = 1) -> Tuple[Tuple[str, int], ...]:
    sizes = {"dp": dp, "pp": pp, "sp": sp, "tp": tp, "ep": ep}
    return tuple((n, int(sizes[n])) for n in _AXIS_ORDER)


def _resolve_dp(dp: int, n_devices: Optional[int], fixed: int) -> int:
    """dp=0 means "whatever is left" of ``n_devices`` (the make_mesh
    contract); explicit dp passes through."""
    if dp not in (0, None):
        return int(dp)
    if n_devices is None:
        import jax

        n_devices = len(jax.devices())
    if n_devices % fixed:
        raise MXNetError(
            f"{n_devices} devices not divisible by tp*pp*sp={fixed}")
    return n_devices // fixed


# ---------------------------------------------------------------------------
# the five legacy strategies as Plan producers (compat shims re-export
# these from their home modules: data_parallel/sharding/pipeline/ring/
# ulysses)
# ---------------------------------------------------------------------------
def dp_plan(dp: int = 0, n_devices: Optional[int] = None,
            accum_steps: int = 1) -> Plan:
    """Pure data parallelism — the ``KVStore('device')``/``local_mesh``
    strategy: batch sharded over every device, params replicated."""
    dp = _resolve_dp(dp, n_devices, 1)
    return Plan(mesh_axes=_axes(dp=dp), accum_steps=accum_steps)


def tensor_parallel_plan(rules: ShardingRules, tp: int, dp: int = 0,
                         n_devices: Optional[int] = None,
                         accum_steps: int = 1) -> Plan:
    """Tensor parallelism via per-param PartitionSpec rules (the
    ``sharding.ShardingRules`` strategy), composed with dp over the
    remaining devices."""
    if tp < 2:
        raise MXNetError(f"tensor_parallel_plan: tp must be >= 2, got {tp}")
    dp = _resolve_dp(dp, n_devices, tp)
    return Plan(mesh_axes=_axes(dp=dp, tp=tp), rules=rules,
                accum_steps=accum_steps)


def pipeline_plan(pp: int, microbatches: int = 4, dp: int = 0,
                  n_devices: Optional[int] = None,
                  rules: Optional[ShardingRules] = None,
                  accum_steps: int = 1) -> Plan:
    """GPipe pipeline parallelism over a pp axis (stacked-encoder models
    route through ``pipeline.pipeline_apply``), composed with dp."""
    if pp < 2:
        raise MXNetError(f"pipeline_plan: pp must be >= 2, got {pp}")
    dp = _resolve_dp(dp, n_devices, pp)
    return Plan(mesh_axes=_axes(dp=dp, pp=pp),
                rules=rules or ShardingRules(),
                pp_microbatches=microbatches, accum_steps=accum_steps)


def ring_plan(sp: int, dp: int = 0, n_devices: Optional[int] = None,
              rules: Optional[ShardingRules] = None,
              accum_steps: int = 1) -> Plan:
    """Ring-attention sequence parallelism: sequence dim sharded over
    sp, fused attention lowered to the ppermute K/V rotation."""
    if sp < 2:
        raise MXNetError(f"ring_plan: sp must be >= 2, got {sp}")
    dp = _resolve_dp(dp, n_devices, sp)
    return Plan(mesh_axes=_axes(dp=dp, sp=sp),
                rules=rules or ShardingRules(),
                sp_attention="ring", accum_steps=accum_steps)


def ulysses_plan(sp: int, dp: int = 0, n_devices: Optional[int] = None,
                 rules: Optional[ShardingRules] = None,
                 accum_steps: int = 1) -> Plan:
    """Ulysses sequence parallelism: one all-to-all reshards heads so
    attention runs locally over the full sequence."""
    if sp < 2:
        raise MXNetError(f"ulysses_plan: sp must be >= 2, got {sp}")
    dp = _resolve_dp(dp, n_devices, sp)
    return Plan(mesh_axes=_axes(dp=dp, sp=sp),
                rules=rules or ShardingRules(),
                sp_attention="ulysses", accum_steps=accum_steps)
