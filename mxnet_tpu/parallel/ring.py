"""Ring attention: exact attention over a sequence-sharded axis.

Long-context support the reference never had (SURVEY §5.7: absent —
bucketing and fused attention matmuls only).  Each device holds a length
L/sp slice of q, k, v.  K/V blocks rotate around the 'sp' mesh axis via
`ppermute` (ICI neighbour exchange); each step folds the visiting block
into a running online-softmax state, so the full (L, L) score matrix never
exists and per-device activation memory stays O((L/sp)^2).

Backward is a second ring pass: q/do/lse/delta stay resident while
(k, v, dk, dv) travel the ring; dk/dv arrive home after a full rotation.
Wrapped in jax.custom_vjp so the forward ring is not differentiated
through (which would save every rotation's intermediates).

Use under `shard_map` with the sequence axis sharded over 'sp'
(see `ring_self_attention` and tests/test_pallas.py).
"""
from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "ring_self_attention", "ring_plan"]


def ring_plan(sp, dp=0, n_devices=None, rules=None, accum_steps=1):
    """Compat shim: ring-attention sequence parallelism as a
    :class:`~mxnet_tpu.parallel.plan.Plan` (docs/PERFORMANCE.md §Plan &
    planner) — the compiled step lowers fused-attention ops to the
    ppermute K/V rotation below."""
    from .plan import ring_plan as _rp

    return _rp(sp, dp=dp, n_devices=n_devices, rules=rules,
               accum_steps=accum_steps)

_NEG = -1e30


class _RCfg(NamedTuple):
    axis_name: str
    causal: bool
    sm_scale: float


def _block(cfg: _RCfg, q, k, v, q_off, k_off):
    """Scores of local q against one visiting k/v block (f32)."""
    s = jnp.einsum("nqd,nkd->nqk", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32) * cfg.sm_scale
    if cfg.causal:
        lq, lk = q.shape[1], k.shape[1]
        qpos = q_off + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 0)
        kpos = k_off + jax.lax.broadcasted_iota(jnp.int32, (lq, lk), 1)
        s = jnp.where(qpos[None] >= kpos[None], s, _NEG)
    return s


def _rotate(cfg: _RCfg, *xs):
    n = jax.lax.psum(1, cfg.axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]
    return tuple(jax.lax.ppermute(x, cfg.axis_name, perm) for x in xs)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _ring(cfg: _RCfg, q, k, v):
    out, _ = _ring_fwd_impl(cfg, q, k, v)
    return out


def _ring_fwd_impl(cfg: _RCfg, q, k, v):
    n = jax.lax.psum(1, cfg.axis_name)
    idx = jax.lax.axis_index(cfg.axis_name)
    lq, lk = q.shape[1], k.shape[1]
    q_off = idx * lq

    m = jnp.full(q.shape[:2], _NEG, jnp.float32)
    l = jnp.zeros(q.shape[:2], jnp.float32)
    acc = jnp.zeros(q.shape, jnp.float32)

    def step(i, carry):
        m, l, acc, k, v = carry
        k_off = ((idx - i) % n) * lk
        s = _block(cfg, q, k, v, q_off, k_off)
        m_new = jnp.maximum(m, s.max(axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l = l * alpha + p.sum(axis=-1)
        acc = acc * alpha[..., None] + jnp.einsum(
            "nqk,nkd->nqd", p, v.astype(jnp.float32))
        k, v = _rotate(cfg, k, v)
        return m_new, l, acc, k, v

    m, l, acc, k, v = jax.lax.fori_loop(0, n, step, (m, l, acc, k, v))
    safe_l = jnp.where(l == 0.0, 1.0, l)
    out = (acc / safe_l[..., None]).astype(q.dtype)
    lse = m + jnp.log(safe_l)
    return out, lse


def _ring_fwd(cfg: _RCfg, q, k, v):
    out, lse = _ring_fwd_impl(cfg, q, k, v)
    return out, (q, k, v, out, lse)


def _ring_bwd(cfg: _RCfg, res, do):
    q, k, v, out, lse = res
    n = jax.lax.psum(1, cfg.axis_name)
    idx = jax.lax.axis_index(cfg.axis_name)
    lq, lk = q.shape[1], k.shape[1]
    q_off = idx * lq
    dof = do.astype(jnp.float32)
    delta = jnp.sum(dof * out.astype(jnp.float32), axis=-1)   # (n_heads, lq)

    dq = jnp.zeros(q.shape, jnp.float32)
    dk = jnp.zeros(k.shape, jnp.float32)
    dv = jnp.zeros(v.shape, jnp.float32)

    def step(i, carry):
        dq, dk, dv, k, v = carry
        k_off = ((idx - i) % n) * lk
        s = _block(cfg, q, k, v, q_off, k_off)
        p = jnp.exp(s - lse[..., None])                       # (N, lq, lk)
        dv = dv + jnp.einsum("nqk,nqd->nkd", p, dof)
        dp = jnp.einsum("nqd,nkd->nqk", dof, v.astype(jnp.float32))
        ds = p * (dp - delta[..., None]) * cfg.sm_scale
        dq = dq + jnp.einsum("nqk,nkd->nqd", ds, k.astype(jnp.float32))
        dk = dk + jnp.einsum("nqk,nqd->nkd", ds, q.astype(jnp.float32))
        k, v, dk, dv = _rotate(cfg, k, v, dk, dv)
        return dq, dk, dv, k, v

    dq, dk, dv, k, v = jax.lax.fori_loop(0, n, step, (dq, dk, dv, k, v))
    # after n rotations dk/dv have returned to their home shard
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


_ring.defvjp(_ring_fwd, _ring_bwd)


def ring_attention(q, k, v, axis_name: str, causal: bool = False,
                   sm_scale: Optional[float] = None):
    """Exact attention with k/v rotating around mesh axis `axis_name`.

    Call inside `shard_map` with q/k/v sequence-sharded over that axis.
    q: (N, Lq/sp, D), k/v: (N, Lk/sp, D) per device, N = batch*heads.
    """
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    cfg = _RCfg(axis_name, bool(causal), float(sm_scale))
    return _ring(cfg, q, k, v)


def ring_self_attention(mesh, q, k, v, causal: bool = False,
                        sm_scale: Optional[float] = None, axis: str = "sp",
                        batch_axes: Optional[tuple] = None):
    """Convenience: shard_map-wrapped ring attention over mesh axis `axis`.

    q/k/v are global (N, L, D) arrays; the sequence dim is sharded over
    `axis`, N sharded over `batch_axes` (replicated when None).  Returns
    the global (N, L, D) output.  The single shard_map wrapper — callers
    (incl. the _contrib_flash_attention ring route) go through here.
    """
    from jax.sharding import PartitionSpec as P

    from .sharding import shard_map_compat

    spec = P(tuple(batch_axes) if batch_axes else None, axis, None)
    fn = functools.partial(ring_attention, axis_name=axis, causal=causal,
                           sm_scale=sm_scale)
    return shard_map_compat(fn, mesh=mesh, in_specs=(spec, spec, spec),
                            out_specs=spec, check_vma=False)(q, k, v)
