"""Ulysses-style all-to-all sequence parallelism (exact attention).

The second long-context mechanism SURVEY §5.7 calls for, complementing
the ring (parallel/ring.py): instead of rotating K/V blocks around the
'sp' axis, ONE all-to-all redistributes the sequence-sharded q/k/v so
each device holds ALL tokens for 1/sp of the heads, attention runs
locally (any kernel — here the dense composition XLA fuses; Pallas
flash drops in), and a second all-to-all restores sequence sharding.

Trade-off vs the ring: 2 all-to-alls of activation size per tensor
(constant collective count, bandwidth-bound, great on ICI's all-to-all)
vs sp-1 ppermute steps overlappable with compute; Ulysses caps sp at
the head count, the ring does not.  Differentiable via the built-in
all_to_all transpose rule.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp

from ..base import MXNetError

__all__ = ["ulysses_self_attention", "ulysses_plan"]


def ulysses_plan(sp, dp=0, n_devices=None, rules=None, accum_steps=1):
    """Compat shim: Ulysses all-to-all sequence parallelism as a
    :class:`~mxnet_tpu.parallel.plan.Plan` (docs/PERFORMANCE.md §Plan &
    planner) — the compiled step reshards heads through the all-to-all
    pair below."""
    from .plan import ulysses_plan as _up

    return _up(sp, dp=dp, n_devices=n_devices, rules=rules,
               accum_steps=accum_steps)


def _local_attn(q, k, v, causal, sm_scale):
    """Per-device attention after the head reshard: the Pallas flash
    kernel when enabled (no (L, L) score materialization — the point of
    SP for long sequences), else the shared dense composition."""
    from ..ops import pallas as _pk
    from ..ops.contrib_ops import _dense_attention

    if _pk.enabled() and _pk.use_compiled():
        return _pk.flash_attention(q, k, v, causal=causal, sm_scale=sm_scale)
    return _dense_attention(q, k, v, causal, sm_scale)


def ulysses_self_attention(mesh, q, k, v, causal: bool = False,
                           sm_scale: Optional[float] = None,
                           axis: str = "sp",
                           batch_axes: Optional[tuple] = None):
    """Exact self-attention over q/k/v (N, L, D) with L sharded on `axis`.

    N (= batch*heads) must be divisible by the axis size: the all-to-all
    trades the sequence shard for a head shard.  Returns (N, L, D) with
    the input sharding.
    """
    from jax.sharding import PartitionSpec as P

    shape = dict(mesh.shape)
    if axis not in shape:
        raise MXNetError(f"mesh has no {axis!r} axis: {tuple(shape)}")
    S = shape[axis]
    # the all_to_all splits the PER-SHARD leading dim: account for any
    # batch_axes sharding of N before checking divisibility
    n_batch = 1
    for a in (batch_axes or ()):
        n_batch *= shape.get(a, 1)
    if q.shape[0] % max(n_batch, 1) or (q.shape[0] // max(n_batch, 1)) % S:
        raise MXNetError(
            f"Ulysses SP: local N={q.shape[0]}/{n_batch} heads*batch not "
            f"divisible by {axis}={S} (the all-to-all shards heads)")
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def fn(q_l, k_l, v_l):
        # (N, L/S, D) -> all-to-all -> (N/S, L, D): all tokens, 1/S heads
        def seq2head(x):
            return jax.lax.all_to_all(x, axis, split_axis=0, concat_axis=1,
                                      tiled=True)

        def head2seq(x):
            return jax.lax.all_to_all(x, axis, split_axis=1, concat_axis=0,
                                      tiled=True)

        qh, kh, vh = seq2head(q_l), seq2head(k_l), seq2head(v_l)
        out = _local_attn(qh, kh, vh, causal, sm_scale)
        return head2seq(out)

    spec = P(tuple(batch_axes) if batch_axes else None, axis, None)
    from .sharding import shard_map_compat

    return shard_map_compat(fn, mesh=mesh, in_specs=(spec,) * 3,
                            out_specs=spec, check_vma=False)(q, k, v)
