"""Device meshes and host-level collectives.

Reference parity: the topology-aware comm layer (src/kvstore/gpu_topology.h
builds reduction trees from link matrices).  On TPU the topology belongs to
XLA: we only choose the logical mesh axes; ICI routing is the compiler's job.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["make_mesh", "local_mesh", "device_mesh", "host_barrier",
           "global_allreduce"]


def _jax():
    import jax

    return jax


def device_mesh(axis_names: Sequence[str], shape: Optional[Sequence[int]] = None,
                devices=None):
    """Build a jax Mesh with named axes over `devices` (default: all)."""
    jax = _jax()
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        raise MXNetError(
            f"mesh shape {tuple(shape)} does not cover {n} devices")
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def make_mesh(dp: int = 0, tp: int = 1, pp: int = 1, sp: int = 1, ep: int = 1,
              devices=None):
    """Mesh factory over the standard parallelism axes.

    Axes with size 1 are still present (so shardings can name them); dp=0
    means "whatever is left".  Axis order (dp, pp, sp, tp, ep) puts tensor
    parallelism innermost — adjacent devices on the ICI ring — which is the
    bandwidth-optimal layout for TP collectives (scaling-book recipe).
    """
    jax = _jax()
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    fixed = tp * pp * sp * ep
    if dp in (0, None):
        if n % fixed != 0:
            raise MXNetError(f"{n} devices not divisible by tp*pp*sp*ep={fixed}")
        dp = n // fixed
    return device_mesh(("dp", "pp", "sp", "tp", "ep"),
                       (dp, pp, sp, tp, ep), devices)


def local_mesh(axis_name: str = "dp", devices=None):
    """1-D data-parallel mesh over local devices (KVStore('device') shape)."""
    jax = _jax()
    if devices is None:
        devices = jax.local_devices()
    return device_mesh((axis_name,), (len(devices),), devices)


def host_barrier() -> None:
    """Block until all hosts reach this point (reference: kv._barrier via
    ps-lite scheduler; here: a tiny global psum)."""
    jax = _jax()
    if jax.process_count() == 1:
        return
    import jax.numpy as jnp

    from jax.experimental import multihost_utils

    multihost_utils.sync_global_devices("mxnet_tpu_barrier")


def global_allreduce(nd):
    """Sum an NDArray across all hosts — a COMPILED XLA collective over the
    host mesh (DCN/Gloo on the wire), not a host-memory allgather
    (reference being replaced: kvstore_dist_server.h DataHandleEx)."""
    jax = _jax()
    if jax.process_count() == 1:
        return nd
    from ..ndarray import NDArray
    from .dist import allreduce_sum

    summed = allreduce_sum(nd._data)
    return NDArray(jax.device_put(summed, nd.context.jax_device),
                   ctx=nd.context)
