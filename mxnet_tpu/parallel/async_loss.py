"""Async step pipeline primitives: lazy loss handles and the bounded
in-flight dispatch window (docs/PERFORMANCE.md §Async pipeline).

The reference's dependency engine makes every ``engine.push`` asynchronous:
the host thread races ahead preparing the next batch while the device
computes, and only ``WaitToRead`` blocks.  jax already queues execution
asynchronously on every backend, so the only thing standing between this
tree and the same pipeline was the per-step host round-trip the callers
imposed by forcing each loss to a host scalar immediately.

This module supplies the missing pieces:

  * :class:`AsyncLoss` — the lazy handle ``DataParallelStep.step()``
    returns instead of a host scalar.  ``float()`` / ``.asnumpy()`` /
    ``.wait()`` force the readback; until then the host never blocks on
    the device.
  * :class:`StepFence` — the same discipline for executors that update
    buffers in place and have no scalar to hand back (``gluon.Trainer``,
    ``module.Module``): waiting on the fence syncs that step's updates.
  * :class:`InflightRing` — the bounded window.  ``MX_ASYNC_INFLIGHT``
    (default 2) caps how many dispatched-but-unforced steps may be
    pending; admitting a new step past the cap blocks on the *oldest*
    pending handle first, so the dispatch queue can never run away from
    the device.  ``MX_ASYNC_INFLIGHT=0`` restores fully synchronous
    behavior (every step forced at dispatch).
  * :func:`drain_all` — force every pending handle in the process; the
    SIGTERM preemption path (``fault.install_preemption_handler``) calls
    it so a final sync checkpoint never snapshots ahead of an in-flight
    step it hasn't observed failing.

Asynchrony changes *when* the host observes results, never what is
computed: per-step losses and final weights are bitwise identical across
window sizes (asserted by ``tests/test_async_step.py``).  Exceptions a
deferred step raises surface at the forcing site, wrapped in an
``MXNetError`` naming the dispatching step.
"""
from __future__ import annotations

import contextlib
import os
import sys
import threading
import time
import weakref
from collections import deque
from typing import Optional

import numpy as np

from .. import memwatch
from .. import telemetry
from ..base import MXNetError

__all__ = ["AsyncLoss", "AsyncResult", "StackedAsyncLoss",
           "SuperstepLossView", "StepFence", "InflightRing",
           "inflight_limit", "drain_all"]

_DEFAULT_INFLIGHT = 2

# every ring in the process, so preemption/checkpoint paths can drain
# pending work they never saw dispatched (weak: a dropped step object
# must not be kept alive by the registry)
_live_rings: "weakref.WeakSet[InflightRing]" = weakref.WeakSet()
_rings_lock = threading.Lock()


def inflight_limit() -> int:
    """The in-flight window size, re-read from ``MX_ASYNC_INFLIGHT`` on
    every call so tests/benches can flip modes without rebuilding steps.
    0 means synchronous (force at dispatch)."""
    try:
        return max(0, int(os.environ.get("MX_ASYNC_INFLIGHT",
                                         _DEFAULT_INFLIGHT)))
    except (TypeError, ValueError):
        return _DEFAULT_INFLIGHT


class _PendingHandle:
    """One dispatched-but-unforced step.  Subclasses define `_force()`."""

    def __init__(self, step: int, executor: str,
                 ring: Optional["InflightRing"] = None):
        self._step = int(step)
        self._executor = executor
        self._ring = ring
        self._forced = False
        self._host = None
        self._exc: Optional[BaseException] = None
        # superstep views delegate their wait to the group handle, which
        # records the blocked wall itself — the view must not re-record
        # the same interval into the rollup
        self._record_wait = True

    @property
    def step(self) -> int:
        """The step counter value at dispatch (names the step in errors)."""
        return self._step

    @property
    def forced(self) -> bool:
        return self._forced

    def _force(self):
        raise NotImplementedError

    def wait(self, _span: bool = True):
        """Force the readback/sync.  Blocks until the device has produced
        this step's result; re-raises (wrapped) anything the deferred
        computation failed with, naming the dispatching step.  Idempotent:
        later calls return the cached host value (or re-raise).

        ``_span=False`` skips the ``loss_wait`` span (NOT the aggregate
        rollup) for callers that record the same blocked interval under
        their own span — one wall fact must reach the phase breakdown
        once."""
        if self._forced:
            if self._exc is not None:
                raise self._exc
            return self._host
        # the span makes the host's device-blocked time VISIBLE on the
        # trace timeline (trace_report's idle-gap straggler rule relies on
        # waits being accounted); the aggregate rollup below stays the
        # cheap always-on form
        with (telemetry.span("loss_wait", paired=True,
                             executor=self._executor, step=self._step)
              if _span else contextlib.nullcontext()):
            t0 = time.perf_counter()
            try:
                self._host = self._force()
                return self._host
            except Exception as exc:
                # under async dispatch a real device OOM surfaces HERE,
                # at the deferred readback — post-mortem before wrapping
                if memwatch.is_resource_exhausted(exc):
                    memwatch.emit_oom_report(
                        executor=self._executor, step=self._step,
                        inflight_depth=(self._ring.depth
                                        if self._ring is not None else 0))
                # the failure belongs to the step that DISPATCHED the
                # program, not to whatever line happened to force it later
                self._exc = MXNetError(
                    f"async step {self._step} dispatched by "
                    f"{self._executor} failed at deferred readback: {exc}")
                raise self._exc from exc
            finally:
                self._forced = True
                if self._ring is not None:
                    self._ring.discard(self)
                # all host time spent blocked on the device funnels into
                # one per-executor rollup
                # (summary()['steps'][name]['block_wait_ms'])
                if self._record_wait:
                    telemetry.record_block_wait(self._executor,
                                                time.perf_counter() - t0)

    def __repr__(self):
        state = "forced" if self._forced else "pending"
        return (f"<{type(self).__name__} step={self._step} "
                f"executor={self._executor!r} {state}>")


class AsyncLoss(_PendingHandle):
    """Lazy scalar loss.  ``float()``, ``np.asarray()``, ``.asnumpy()``,
    ``.asscalar()``, ``.item()`` and ``.wait()`` all force readback."""

    def __init__(self, value, step: int, executor: str,
                 ring: Optional["InflightRing"] = None, host_fn=None):
        super().__init__(step, executor, ring)
        self._value = value
        self._host_fn = host_fn

    def _force(self):
        value, self._value = self._value, None  # drop the device ref
        if self._host_fn is not None:
            value = self._host_fn(value)
        return np.asarray(value)

    def asnumpy(self) -> np.ndarray:
        return np.asarray(self.wait())

    def asscalar(self):
        return self.asnumpy().item()

    def item(self):
        return self.asscalar()

    def __float__(self):
        return float(self.asscalar())

    def __int__(self):
        return int(self.asscalar())

    def __bool__(self):
        return bool(self.asscalar())

    def __array__(self, dtype=None, *args, **kwargs):
        out = self.asnumpy()
        return out if dtype is None else out.astype(dtype)


class AsyncResult(AsyncLoss):
    """Generic lazy device->host handle over ANY array-valued dispatch —
    the same forcing/ring/error semantics as :class:`AsyncLoss`, result
    returned as the raw ``np.ndarray``.  The serving engine
    (``mxnet_tpu.serving.engine``) admits one per compiled decode step
    (the (S,) per-slot token vector) through its bounded ring, so token
    readbacks happen at stream cadence, never per token."""


class StackedAsyncLoss(AsyncLoss):
    """Lazy (K,) vector of per-step losses from ONE superstep dispatch
    (``DataParallelStep.superstep`` — K training steps inside a single
    compiled ``lax.scan``).  One handle flows through the in-flight ring
    per superstep, so the window bounds dispatched *supersteps*.

    ``asnumpy()`` / ``np.asarray()`` force the readback and return the
    full (K,) loss vector in step order; scalar conversions
    (``float()`` / ``.asscalar()`` / ``.item()``) return the LAST step's
    loss — exactly the value a sequential training loop would hold in
    ``loss`` after the same K steps (what Speedometer-style display
    callbacks want)."""

    def __init__(self, value, steps, executor: str,
                 ring: Optional["InflightRing"] = None, host_fn=None):
        steps = tuple(int(s) for s in steps)
        super().__init__(value, step=steps[-1], executor=executor,
                         ring=ring, host_fn=host_fn)
        self._steps = steps

    @property
    def steps(self):
        """The step numbers this superstep covered, in dispatch order."""
        return self._steps

    def __len__(self):
        return len(self._steps)

    def asscalar(self):
        return float(np.asarray(self.wait()).ravel()[-1])


class SuperstepLossView(AsyncLoss):
    """Per-step scalar view into a (possibly not-yet-dispatched)
    superstep group — what ``DataParallelStep.step()`` returns in
    transparent superstep mode so existing training loops keep their
    one-loss-per-batch contract.  Forcing a view dispatches its group if
    still buffered (a partial group runs as a shorter scan) and reads
    this step's slot out of the stacked loss vector."""

    def __init__(self, idx: int, step: int, executor: str, dispatch_fn):
        super().__init__(None, step=step, executor=executor, ring=None)
        self._idx = int(idx)
        self._dispatch_fn = dispatch_fn
        # the group's StackedAsyncLoss records the blocked wall once
        self._record_wait = False

    def _force(self):
        stacked = self._dispatch_fn()
        arr = np.asarray(stacked.wait(_span=False))
        return arr.ravel()[self._idx]


class StepFence(_PendingHandle):
    """Pending handle over in-place buffer updates (Trainer/Module steps):
    waiting blocks until every listed device array is ready."""

    def __init__(self, arrays, step: int, executor: str,
                 ring: Optional["InflightRing"] = None):
        super().__init__(step, executor, ring)
        self._arrays = list(arrays)

    def _force(self):
        import jax

        arrays, self._arrays = self._arrays, []
        jax.block_until_ready(arrays)
        return None


def _pending_arrays(ring):
    """memwatch provider: device buffers pinned by unforced handles."""
    with ring._lock:
        handles = list(ring._pending)
    out = []
    for h in handles:
        v = getattr(h, "_value", None)
        if v is not None:
            out.append(v)
        out.extend(getattr(h, "_arrays", None) or ())
    return out


class InflightRing:
    """Bounded ring of pending handles for ONE executor.

    ``make_room(limit)`` blocks (oldest-first) until fewer than ``limit``
    handles are pending — the only place the async pipeline ever waits.
    ``admit()`` registers a freshly dispatched handle and returns the
    depth, which telemetry reports as ``inflight_depth`` (the window-bound
    assertion in tests rides on it never exceeding the limit)."""

    def __init__(self, executor: str):
        self._executor = executor
        self._pending: deque = deque()
        self._lock = threading.Lock()
        with _rings_lock:
            _live_rings.add(self)
        # live-array census: pending handles pin this step's loss/fence
        # buffers — the "inflight" category of the memory watchdog
        memwatch.register("inflight", self, _pending_arrays)

    def discard(self, handle) -> None:
        """Drop a handle the consumer forced out-of-band (float(loss))."""
        with self._lock:
            try:
                self._pending.remove(handle)
            except ValueError:
                pass

    def _oldest_over(self, limit: int):
        with self._lock:
            while self._pending and self._pending[0].forced:
                self._pending.popleft()
            if len(self._pending) < max(1, limit):
                return None
            return self._pending[0]

    def make_room(self, limit: int, wait_span: bool = True) -> float:
        """Ensure the window has a free slot; returns seconds spent
        blocked (0.0 when the ring wasn't full).  ``wait_span=False``
        suppresses the inner waits' ``loss_wait`` spans for a caller that
        records the returned duration as its own ``block_wait`` span —
        the same blocked wall must not land in the trace twice."""
        waited = 0.0
        while True:
            oldest = self._oldest_over(limit)
            if oldest is None:
                return waited
            t0 = time.perf_counter()
            oldest.wait(_span=wait_span)  # discards itself from the ring
            waited += time.perf_counter() - t0

    def admit(self, handle) -> int:
        with self._lock:
            self._pending.append(handle)
            return len(self._pending)

    @property
    def depth(self) -> int:
        with self._lock:
            return sum(1 for h in self._pending if not h.forced)

    def drain(self) -> None:
        """Force every pending handle, oldest first (epoch end, shutdown,
        checkpoint sync).  Raises the first deferred failure it hits."""
        if self.depth == 0:
            return  # no span noise for the common already-empty drain
        with telemetry.span("inflight_drain", paired=True,
                            executor=self._executor):
            while True:
                with self._lock:
                    while self._pending and self._pending[0].forced:
                        self._pending.popleft()
                    if not self._pending:
                        return
                    oldest = self._pending[0]
                oldest.wait()


def drain_all():
    """Drain every live ring in the process (preemption/checkpoint paths).
    Best-effort: deferred failures are collected and returned, not raised —
    the caller is usually about to snapshot-and-exit and must not die on a
    step that was doomed anyway.

    Buffered-but-undispatched superstep groups are flushed FIRST (via the
    ``data_parallel`` step registry): they were never admitted to any
    ring, so draining alone would silently drop up to K-1 enqueued steps
    from a SIGTERM preemption's final checkpoint.  sys.modules lookup,
    not import — this runs inside a signal handler."""
    errors = []
    dp = sys.modules.get("mxnet_tpu.parallel.data_parallel")
    if dp is not None:
        try:
            errors.extend(dp.flush_all_steps())
        except Exception as exc:  # noqa: BLE001 — survey, don't die
            errors.append(exc)
    with _rings_lock:
        rings = list(_live_rings)
    for ring in rings:
        try:
            ring.drain()
        except Exception as exc:  # noqa: BLE001 — survey, don't die
            errors.append(exc)
    return errors
