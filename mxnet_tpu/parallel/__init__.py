"""Parallelism: meshes, collectives, and fused distributed training steps.

This package is the TPU-native replacement for the reference's entire
distributed stack (src/kvstore/comm.h device reduce, kvstore_nccl.h RCCL
rings, ps-lite parameter server): parallelism is expressed as shardings over
a jax.sharding.Mesh and compiled into XLA programs whose collectives ride
ICI/DCN (SURVEY §2.4, §5.8).
"""
from .mesh import (make_mesh, local_mesh, device_mesh, host_barrier,
                   global_allreduce)
from .async_loss import (AsyncLoss, InflightRing, StackedAsyncLoss,
                         SuperstepLossView, drain_all, inflight_limit)
from .data_parallel import (DataParallelStep, compile_step_with_plan,
                            make_train_step, superstep_k)
from .plan import (Plan, dp_plan, tensor_parallel_plan, pipeline_plan,
                   ring_plan, ulysses_plan)
from .ring import ring_attention, ring_self_attention
from .ulysses import ulysses_self_attention
from .pipeline import pipeline_apply
from .scope import ring_attention_scope, ring_scope, ring_scope_mesh
from . import dist
from . import planner
from . import sharding
