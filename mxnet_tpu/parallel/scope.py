"""Trace-time parallelism scopes.

DataParallelStep(ring_attention=True) activates `ring_attention_scope`
around its jit trace/execution — only when its own SP gating decided the
sequence dim really is sharded; the fused-attention op
(`_contrib_flash_attention`) consults `ring_scope()` and lowers to the
ring kernel (parallel/ring.py) instead of letting GSPMD all-gather K/V —
the long-context memory win.  The scope carries the step's batch-dim
axes so the shard_map spec matches the activations' actual sharding.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional, Tuple

_state = threading.local()


def ring_scope() -> Optional[Tuple]:
    """(mesh, batch_axes, mode) of the innermost active scope, or None."""
    return getattr(_state, "scope", None)


def ring_scope_mesh():
    s = ring_scope()
    return None if s is None else s[0]


def pipeline_scope() -> Optional[Tuple]:
    """(mesh, batch_axes, microbatches) of the active pp scope, or None.
    Consulted by stacked-encoder blocks (models/bert_pp.py) to route their
    layer stack through parallel/pipeline.pipeline_apply instead of a
    local lax.scan."""
    return getattr(_state, "pp_scope", None)


@contextlib.contextmanager
def pipeline_parallel_scope(mesh, batch_axes: Tuple[str, ...] = (),
                            microbatches: int = 4):
    prev = getattr(_state, "pp_scope", None)
    _state.pp_scope = (mesh, tuple(batch_axes), int(microbatches))
    try:
        yield
    finally:
        _state.pp_scope = prev


@contextlib.contextmanager
def ring_attention_scope(mesh, batch_axes: Tuple[str, ...] = (),
                         mode: str = "ring"):
    """mode: 'ring' (ppermute K/V rotation) or 'ulysses' (all-to-all head
    resharding) — the two §5.7 sequence-parallel attention mechanisms."""
    if mode not in ("ring", "ulysses"):
        from ..base import MXNetError

        raise MXNetError(f"unknown SP attention mode {mode!r} "
                         "(expected 'ring' or 'ulysses')")
    prev = getattr(_state, "scope", None)
    _state.scope = (mesh, tuple(batch_axes), mode)
    try:
        yield
    finally:
        _state.scope = prev
