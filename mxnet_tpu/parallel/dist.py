"""Multi-process (multi-host) distributed backend.

Reference parity: ps-lite's scheduler rendezvous + ZMQ data plane
(3rdparty/ps-lite, src/kvstore/kvstore_dist.h worker side,
kvstore_dist_server.h server side) and tools/launch.py's DMLC_* env
contract.  TPU-native design (SURVEY §2.4, §5.8): the rendezvous is
jax.distributed.initialize (coordination service), and the data plane is a
COMPILED XLA collective over the global device mesh — gradients are summed
by `psum` riding DCN (Gloo on CPU hosts, ICI/DCN on pods), never staged
through host memory the way a parameter server would.

Environment contract (reference tools/launch.py exports DMLC_*; both
spellings are honored so reference launch scripts work unchanged):

  MX_COORDINATOR      / DMLC_PS_ROOT_URI + DMLC_PS_ROOT_PORT
  MX_NUM_PROCS        / DMLC_NUM_WORKER
  MX_PROC_ID          / DMLC_WORKER_ID
"""
from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

import numpy as np

from ..base import MXNetError

__all__ = ["init_from_env", "is_initialized", "allreduce_sum",
           "process_index", "process_count", "bucket_cap_bytes",
           "flatten_bucket", "unflatten_bucket"]

_initialized = False


def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v is not None:
            return v
    return default


def _jax_distributed_active() -> bool:
    """True when jax.distributed.initialize already ran (by us or by the
    user's own pod-startup code)."""
    try:
        from jax._src import distributed as _jd

        return _jd.global_state.client is not None
    except Exception:
        return False


def init_from_env(force_cpu: Optional[bool] = None) -> bool:
    """Connect this process to the coordination service if the launcher env
    is present (reference: ps::Postoffice::Start reading DMLC_ROLE etc.).

    Returns True when running multi-process after the call.  Idempotent,
    and treats a distributed runtime that the USER already initialized
    (conventional on pod startup) as success.

    jax requires this to run before any computation initializes the
    backends — mxnet_tpu/__init__ therefore calls this at import time when
    the launcher env is present; the KVStore constructor is only a
    fallback for exotic import orders.
    """
    global _initialized
    import jax

    if _initialized or _jax_distributed_active():
        _initialized = True
        return jax.process_count() > 1
    coord = _env("MX_COORDINATOR")
    if coord is None:
        uri = _env("DMLC_PS_ROOT_URI")
        port = _env("DMLC_PS_ROOT_PORT")
        coord = f"{uri}:{port}" if uri and port else None
    n = _env("MX_NUM_PROCS", "DMLC_NUM_WORKER")
    rank = _env("MX_PROC_ID", "DMLC_WORKER_ID")
    if coord is None or n is None or rank is None:
        return False  # single-process
    from jax._src import xla_bridge

    if xla_bridge.backends_are_initialized():
        raise MXNetError(
            "the distributed launcher env (MX_COORDINATOR/MX_NUM_PROCS) is "
            "set, but jax backends were already initialized before the "
            "rendezvous could run.  Import mxnet_tpu (or create the dist "
            "kvstore) BEFORE running any computation, or call "
            "jax.distributed.initialize() yourself at program start.")
    if force_cpu or (force_cpu is None and _env("MX_FORCE_CPU") == "1"):
        jax.config.update("jax_platforms", "cpu")
    # CPU hosts need an explicit cross-process collectives implementation:
    # the default ("none") makes every multiprocess computation fail with
    # "Multiprocess computations aren't implemented on the CPU backend".
    # Harmless on TPU (the flag only affects CPU client creation).
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:  # jax versions without the flag pick gloo themselves
        pass
    _initialize_with_retry(coord, int(n), int(rank))
    _initialized = True
    return jax.process_count() > 1


def _initialize_with_retry(coord: str, n: int, rank: int) -> None:
    """jax.distributed.initialize with exponential-backoff retries up to
    MX_RENDEZVOUS_TIMEOUT seconds (default 300).

    After a supervised gang restart (tools/launch.py --max-restarts) the
    re-spawned ranks race the new coordinator: a non-zero rank can dial
    before rank 0's coordination service is listening, and a too-fast
    restart can find the port still in TIME_WAIT — both surface as an
    immediate initialize() error that a bounded retry absorbs."""
    import jax

    import logging

    from .. import fault
    from .. import telemetry

    timeout = float(_env("MX_RENDEZVOUS_TIMEOUT", default="300"))
    deadline = time.monotonic() + timeout
    delay = 0.5
    retries = 0
    while True:
        try:
            # chaos harness: `crash-rendezvous` dies HERE — the elastic
            # re-rendezvous failure shape (a re-admitted host that dials
            # the fresh coordinator and drops dead)
            fault.on_rendezvous()
            jax.distributed.initialize(
                coordinator_address=coord, num_processes=n, process_id=rank,
                initialization_timeout=max(
                    10, int(deadline - time.monotonic())))
            telemetry.record("rendezvous", coordinator=coord, nproc=n,
                             retries=retries)
            _record_resize(n)
            return
        except (TypeError, ValueError):
            raise  # misconfiguration, deterministic — fail fast, no retry
        except Exception as e:
            # jax assigns global_state.client BEFORE client.connect(), so
            # a failed connect leaves a half-initialized client (and, on
            # rank 0, a live coordination service) behind; without this
            # teardown the next attempt dies with "initialize should only
            # be called once" — and that stale client must NOT be taken
            # as rendezvous success.
            try:
                jax.distributed.shutdown()
            except Exception:
                # best-effort teardown of the half-initialized client while
                # already on the retry path — the real error is re-raised
                # or retried below
                pass
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise MXNetError(
                    f"rendezvous with coordinator {coord} (rank {rank}/{n}) "
                    f"failed after {timeout:.0f}s — set MX_RENDEZVOUS_TIMEOUT "
                    f"to extend; last error: {e}") from e
            logging.getLogger("mxnet_tpu.dist").warning(
                "rendezvous with %s failed (%s); retrying for another "
                "%.0fs", coord, e, remaining)
            retries += 1
            telemetry.record("rendezvous_retry", coordinator=coord,
                             retries=retries, error=str(e)[:200])
            time.sleep(min(delay, remaining))
            delay = min(delay * 2, 10.0)


def _record_resize(n: int) -> None:
    """One telemetry ``resize`` event when this incarnation follows an
    elastic world-size change (tools/launch.py --elastic exports
    MX_PREV_NUM_PROCS alongside the reduced/grown MX_NUM_PROCS).  The
    event marks the segment boundary trace_report/mem_report use to keep
    the post-resize recompile wall and the restart dead-time out of the
    straggler/leak verdicts."""
    from .. import telemetry

    prev = _env("MX_PREV_NUM_PROCS")
    try:
        prev_n = int(prev) if prev else None
    except ValueError:
        return
    if prev_n is not None and prev_n != n:
        telemetry.record(
            "resize", old_world=prev_n, new_world=n,
            restart=int(_env("MX_RESTART_COUNT", default="0") or 0))


def is_initialized() -> bool:
    return _initialized


def process_index() -> int:
    import jax

    try:
        return jax.process_index()
    except Exception:
        return 0


def process_count() -> int:
    import jax

    try:
        return jax.process_count()
    except Exception:
        return 1


# ---------------------------------------------------------------------------
# gradient bucketing (docs/PERFORMANCE.md)
#
# Coalescing many small per-param gradients into size-capped flat buckets
# is what turns an O(n_params) stream of sub-megabyte collectives into
# O(total_bytes / cap) wire-efficient ones.  The flatten/unflatten pair
# lives here because BOTH reduction planes ride it: the intra-host device
# reduce (kvstore._reduce over ICI) and this module's cross-host DCN
# allreduce.  Each is one jitted dispatch per bucket; jax's signature
# cache makes repeat steps free.
# ---------------------------------------------------------------------------
_BUCKET_MB_DEFAULT = 32.0


def bucket_cap_bytes() -> int:
    """Gradient-allreduce bucket cap in bytes (MX_ALLREDUCE_BUCKET_MB,
    default 32 MB).  0 (or any non-positive/garbled value) disables
    bucketing entirely — the per-param pushpull kill switch."""
    raw = os.environ.get("MX_ALLREDUCE_BUCKET_MB")
    try:
        mb = float(raw) if raw is not None else _BUCKET_MB_DEFAULT
    except (TypeError, ValueError):
        return 0
    return int(mb * (1 << 20)) if mb > 0 else 0


_flatten_jit = None
_unflatten_cache: Dict[Tuple, object] = {}


def flatten_bucket(arrs):
    """Concatenate same-dtype jax arrays into one flat buffer — a single
    jitted dispatch regardless of how many gradients the bucket holds."""
    global _flatten_jit
    if _flatten_jit is None:
        import jax
        import jax.numpy as jnp

        _flatten_jit = jax.jit(
            lambda *xs: jnp.concatenate([x.reshape(-1) for x in xs]))
    return _flatten_jit(*arrs)


def unflatten_bucket(flat, shapes):
    """Split a reduced flat bucket back into the original shapes (one
    jitted dispatch; executables cached per bucket layout)."""
    shapes = tuple(tuple(int(d) for d in s) for s in shapes)
    fn = _unflatten_cache.get(shapes)
    if fn is None:
        import jax
        import jax.numpy as jnp

        sizes = [int(np.prod(s)) if s else 1 for s in shapes]
        offsets = list(np.cumsum(sizes)[:-1])

        def split(buf):
            parts = jnp.split(buf, offsets) if offsets else [buf]
            return tuple(p.reshape(s) for p, s in zip(parts, shapes))

        fn = _unflatten_cache[shapes] = jax.jit(split)
    return fn(flat)


# ---------------------------------------------------------------------------
# compiled global allreduce
# ---------------------------------------------------------------------------
# (mesh, my lead device, jitted reducer) — built once; jax.jit's own cache
# handles per-shape/dtype specialization
_allreduce_state = None
# (shape, dtype) pairs whose reducer specialization already compiled —
# telemetry uses this to tag first-use collective events as compile
_allreduce_seen: set = set()


def _get_allreduce_state():
    global _allreduce_state
    if _allreduce_state is None:
        import jax
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        by_proc: Dict[int, object] = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        leads = [by_proc[i] for i in sorted(by_proc)]
        mesh = Mesh(np.array(leads), ("hosts",))
        reducer = jax.jit(lambda a: a.sum(axis=0),
                          out_shardings=NamedSharding(mesh, P()))
        _allreduce_state = (mesh, leads[process_index()], reducer)
    return _allreduce_state


def allreduce_sum(arr):
    """Sum a per-process jax/numpy array across all processes; returns the
    (replicated) result as a jax array on this process's lead device.

    Compiled path: the per-host contributions form ONE global array sharded
    over the 'hosts' mesh axis; a jitted sum over that axis lowers to an
    XLA all-reduce on the wire (reference equivalent being replaced:
    kvstore_dist_server.h DataHandleEx server-side aggregation ~L200).
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    n = process_count()
    if n == 1:
        return jax.numpy.asarray(arr)
    mesh, lead, reducer = _get_allreduce_state()
    local = jax.numpy.asarray(arr)
    garr = jax.make_array_from_single_device_arrays(
        (n,) + tuple(local.shape),
        NamedSharding(mesh, P("hosts")),
        [jax.device_put(local[None], lead)])
    from .. import telemetry

    t0 = time.perf_counter()
    out = reducer(garr)
    if telemetry.enabled():
        # the shared reducer jit re-specializes per (shape, dtype); tag
        # each first use so compile time stays out of the comm aggregates
        shape_key = (tuple(local.shape), str(local.dtype))
        traced = shape_key not in _allreduce_seen
        _allreduce_seen.add(shape_key)
        telemetry.record_collective("global_allreduce",
                                    nbytes=int(local.nbytes),
                                    wall_s=time.perf_counter() - t0,
                                    nproc=n, traced=traced)
    return out.addressable_shards[0].data
