"""Analytic auto-sharding planner: CHOOSE the dp×tp×pp×sp layout.

Nobody should hand-pick a parallelism strategy per run — the reference
stack made the user do it (KVStore type, group2ctx placement), and until
now this tree did too (DataParallelStep kwargs).  This module enumerates
every legal factorization of a mesh into (dp, tp, pp, sp) for a given
model signature and ranks them with a closed-form cost model — bytes
moved per collective over per-axis bandwidth, per-stage FLOPs with the
pipeline bubble, per-device memory against a budget — in the spirit of
*A Learned Performance Model for TPUs* (arxiv 2008.01040; the analytic
form is the v0 the learned model later replaces, trained on the very
`plan`-vs-`step` telemetry this module emits through
``compile_step_with_plan``).

The formulas (documented with worked examples in docs/PERFORMANCE.md
§Plan & planner; all sizes in bytes, times in seconds):

  per-device params   P_dev  = (P_tp/tp + P_rest) / pp
  per-device acts     A_dev  = A / (dp*sp)
  compute             C      = F / (N * flops_per_device) * bubble
                      bubble = (M + pp - 1) / M          (pp > 1)
  dp grad allreduce   t_dp   = 2*(dp-1)/dp * P_dev / bw(dp)
  tp act collectives  t_tp   = 4*(tp-1)/tp * A_dev / bw(tp)
  sp seq collectives  t_sp   = 4*(sp-1)/sp * A_dev / bw(sp)
  pp boundary hops    t_pp   = 2*(pp-1)/pp * A_dev / bw(pp)
  step                T      = C + t_dp + t_tp + t_sp + t_pp
  memory              M_dev  = (2 + opt_slots) * P_dev
                               + A_dev / (accum * (M if pp>1 else 1))

Legality is structural, not heuristic: dp must divide the batch, sp the
sequence length, pp the stacked layer count (and the per-device batch
the microbatch count), and tp every dimension the sharding rules put it
on.  A plan that exceeds the memory budget ranks strictly below every
plan that fits — the "memory forces sharding" case where the fastest
layout is not a legal one.

``plan_for`` picks the argmin; ``MX_PLAN`` overrides (``auto`` |
``dp`` | ``tp`` | ``pp`` | ``sp`` | ``ring`` | ``ulysses``) — an
operator pinning a strategy for an ablation without touching code.
"""
from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError
from .plan import Plan, STRATEGY_NAMES
from .sharding import ShardingRules

__all__ = ["ModelSignature", "Hardware", "PlanChoice", "signature_of",
           "enumerate_plans", "plan_cost", "plan_for"]


@dataclass
class Hardware:
    """What the cost model knows about one device class and its links.

    Relative ranking (the planner's job) only needs the RATIOS to be
    sane, so the defaults are generic accelerator-ish numbers;
    ``bw_override`` pins individual axes (tests and heterogeneous
    meshes).  ``dcn_axes`` names the axes whose collectives cross hosts
    (DCN/Gloo bandwidth class instead of ICI)."""

    flops_per_device: float = 1e12
    ici_bw: float = 1e11          # bytes/s, on-chip interconnect class
    dcn_bw: float = 2.5e9         # bytes/s, cross-host class
    mem_per_device: Optional[float] = None   # bytes; None = unbounded
    opt_slots: float = 2.0        # adam: 2 fp32 slots besides param+grad
    dcn_axes: Tuple[str, ...] = ()
    bw_override: Dict[str, float] = field(default_factory=dict)

    def bw(self, axis: str) -> float:
        if axis in self.bw_override:
            return self.bw_override[axis]
        return self.dcn_bw if axis in self.dcn_axes else self.ici_bw


@dataclass
class ModelSignature:
    """The shape-level facts the cost model needs about one (model,
    batch) pair — constructible by hand for fixtures (every number
    explicit and hand-checkable) or derived from a Gluon block via
    :func:`signature_of`.

    ``flops_per_step`` defaults to the 6·tokens·params dense-training
    estimate over matmul-shaped (ndim>=2) params; ``act_bytes``
    defaults to a rough activations-per-step volume.  Fixtures should
    pass both explicitly."""

    param_shapes: Dict[str, Tuple[int, ...]]
    batch_shape: Tuple[int, ...]
    bytes_per_param: int = 4
    seq_len: Optional[int] = None
    stacked_layers: Optional[int] = None
    rules: Optional[ShardingRules] = None
    flops_per_step: Optional[float] = None
    act_bytes: Optional[float] = None

    def __post_init__(self):
        self.param_shapes = {n: tuple(int(d) for d in s)
                             for n, s in self.param_shapes.items()}
        self.batch_shape = tuple(int(d) for d in self.batch_shape)
        if self.seq_len is None and len(self.batch_shape) >= 2:
            self.seq_len = self.batch_shape[1]
        if self.flops_per_step is None:
            self.flops_per_step = 6.0 * self.tokens * self._matmul_numel()
        if self.act_bytes is None:
            widths = [s[-1] for s in self.param_shapes.values()
                      if len(s) >= 2]
            self.act_bytes = (4.0 * self.tokens
                              * float(max(widths) if widths else 1)
                              * max(1, len(widths)))

    @property
    def batch(self) -> int:
        return self.batch_shape[0]

    @property
    def tokens(self) -> int:
        return self.batch * (self.seq_len or 1)

    def _matmul_numel(self) -> float:
        total = 0.0
        for s in self.param_shapes.values():
            if len(s) >= 2:
                n = 1.0
                for d in s:
                    n *= d
                total += n
        return total

    @property
    def param_bytes(self) -> float:
        total = 0.0
        for s in self.param_shapes.values():
            n = 1.0
            for d in s:
                n *= d
            total += n
        return total * self.bytes_per_param

    def tp_split(self, tp: int) -> Tuple[float, float, bool]:
        """(tp-sharded param bytes, replicated param bytes, divisible):
        which params the rules put on 'tp' and whether every such dim
        divides by ``tp``."""
        if not self.rules or tp < 2:
            return 0.0, self.param_bytes, True
        sharded = 0.0
        ok = True
        for name, shape in self.param_shapes.items():
            spec = tuple(self.rules.spec_for(name, len(shape)))
            dims = [i for i, entry in enumerate(spec)
                    if entry is not None
                    and ("tp" == entry or (isinstance(entry, (tuple, list))
                                           and "tp" in entry))]
            if not dims:
                continue
            n = 1.0
            for d in shape:
                n *= d
            sharded += n * self.bytes_per_param
            for i in dims:
                if shape[i] % tp:
                    ok = False
        return sharded, self.param_bytes - sharded, ok


def signature_of(block, data_shape: Sequence[int],
                 rules: Optional[ShardingRules] = None,
                 stacked_layers: Optional[int] = None,
                 bytes_per_param: int = 4) -> ModelSignature:
    """Derive a :class:`ModelSignature` from an initialized Gluon block
    and one batch shape.  Deferred-init params with unknown shapes are
    skipped (their cost contribution is unknowable pre-trace);
    ``stacked_layers`` defaults to the block's ``_L`` when it exposes
    one (the stacked-encoder pipeline contract of models/bert_pp.py)."""
    shapes = {}
    for name, p in block.collect_params().items():
        shape = tuple(getattr(p, "shape", ()) or ())
        if shape and all(int(d) > 0 for d in shape):
            shapes[name] = shape
    if stacked_layers is None:
        stacked_layers = getattr(block, "_L", None)
    return ModelSignature(param_shapes=shapes,
                          batch_shape=tuple(data_shape),
                          bytes_per_param=bytes_per_param,
                          rules=rules, stacked_layers=stacked_layers)


@dataclass
class PlanChoice:
    """One enumerated candidate: the Plan plus its predicted cost
    breakdown (the ``predicted`` dict also rides on the Plan itself)."""

    plan: Plan
    cost: Dict[str, object]

    @property
    def step_s(self) -> float:
        return self.cost["step_s"]


def plan_cost(sig: ModelSignature, plan: Plan,
              hw: Optional[Hardware] = None) -> Dict[str, object]:
    """Closed-form cost of running ``sig`` under ``plan`` on ``hw`` —
    the docstring formulas, every intermediate in the returned dict so
    fixtures can hand-check each term."""
    hw = hw or Hardware()
    dp, tp = plan.axis_size("dp"), plan.axis_size("tp")
    pp, sp = plan.axis_size("pp"), plan.axis_size("sp")
    n = plan.n_devices
    p_tp, p_rest, _ = sig.tp_split(tp)
    p_dev = (p_tp / tp + p_rest) / pp
    a_dev = sig.act_bytes / (dp * sp)
    micro = plan.pp_microbatches
    bubble = (micro + pp - 1) / micro if pp > 1 else 1.0
    compute_s = sig.flops_per_step / (n * hw.flops_per_device) * bubble
    comm: Dict[str, float] = {}
    if dp > 1:
        comm["dp"] = 2.0 * (dp - 1) / dp * p_dev / hw.bw("dp")
    if tp > 1:
        comm["tp"] = 4.0 * (tp - 1) / tp * a_dev / hw.bw("tp")
    if sp > 1:
        comm["sp"] = 4.0 * (sp - 1) / sp * a_dev / hw.bw("sp")
    if pp > 1:
        comm["pp"] = 2.0 * (pp - 1) / pp * a_dev / hw.bw("pp")
    comm_s = sum(comm.values())
    act_mem = a_dev / (plan.accum_steps * (micro if pp > 1 else 1))
    mem_bytes = (2.0 + hw.opt_slots) * p_dev + act_mem
    mem_ok = hw.mem_per_device is None or mem_bytes <= hw.mem_per_device
    return {
        "step_s": compute_s + comm_s,
        "compute_s": compute_s,
        "comm_s": comm_s,
        "comm": comm,
        "bubble": bubble,
        "param_bytes_per_device": p_dev,
        "act_bytes_per_device": a_dev,
        "mem_bytes": mem_bytes,
        "mem_ok": mem_ok,
    }


def _legal(sig: ModelSignature, dp: int, tp: int, pp: int, sp: int,
           microbatches: int) -> bool:
    if sig.batch % dp:
        return False
    if sp > 1 and (not sig.seq_len or sig.seq_len % sp):
        return False
    if tp > 1:
        sharded, _, ok = sig.tp_split(tp)
        if not sig.rules or sharded == 0.0 or not ok:
            return False
    if pp > 1:
        if not sig.stacked_layers or sig.stacked_layers % pp:
            return False
        per_dev_batch = sig.batch // dp
        if per_dev_batch % microbatches:
            return False
    return True


def _factorizations(n: int):
    """Every (dp, tp, pp, sp) with dp*tp*pp*sp == n."""
    divs = [d for d in range(1, n + 1) if n % d == 0]
    for tp in divs:
        for pp in [d for d in divs if (n // tp) % d == 0]:
            rem = n // (tp * pp)
            for sp in [d for d in divs if rem % d == 0]:
                yield rem // sp, tp, pp, sp


def _mk_plan(sig: ModelSignature, dp: int, tp: int, pp: int, sp: int,
             microbatches: int, sp_mode: str) -> Plan:
    from .plan import _axes

    return Plan(mesh_axes=_axes(dp=dp, tp=tp, pp=pp, sp=sp),
                rules=(sig.rules if tp > 1 and sig.rules
                       else ShardingRules()),
                seq_axis=(1 if sp > 1 else None),
                sp_attention=(sp_mode if sp > 1 else "gspmd"),
                pp_microbatches=microbatches)


def enumerate_plans(sig: ModelSignature, n_devices: int,
                    hw: Optional[Hardware] = None,
                    microbatches: int = 4,
                    sp_mode: str = "gspmd") -> List[PlanChoice]:
    """Every LEGAL (dp, tp, pp, sp) factorization of ``n_devices`` for
    ``sig``, costed and ranked: plans that fit the memory budget first
    (ascending predicted step time), over-budget plans after (ascending
    memory) — so the head of the list is "fastest that fits" and a
    memory-infeasible mesh still returns its least-bad candidate
    rather than nothing."""
    hw = hw or Hardware()
    choices: List[PlanChoice] = []
    for dp, tp, pp, sp in _factorizations(int(n_devices)):
        if not _legal(sig, dp, tp, pp, sp, microbatches):
            continue
        plan = _mk_plan(sig, dp, tp, pp, sp, microbatches, sp_mode)
        choices.append(PlanChoice(plan, plan_cost(sig, plan, hw)))
    # tie-break: prefer the SIMPLER layout (fewer non-dp axes) — equal
    # predicted cost should never pick tp/pp/sp machinery over plain dp
    choices.sort(key=lambda c: (
        not c.cost["mem_ok"], c.step_s,
        sum(1 for a in ("tp", "pp", "sp") if c.plan.axis_size(a) > 1),
        c.cost["mem_bytes"]))
    return choices


def _ranking_summary(choices: List[PlanChoice], top: int = 5) -> list:
    return [{
        "strategy": c.plan.strategy,
        "mesh": {n: s for n, s in c.plan.mesh_axes if s > 1} or {"dp": 1},
        "step_s": round(float(c.step_s), 9),
        "mem_ok": bool(c.cost["mem_ok"]),
    } for c in choices[:top]]


def _apply_override(choices: List[PlanChoice], strategy: str) -> PlanChoice:
    if strategy == "auto":
        return choices[0]
    if strategy == "dp":
        pure = [c for c in choices
                if all(c.plan.axis_size(a) == 1 for a in ("tp", "pp", "sp"))]
        if not pure:
            raise MXNetError("MX_PLAN=dp: pure data parallelism is not "
                             "legal here (batch not divisible by the "
                             "device count?)")
        return pure[0]
    axis = {"tp": "tp", "pp": "pp", "sp": "sp", "ring": "sp",
            "ulysses": "sp"}[strategy]
    cands = [c for c in choices if c.plan.axis_size(axis) > 1]
    if not cands:
        raise MXNetError(
            f"MX_PLAN={strategy}: no legal layout uses a {axis}>1 axis "
            f"for this model/mesh (divisibility or missing "
            f"rules/stacked layers/sequence dim)")
    best = cands[0]
    if strategy in ("ring", "ulysses"):
        from dataclasses import replace

        plan = replace(best.plan, sp_attention=strategy)
        best = PlanChoice(plan, best.cost)
    return best


def plan_for(sig: ModelSignature, mesh_or_n, hw: Optional[Hardware] = None,
             strategy: Optional[str] = None,
             microbatches: int = 4) -> Plan:
    """The planner entry point: the best legal Plan for ``sig`` over a
    mesh (or raw device count), with its predicted cost breakdown and
    the top of the ranking attached as ``plan.predicted`` — which
    ``compile_step_with_plan`` records as the ``plan`` telemetry event,
    the predicted-vs-measured hook.

    ``strategy`` (default: the ``MX_PLAN`` env var, default ``auto``)
    overrides the argmin: ``dp``/``tp``/``pp``/``sp`` pin the
    corresponding axis family, ``ring``/``ulysses`` additionally select
    the SP attention mechanism.  Raises when nothing legal exists —
    silence here would train on a wrong layout."""
    n = (mesh_or_n if isinstance(mesh_or_n, int)
         else int(len(list(mesh_or_n.devices.flat))))
    strategy = (strategy or os.environ.get("MX_PLAN") or "auto").lower()
    if strategy not in STRATEGY_NAMES:
        raise MXNetError(f"MX_PLAN={strategy!r}: expected one of "
                         f"{STRATEGY_NAMES}")
    choices = enumerate_plans(sig, n, hw=hw, microbatches=microbatches)
    if not choices:
        raise MXNetError(
            f"planner: no legal dp*tp*pp*sp factorization of {n} devices "
            f"for batch {sig.batch} (seq {sig.seq_len}, layers "
            f"{sig.stacked_layers}) — adjust the batch or the mesh")
    chosen = _apply_override(choices, strategy)
    predicted = dict(chosen.cost)
    predicted["comm"] = {k: float(v) for k, v in predicted["comm"].items()}
    predicted["ranking"] = _ranking_summary(choices)
    predicted["override"] = strategy
    return chosen.plan.with_predicted(predicted)
