"""Fused distributed training step: forward + backward + optimizer in ONE
XLA program over a device mesh.

This is the performance path that replaces the reference's per-batch chain
of engine pushes (CachedOp forward -> backward -> kvstore push/reduce ->
optimizer kernels -> broadcast; SURVEY §3.3).  Here the whole chain is a
single jit: XLA overlaps the gradient reduce-scatter/all-reduce with the
backward pass over ICI and fuses the optimizer update into the gradient
buffers — strictly less launch overhead and less HBM traffic than the
eager path.

Works with any Gluon HybridBlock: its forward is traced into the step
function via the same parameter-substitution trace the CachedOp uses.
"""
from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import aot_cache
from .. import fault
from .. import memwatch
from .. import telemetry
from ..base import MXNetError
from .async_loss import (AsyncLoss, InflightRing, StackedAsyncLoss,
                         SuperstepLossView, inflight_limit)
from .plan import Plan, dp_plan
from .sharding import ShardingRules, replicated, shard_batch

__all__ = ["DataParallelStep", "make_train_step", "compile_step_with_plan",
           "superstep_k", "flush_all_steps", "dp_plan"]

# every live step object in the process, so preemption paths can flush
# buffered-but-undispatched superstep groups they never saw (weak: the
# registry must not keep a dropped step alive)
_live_steps: "weakref.WeakSet" = weakref.WeakSet()


def flush_all_steps() -> List[BaseException]:
    """Dispatch every live step's buffered partial superstep group
    (best-effort, errors collected not raised).  The SIGTERM preemption
    path runs this BEFORE ``async_loss.drain_all``: a buffered
    ``_SuperstepGroup`` was never dispatched, so draining the in-flight
    rings alone would silently drop up to K-1 enqueued steps from the
    final sync checkpoint (the PR 9 known issue)."""
    errors: List[BaseException] = []
    for step in list(_live_steps):
        try:
            step.flush()
        except BaseException as exc:  # noqa: BLE001 — survey, don't die
            errors.append(exc)
    return errors


def superstep_k(mesh=None) -> int:
    """Transparent superstep group size: how many ``step()`` calls are
    batched into ONE compiled ``lax.scan`` dispatch (``MX_SUPERSTEP``,
    re-read per call; 0/unset = off).  Defaults OFF on CPU meshes
    regardless of the value — XLA:CPU runs scan bodies ~4.7x slower than
    standalone steps (ROADMAP item 3 caveat) — unless
    ``MX_SUPERSTEP_FORCE_CPU=1`` (the CPU parity-test override).  The
    explicit :meth:`DataParallelStep.superstep` API is always available;
    this gate only controls the transparent ``step()`` routing."""
    try:
        k = int(os.environ.get("MX_SUPERSTEP", "0") or "0")
    except (TypeError, ValueError):
        return 0
    if k < 1:
        return 0
    if mesh is not None:
        platform = next(iter(mesh.devices.flat)).platform
        if platform == "cpu" and os.environ.get(
                "MX_SUPERSTEP_FORCE_CPU", "0").lower() in (
                    "", "0", "false", "off"):
            return 0
    return k


class _SuperstepGroup:
    """One buffered batch-group awaiting its scan dispatch (transparent
    superstep mode).  ``sig`` is the (shapes, dtypes) signature of the
    group's first batch — a later batch with a different signature (the
    classic ragged final batch) closes the group instead of poisoning
    its stack.  ``handle`` is set exactly once, at dispatch; ``entries``
    are released then (loss views outlive the group and must not pin K
    batches of device input buffers)."""

    __slots__ = ("entries", "handle", "sig")

    def __init__(self, sig=None):
        self.entries: List[dict] = []
        self.handle: Optional[StackedAsyncLoss] = None
        self.sig = sig


def _global_put(arr, sharding):
    """device_put that also works on multi-process (multi-controller)
    meshes: every process passes the same host-global value and installs
    only its addressable shards (the pjit pod-input pattern; the
    reference's analog is each worker feeding its own data slice to its
    local executor)."""
    import jax

    if sharding.is_fully_addressable:
        return jax.device_put(arr, sharding)
    # mxlint: disable=hot-sync — materializes the host INPUT batch for
    # per-shard placement; never a readback of device compute
    host = np.asarray(arr)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: host[idx])


def _maybe_put(arr, sharding):
    """(placed_array, was_preplaced): skip the transfer when ``arr`` is
    already a device array carrying exactly the target sharding — the
    prefetcher/step handshake.  ``io.DevicePrefetchIter`` stages batches
    through ``DataParallelStep.stage()`` onto these same shardings from a
    background thread, and the step must not pay the H2D again."""
    if getattr(arr, "sharding", None) == sharding:
        return arr, True
    return _global_put(arr, sharding), False


def _shard_index_key(idx, shape) -> tuple:
    """Canonical hashable key for one shard's global index: a tuple of
    ``(start, stop)`` per dimension with the open-ended slices jax hands
    back (``slice(None)``) normalized against the array shape, so the
    same shard is the same key no matter which device reported it."""
    key = []
    for dim, s in enumerate(idx):
        start = 0 if s.start is None else int(s.start)
        stop = int(shape[dim]) if s.stop is None else int(s.stop)
        key.append((start, stop))
    return tuple(key)


def _local_shard_split(arr, rank: int, nprocs: int):
    """Split one (possibly sharded) array into its deduplicated shard
    set with a deterministic owner rank per shard — computed ENTIRELY
    from local metadata (``devices_indices_map`` enumerates every
    device's slice on every process), so all ranks derive the identical
    manifest without a single collective.

    Returns ``(shards, payloads)``: ``shards`` is the manifest entry
    (``[{"rank", "j", "slice"}]``, ordered by slice), ``payloads`` the
    ``[(j, ndarray)]`` this rank must persist (empty when it owns none).
    Replicas dedup to one owner: the minimal ``(process_index, id)``
    device holding the shard.  Process-local arrays (a single-device
    scalar every rank holds its own copy of — adam's ``t``) canonicalize
    to one rank-0 full-shape shard so the manifest stays rank-invariant."""
    shape = tuple(int(s) for s in np.shape(arr))
    full = tuple((0, int(s)) for s in shape)
    if nprocs > 1 and getattr(arr, "is_fully_addressable", True):
        shards = [{"rank": 0, "j": 0, "slice": [list(p) for p in full]}]
        if rank != 0:
            return shards, []
        import jax

        # mxlint: disable=hot-sync — checkpoint host snapshot
        return shards, [(0, np.asarray(jax.device_get(arr)))]
    if getattr(arr, "is_fully_replicated", False) or not hasattr(
            arr, "sharding"):
        shards = [{"rank": 0, "j": 0, "slice": [list(p) for p in full]}]
        if rank != 0:
            return shards, []
        if hasattr(arr, "addressable_shards"):
            # mxlint: disable=hot-sync — checkpoint host snapshot
            host = np.asarray(arr.addressable_shards[0].data)
        else:
            host = np.asarray(arr)
        return shards, [(0, host)]
    owners: Dict[tuple, tuple] = {}
    for dev, idx in arr.sharding.devices_indices_map(shape).items():
        key = _shard_index_key(idx, shape)
        cand = (int(dev.process_index), int(dev.id))
        if key not in owners or cand < owners[key]:
            owners[key] = cand
    local = {}
    for sh in arr.addressable_shards:
        local.setdefault(_shard_index_key(sh.index, shape), sh)
    shards, payloads = [], []
    counters: Dict[int, int] = {}
    for key in sorted(owners):
        owner_rank = owners[key][0]
        j = counters.get(owner_rank, 0)
        counters[owner_rank] = j + 1
        shards.append({"rank": owner_rank, "j": j,
                       "slice": [list(p) for p in key]})
        if owner_rank == rank:
            # mxlint: disable=hot-sync — checkpoint host snapshot
            payloads.append((j, np.asarray(local[key].data)))
    return shards, payloads


def _lazy_put(lazy, sharding):
    """Place a lazily-readable sharded-checkpoint value (anything with
    ``read_slice(idx) -> ndarray``) onto ``sharding`` WITHOUT ever
    composing the full array on this host: the callback reads exactly
    the slice each addressable device needs, straight out of the shard
    files that cover it — the N->M elastic restore path at TB scale."""
    import jax

    shape = tuple(int(s) for s in lazy.shape)
    return jax.make_array_from_callback(
        shape, sharding, lambda idx: lazy.read_slice(idx))


def _host_scalar(loss):
    """A replicated (possibly non-fully-addressable) loss -> host scalar
    array via this process's local shard."""
    if getattr(loss, "is_fully_addressable", True):
        return loss
    return np.asarray(loss.addressable_shards[0].data)


def _params_arrays(step):
    """memwatch provider: the sharded parameter buffers this step owns."""
    return list((step.params or {}).values())


def _opt_state_arrays(step):
    """memwatch provider: optimizer-state buffers (momenta/Adam moments)."""
    if step.opt_state is None:
        return ()
    import jax

    return jax.tree_util.tree_leaves(step.opt_state)


def _block_apply_fn(block, ctx, train: bool):
    """Build a pure fn(params_dict, key, *inputs) -> outputs from a Gluon
    block (same mechanism as gluon.block.CachedOp)."""
    from .. import autograd
    from .. import random as _random
    from ..gluon.parameter import begin_trace, end_trace
    from ..ndarray import NDArray

    param_items = list(block.collect_params().items())
    name_of = {p: name for name, p in param_items}

    def fn(param_arrays: Dict[str, Any], key, *input_arrays):
        param_map = {p: NDArray(param_arrays[name], ctx=ctx)
                     for name, p in param_items}
        nd_inputs = [NDArray(a, ctx=ctx) for a in input_arrays]
        prev_trace = begin_trace(param_map, ctx)
        prev_rec = autograd.set_recording(False)
        prev_train = autograd.set_training(train)
        prev_key = _random.set_trace_key_provider(_random._TraceKeyProvider(key))
        try:
            out = block.forward(*nd_inputs)
        finally:
            state = end_trace(prev_trace)
            autograd.set_recording(prev_rec)
            autograd.set_training(prev_train)
            _random.set_trace_key_provider(prev_key)
        aux = [(name_of[p], v._data) for p, v in state["aux"]]
        if isinstance(out, (list, tuple)):
            return [o._data for o in out], aux
        return out._data, aux

    return fn, param_items


def _sgd_tree_update(params, grads, momenta, lr, momentum, wd, rescale, mults,
                     clip=None):
    import jax.numpy as jnp

    new_params, new_momenta = {}, {}
    for name, w in params.items():
        lr_mult, wd_mult = mults.get(name, (1.0, 1.0))
        if lr_mult is None:  # frozen (grad_req='null'): leave untouched
            new_params[name] = w
            new_momenta[name] = momenta[name]
            continue
        g = grads[name].astype(jnp.float32) * rescale
        if clip is not None:  # Optimizer.clip_gradient: after rescale, pre-wd
            g = jnp.clip(g, -clip, clip)
        g = g + wd * wd_mult * w.astype(jnp.float32)
        m = momentum * momenta[name] - lr * lr_mult * g
        new_params[name] = (w.astype(jnp.float32) + m).astype(w.dtype)
        new_momenta[name] = m
    return new_params, new_momenta


def _adam_tree_update(params, grads, state, lr, beta1, beta2, eps, wd, rescale,
                      mults, clip=None):
    import jax.numpy as jnp

    means, vars_, t = state
    t = t + 1
    corr = jnp.sqrt(1 - beta2**t) / (1 - beta1**t)
    new_p, new_m, new_v = {}, {}, {}
    for name, w in params.items():
        lr_mult, wd_mult = mults.get(name, (1.0, 1.0))
        if lr_mult is None:  # frozen
            new_p[name] = w
            new_m[name] = means[name]
            new_v[name] = vars_[name]
            continue
        g = grads[name].astype(jnp.float32) * rescale
        if clip is not None:
            g = jnp.clip(g, -clip, clip)
        g = g + wd * wd_mult * w.astype(jnp.float32)
        m = beta1 * means[name] + (1 - beta1) * g
        v = beta2 * vars_[name] + (1 - beta2) * jnp.square(g)
        new_p[name] = (w.astype(jnp.float32)
                       - lr * lr_mult * corr * m / (jnp.sqrt(v) + eps)).astype(w.dtype)
        new_m[name] = m
        new_v[name] = v
    return new_p, (new_m, new_v, t)


class DataParallelStep:
    """Compiled train step for a Gluon block over a mesh.

    Parameters live as sharded jax arrays owned by this object (master fp32
    optionally); sync_to_block() writes them back into the Gluon parameters.
    """

    _instance_counter = 0

    def __init__(self, block, loss_fn: Callable, mesh=None,
                 optimizer: str = "sgd", optimizer_params: Optional[Dict] = None,
                 rules: Optional[ShardingRules] = None,
                 batch_axes: Sequence[str] = ("dp", "sp"),
                 seq_axis: Optional[int] = None,
                 donate: bool = True, remat: bool = False,
                 ring_attention: bool = False, accum_steps: int = 1,
                 clip_global_norm: Optional[float] = None,
                 pp_microbatches: int = 4,
                 plan: Optional[Plan] = None,
                 precision=None):
        """seq_axis: which input dim is the sequence dim for sequence
        parallelism over an 'sp' mesh axis.  None (default) auto-detects:
        dim 1 is treated as the sequence dim only when it is divisible by
        the sp axis size; otherwise (e.g. NCHW/NHWC image batches) the
        batch dim is sharded over dp*sp as plain data parallelism.  Pass
        seq_axis=1 to force SP, seq_axis=-1 to disable it.

        remat: rematerialize the forward in the backward pass
        (jax.checkpoint over the block apply) — trades ~1 extra forward of
        FLOPs for not storing activations, the HBM lever for large
        per-chip batches (reference analog: MXNet memonger/mirror).

        ring_attention: with an active sp>1 axis, fused-attention ops in
        the model lower to a sequence-parallel kernel instead of GSPMD's
        K/V all-gather.  True/'ring': K/V rotate over ICI via ppermute
        (online softmax, per-device attention memory O((L/sp)^2)).
        'ulysses': one all-to-all reshards heads so attention runs
        locally over the full sequence (constant collective count; head
        count must divide by sp).

        clip_global_norm: clip the rescaled gradients to this global L2
        norm INSIDE the fused program (gluon.utils.clip_global_norm
        semantics, but compiled: one fused norm reduction over every
        trainable gradient, then one scalar scale).  Composable with the
        per-element Optimizer `clip_gradient` (optimizer_params), which
        applies after it, matching Trainer-then-optimizer order.

        pp_microbatches: GPipe microbatch count when the mesh has a pp>1
        axis.  Models built on a stacked encoder (models/bert_pp.py)
        consult the pipeline scope this step activates and route their
        layer stack through the compiled ppermute schedule; models
        without a stacked encoder simply ignore the scope (their pp-axis
        devices then duplicate dp work — shard params over pp via rules
        only with a pipeline-capable model).  pp currently composes with
        dp (batch dim); not with active sequence parallelism.

        accum_steps: gradient accumulation INSIDE the fused step — the
        batch is split into accum_steps contiguous microbatches, each
        forward/backward runs in turn (activation memory is one
        microbatch's), gradients average, then ONE optimizer update.
        Statically unrolled in the XLA program; combine with remat=True
        for maximum effective batch per chip (reference analog:
        grad_req='add' + delayed Trainer.step).

        precision: a :class:`~mxnet_tpu.precision.config.PrecisionConfig`
        — the graph-level AMP cast policy and/or traced dynamic loss
        scaling (docs/PRECISION.md).  Carried on the Plan (so it rides
        into checkpoint layouts and elastic restores); ``MX_AMP`` /
        ``MX_LOSS_SCALE`` provide the env default when neither the plan
        nor this kwarg sets one.  With no precision config, the built
        step program is byte-for-byte the pre-precision f32 program.

        plan: a :class:`~mxnet_tpu.parallel.plan.Plan` carrying ALL of
        the strategy knobs above (rules/batch_axes/seq_axis/
        ring_attention/accum_steps/pp_microbatches) as one value — the
        unified path ``compile_step_with_plan`` uses; the individual
        kwargs then must stay at their defaults.  Without a plan, this
        constructor is itself the dp-era compat shim: it builds the
        equivalent Plan from its kwargs, so every step — legacy or
        plan-built — flows through the same plan-driven dispatch."""
        import jax

        from ..context import current_context

        if plan is not None:
            clash = [kw for kw, val, dflt in (
                ("rules", rules, None),
                ("batch_axes", tuple(batch_axes), ("dp", "sp")),
                ("seq_axis", seq_axis, None),
                ("ring_attention", ring_attention, False),
                ("accum_steps", accum_steps, 1),
                ("pp_microbatches", pp_microbatches, 4),
                ("precision", precision, None),
            ) if val != dflt]
            if clash:
                raise MXNetError(
                    f"DataParallelStep: both plan= and strategy kwargs "
                    f"{clash} given — the Plan already carries them")
            if mesh is None:
                mesh = plan.build_mesh()
            elif not plan.matches_mesh(mesh):
                raise MXNetError(
                    f"Plan axes {dict(plan.mesh_axes)} do not match the "
                    f"given mesh {dict(mesh.shape)}")
        else:
            if mesh is None:
                from .mesh import local_mesh

                mesh = local_mesh()
            if ring_attention not in (True, False, "ring", "ulysses"):
                raise MXNetError("ring_attention must be bool, 'ring' or "
                                 f"'ulysses', got {ring_attention!r}")
            sp_mode = ("gspmd" if ring_attention is False
                       else "ring" if ring_attention is True
                       else ring_attention)
            if sp_mode != "gspmd" and dict(mesh.shape).get("sp", 1) < 2 \
                    and seq_axis != 1:
                # legacy tolerance: ring_attention on a mesh with no sp
                # axis was inert (the scope only activates with a
                # sequence-sharded input) — keep it inert, not an error
                sp_mode = "gspmd"
            plan = Plan(
                mesh_axes=tuple(mesh.shape.items()),
                rules=rules or ShardingRules(),
                # shard_batch ignores absent axes; the Plan is strict
                # about naming only real ones
                batch_axes=tuple(a for a in batch_axes
                                 if a in mesh.axis_names),
                seq_axis=seq_axis,
                sp_attention=sp_mode,
                pp_microbatches=int(pp_microbatches),
                accum_steps=int(accum_steps),
                precision=precision)
        if plan.precision is None:
            # env default (MX_AMP / MX_AMP_POLICY / MX_LOSS_SCALE), read
            # ONCE here: the resolved config becomes part of the Plan —
            # and therefore of checkpoint layouts and executable
            # fingerprints — so a mid-run env flip cannot silently split
            # the program from its recorded identity
            from dataclasses import replace as _dc_replace

            from ..precision.config import PrecisionConfig

            env_precision = PrecisionConfig.from_env()
            if env_precision is not None:
                plan = _dc_replace(plan, precision=env_precision)
        self.plan = plan
        self._precision = plan.precision
        self._loss_scale_cfg = (plan.precision.loss_scale
                                if plan.precision is not None else None)
        # the training pass pipeline (passes/builtin): the Plan's AMP
        # policy + fused-kernel substitution (MX_PALLAS_FUSED), subject
        # to MX_PASSES toggles.  _build wraps the block apply with it,
        # and its ONE signature joins the executable fingerprint below.
        from ..passes.builtin import pipeline_for_training

        self._pipeline = pipeline_for_training(plan.precision)
        self.mesh = mesh
        self.block = block
        self.loss_fn = loss_fn
        opt_params = dict(optimizer_params or {})
        self._lr = opt_params.get("learning_rate", 0.01)
        # lr is a DEVICE SCALAR ARGUMENT of the compiled step (not a trace
        # constant), so schedules/manual set_learning_rate never retrace
        self._lr_scheduler = opt_params.get("lr_scheduler")
        if self._lr_scheduler is not None:
            self._lr_scheduler.base_lr = self._lr
        self._clip_gradient = opt_params.get("clip_gradient")
        self._clip_global = clip_global_norm
        self._momentum = opt_params.get("momentum", 0.9)
        self._wd = opt_params.get("wd", 0.0)
        self._beta1 = opt_params.get("beta1", 0.9)
        self._beta2 = opt_params.get("beta2", 0.999)
        self._eps = opt_params.get("epsilon", 1e-8)
        self._rescale = opt_params.get("rescale_grad", 1.0)
        self._optimizer = optimizer
        self._donate = donate
        self._remat = remat

        ctx = current_context()
        self._ctx = ctx
        self._apply, self._param_items = _block_apply_fn(block, ctx, train=True)
        # frozen params (grad_req='null') are marked with lr_mult=None and
        # skipped by the tree updates; others carry their lr/wd multipliers
        self._mults = {
            n: ((None, None) if p.grad_req == "null"
                else (p.lr_mult, p.wd_mult))
            for n, p in self._param_items
        }

        if optimizer not in ("sgd", "adam"):
            raise MXNetError(f"fused step supports sgd/adam, got {optimizer}")
        # per-instance telemetry key: two fused steps over same-class
        # blocks must not pool retrace signatures (false-storm warnings)
        DataParallelStep._instance_counter += 1
        self._tele_name = (f"DataParallelStep:{type(block).__name__}"
                           f"#{DataParallelStep._instance_counter}")
        self.params = None
        self.opt_state = None
        # traced loss-scale state (docs/PRECISION.md): replicated device
        # scalars {scale, growth, skipped} threaded through the jitted
        # step; None when the plan carries no loss-scale config
        self.scaler_state = None
        self._shardings = None
        self._jitted = None
        self._step_count = 0
        # superstep mode (docs/PERFORMANCE.md §Superstep): buffered
        # batch-group awaiting one lax.scan dispatch, the per-length scan
        # executables, their AOT-cache resolutions, and the per-shape
        # device stackers that build the scanned (K, B, ...) inputs
        self._open_group: Optional[_SuperstepGroup] = None
        self._super_jits: Dict[int, Any] = {}
        self._super_aot: Dict[Any, Any] = {}
        self._stackers: Dict[Any, Any] = {}
        # single-step AOT executables (MX_EXECUTABLE_CACHE_DIR): one per
        # input signature (alternating shapes — bucketed lengths,
        # train/eval interleave — must reuse in memory, not re-hit disk);
        # False = resolution failed, stay on the plain jit path
        self._aot_execs: Dict[Any, Any] = {}
        self._last_cache_info: Dict[str, Any] = {}
        # bounded async dispatch window (MX_ASYNC_INFLIGHT handles pending
        # at once); the device prefetcher's staging thread and step() may
        # both trigger first-use state init, hence the lock
        self._inflight = InflightRing(self._tele_name)
        self._state_lock = threading.Lock()
        # deferred compile record: _step_impl (the hot path — which must
        # never run memory/analysis APIs, mxlint hot-sync) stamps what it
        # knows at the traced call; step() hands it to memwatch after
        self._pending_compile: Optional[Dict[str, Any]] = None
        # compiled allgather for state_dict's sharded->host baseline,
        # built lazily once per step object
        self._gather_jit = None
        # live-array census attribution (docs/OBSERVABILITY.md §Memory):
        # weak registration — the watchdog never keeps this step alive
        memwatch.register("params", self, _params_arrays)
        memwatch.register("optimizer", self, _opt_state_arrays)
        # preemption paths flush buffered superstep groups via this
        # process-wide registry (flush_all_steps)
        _live_steps.add(self)

    def _ensure_state(self, example_inputs):
        """Gather params (resolving deferred init via one eager forward) and
        shard them per the rules.  Thread-safe: a DevicePrefetchIter's
        background stage() may race the first step() here."""
        import jax

        if self.params is not None:
            return
        with self._state_lock:
            if self.params is not None:
                return
            from .. import autograd
            from ..gluon.parameter import DeferredInitializationError

            try:
                for _, p in self._param_items:
                    p.data()
            except DeferredInitializationError:
                with autograd.pause(train_mode=True):
                    self.block(*example_inputs)
            names = [n for n, _ in self._param_items]
            shapes = {n: tuple(p.data().shape) for n, p in self._param_items}
            self._shardings = self.plan.rules.shardings(self.mesh, shapes)
            params = {
                n: _global_put(p.data()._data, self._shardings[n])
                for n, p in self._param_items
            }
            if self._optimizer == "sgd":
                self.opt_state = {
                    n: _global_put(np.zeros(shapes[n], np.float32),
                                   self._shardings[n])
                    for n in names
                }
            else:
                z = {n: _global_put(np.zeros(shapes[n], np.float32),
                                    self._shardings[n]) for n in names}
                z2 = {n: _global_put(np.zeros(shapes[n], np.float32),
                                     self._shardings[n]) for n in names}
                self.opt_state = (z, z2,
                                  jax.numpy.zeros((), jax.numpy.int32))
            if self._loss_scale_cfg is not None and \
                    self.scaler_state is None:
                from ..precision import loss_scale as _ls

                repl = replicated(self.mesh)
                self.scaler_state = {
                    k: _global_put(v, repl)
                    for k, v in _ls.init_scaler_host(
                        self._loss_scale_cfg).items()
                }
            # publish params LAST: it is the unlocked fast-path check
            self.params = params

    # ------------------------------------------------------------------
    def _build(self):
        import jax
        import jax.numpy as jnp

        from jax.sharding import NamedSharding, PartitionSpec

        apply_fn = self._apply
        if self._remat:
            # jax.checkpoint only accepts JAX-typed outputs: strip the
            # static aux NAMES (strings) out of the rematerialized region
            # and re-pair them outside — they're trace-stable for a block
            base, names_cell = apply_fn, []

            def _arrays_only(params, key, *xs):
                out, aux = base(params, key, *xs)
                if not names_cell:
                    names_cell.append([n for n, _ in aux])
                return out, [v for _, v in aux]

            ck = jax.checkpoint(_arrays_only)

            def apply_fn(params, key, *xs):
                out, vals = ck(params, key, *xs)
                return out, list(zip(names_cell[0], vals))
        # the pass pipeline wraps the block apply (docs/PRECISION.md
        # §Pass pipeline): AMP's policy scope is active during THIS
        # trace only, so the whole mixed-precision program lands in the
        # one compiled executable (outputs widen to f32 at the
        # boundary); fused-kernel substitution swaps Pallas kernels at
        # the dispatch point.  An empty pipeline returns apply_fn
        # itself — the bitwise pre-pipeline program.
        apply_fn = self._pipeline.wrap_apply(apply_fn)
        loss_fn = self.loss_fn
        opt = self._optimizer
        momentum, wd, rescale = self._momentum, self._wd, self._rescale
        beta1, beta2, eps = self._beta1, self._beta2, self._eps
        clip_elem, clip_global = self._clip_gradient, self._clip_global
        mults = self._mults

        ctx = self._ctx

        def loss_of(params, key, data, label):
            from ..ndarray import NDArray

            out, aux = apply_fn(params, key, *data)  # data: tuple of arrays
            out_nd = (NDArray(out, ctx=ctx) if not isinstance(out, list)
                      else [NDArray(o, ctx=ctx) for o in out])
            loss = loss_fn(out_nd, NDArray(label, ctx=ctx))
            larr = loss._data if isinstance(loss, NDArray) else loss
            return jnp.mean(larr.astype(jnp.float32)), aux

        accum = self.plan.accum_steps
        ls_cfg = self._loss_scale_cfg

        def _update_core(params, opt_state, key, lr, data, label, scale):
            """ONE copy of the grad/accum/clip/optimizer body shared by
            ``step`` and ``scaled_step``.  ``scale=None`` is the plain
            f32 program — no scaling op is emitted, so the unscaled
            trace stays byte-identical to the pre-AMP step (pinned by
            the AMP-off bitwise test).  A device ``scale`` folds the
            loss multiply in before value_and_grad and the un-scale into
            the optimizer's rescale multiply (zero extra HBM passes over
            the gradient buffers).  Returns grads too, for the caller's
            overflow check."""
            if scale is None:
                vg_target = loss_of
            else:
                def vg_target(params, key, data, label):
                    loss, aux = loss_of(params, key, data, label)
                    return loss * scale, (loss, aux)

            def run_vg(p, k, d, l):
                out, grads = jax.value_and_grad(
                    vg_target, has_aux=True)(p, k, d, l)
                loss, aux = out if scale is None else out[1]
                return loss, aux, grads

            if accum == 1:
                loss, aux, grads = run_vg(params, key, data, label)
            else:
                # statically-unrolled microbatch loop.  STRIDED slices
                # (rows i::accum): each microbatch draws an equal share of
                # every device's dp shard, so no per-microbatch resharding
                # collective and no idle devices (a contiguous B/accum
                # block would live on only dp/accum of the devices)
                keys = jax.random.split(key, accum)
                grads, loss, aux_sums = None, 0.0, {}
                for i in range(accum):
                    def mb(a, _i=i):
                        return a[_i::accum]
                    l_i, aux, g_i = run_vg(
                        params, keys[i], tuple(mb(a) for a in data),
                        mb(label))
                    loss = loss + l_i / accum
                    # aux (BN batch stats) averages over ALL microbatches,
                    # keeping the "global batch average" contract below
                    for name, val in aux:
                        prev = aux_sums.get(name)
                        aux_sums[name] = val if prev is None else prev + val
                    grads = (g_i if grads is None else jax.tree_util.tree_map(
                        lambda a, b: a + b, grads, g_i))
                grads = jax.tree_util.tree_map(lambda g: g / accum, grads)
                aux = [(n, v / accum) for n, v in aux_sums.items()]
            base_rescale = rescale if scale is None else rescale / scale
            eff_rescale = base_rescale
            if clip_global is not None:
                # ONE fused global-norm reduction over the rescaled grads of
                # the trainable params, folded into the per-param rescale
                sq = sum(
                    jnp.sum(jnp.square(grads[n].astype(jnp.float32)
                                       * base_rescale))
                    for n in grads if mults.get(n, (1.0, 1.0))[0] is not None)
                gnorm = jnp.sqrt(sq)
                eff_rescale = base_rescale * jnp.minimum(
                    1.0, clip_global / (gnorm + 1e-12))
            if opt == "sgd":
                new_params, new_state = _sgd_tree_update(
                    params, grads, opt_state, lr, momentum, wd, eff_rescale,
                    mults, clip_elem)
            else:
                new_params, new_state = _adam_tree_update(
                    params, grads, opt_state, lr, beta1, beta2, eps, wd,
                    eff_rescale, mults, clip_elem)
            # aux (BN stats): already averaged over the global batch by XLA
            for name, val in aux:
                new_params[name] = val.astype(new_params[name].dtype)
            return new_params, new_state, loss, grads

        def step(params, opt_state, key, lr, data, label):
            new_params, new_state, loss, _grads = _update_core(
                params, opt_state, key, lr, data, label, None)
            return new_params, new_state, loss

        def scaled_step(params, opt_state, scaler, key, lr, data, label):
            """The loss-scaled twin of ``step`` (docs/PRECISION.md):
            same ``_update_core`` with the scale folded in, overflow
            detection is one fused isfinite reduce, and a non-finite
            step SELECTS the old params/opt_state — a traced no-op
            update.  The scaler state machine transitions as device
            values; no host readback ever enters this body."""
            from ..precision import loss_scale as _ls

            new_params, new_state, loss, grads = _update_core(
                params, opt_state, key, lr, data, label, scaler["scale"])
            finite = _ls.grads_finite(grads, mults)
            # skip-step selection: weights, momenta, Adam's t AND the
            # forward's aux stats all hold when any grad is non-finite
            def hold(new, old):
                return jax.tree_util.tree_map(
                    lambda a, b: jnp.where(finite, a, b), new, old)

            new_params = hold(new_params, params)
            new_state = hold(new_state, opt_state)
            new_scaler = _ls.scaler_update(scaler, finite, ls_cfg)
            return new_params, new_state, new_scaler, loss

        repl = replicated(self.mesh)
        # XLA:CPU's runtime aliasing check rejects a donated param whose
        # incoming layout/sharding differs from its out_sharding
        # ("INTERNAL: Expected aliased input ... to have the same size",
        # seen on dp×tp CPU meshes).  Donation only saves device memory,
        # so keep it for accelerators and skip it on CPU hosts.
        mesh_platform = next(iter(self.mesh.devices.flat)).platform
        donate = (0, 1) if (self._donate and mesh_platform != "cpu") else ()
        # built ONCE per step object (guarded by `self._jitted is None`
        # in _step_impl); ls_cfg is construction-time state, so exactly
        # one of the two programs ever exists per step object
        if ls_cfg is None:
            # mxlint: disable=retrace-hazard — built once per step object
            self._jitted = jax.jit(
                step,
                out_shardings=(self._shardings, None, repl),
                donate_argnums=donate,
            )
        else:
            # mxlint: disable=retrace-hazard — built once per step object
            self._jitted = jax.jit(
                scaled_step,
                out_shardings=(self._shardings, None, None, repl),
                donate_argnums=donate,
            )

    # ------------------------------------------------------------------
    def _input_shardings(self, data_arrs, label_arr):
        """Per-input shardings for one batch -> (data_shardings,
        label_sharding, sp_active).  Shared by step() and the prefetcher's
        stage() so both place inputs identically (the handshake contract).

        With an active 'sp' axis, the sequence dim (1) shards over it:
        true sequence parallelism — GSPMD emits the cross-device
        collectives for attention over the sharded T axis.  Gated (r3
        advisor): only when the caller opted in via seq_axis=1, or in auto
        mode when dim 1 is actually divisible by the sp size — image
        batches (NCHW: dim 1 = 3 channels) fall back to plain dp*sp batch
        sharding."""
        sp_active = (
            "sp" in self.mesh.axis_names
            and self.mesh.shape["sp"] > 1
            and "sp" in self.plan.batch_axes
            and self.plan.seq_axis != -1
            and any(np.ndim(a) >= 2 for a in data_arrs)
        )
        if sp_active and self.plan.seq_axis is None:
            sp_active = all(np.shape(a)[1] % self.mesh.shape["sp"] == 0
                            for a in data_arrs if np.ndim(a) >= 2)
        if self.plan.seq_axis == 1 and sp_active:
            # explicit SP opt-in: a non-divisible seq dim is a caller error,
            # not something to silently decline (the ring scope and the
            # shard specs must agree on what was sequence-sharded)
            bad = [np.shape(a) for a in data_arrs
                   if np.ndim(a) >= 2
                   and np.shape(a)[1] % self.mesh.shape["sp"] != 0]
            if bad:
                raise MXNetError(
                    f"seq_axis=1: sequence dims of {bad} are not divisible "
                    f"by sp={self.mesh.shape['sp']}")

        def _shard_one(arr):
            if (sp_active and np.ndim(arr) >= 2
                    and np.shape(arr)[1] % self.mesh.shape["sp"] == 0):
                from .sharding import shard_batch_seq

                return shard_batch_seq(self.mesh, np.ndim(arr))
            if sp_active:  # rank-1 (or ragged) input under SP: dp only
                return shard_batch(self.mesh, ("dp",), np.ndim(arr))
            return shard_batch(self.mesh, self.plan.batch_axes, np.ndim(arr))

        return (tuple(_shard_one(a) for a in data_arrs),
                _shard_one(label_arr), sp_active)

    def stage(self, data, label):
        """Pre-place one batch onto this step's input shardings (the
        device-side prefetch half of the pipeline) -> (data_tuple, label)
        of device-backed NDArrays.  Called from ``io.DevicePrefetchIter``'s
        background thread while the current step computes; a later
        ``step()`` recognizes the placement and skips its own transfer.
        Values are bit-identical either way — staging only moves WHEN the
        H2D copy happens."""
        from ..ndarray import NDArray

        datas = tuple(data) if isinstance(data, (tuple, list)) else (data,)
        datas = tuple(d if isinstance(d, NDArray)
                      else NDArray(d, ctx=self._ctx) for d in datas)
        self._ensure_state(datas)
        data_arrs = tuple(d._data for d in datas)
        label_arr = (label._data if isinstance(label, NDArray) else label)
        data_sh, label_sh, _sp = self._input_shardings(data_arrs, label_arr)
        staged = tuple(
            NDArray(_maybe_put(a, s)[0], ctx=self._ctx)
            for a, s in zip(data_arrs, data_sh))
        staged_label = (None if label is None else
                        NDArray(_maybe_put(label_arr, label_sh)[0],
                                ctx=self._ctx))
        return staged, staged_label

    def step(self, data, label):
        """One fused training step; returns a lazy :class:`AsyncLoss`.

        Dispatch is non-blocking (jax queues the execution): the handle's
        ``float()`` / ``.asnumpy()`` / ``.wait()`` force the host readback,
        so compute for step N overlaps host prep for step N+1.  At most
        ``MX_ASYNC_INFLIGHT`` (default 2) steps may be pending — admitting
        one more blocks on the oldest first; ``MX_ASYNC_INFLIGHT=0``
        forces every step at dispatch (the old synchronous behavior, same
        numbers: asynchrony never changes what is computed).

        `data` may be a single NDArray or a tuple/list of NDArrays for
        multi-input blocks (e.g. the seq2seq Transformer's (src, tgt)).

        With telemetry spans on (docs/OBSERVABILITY.md §Tracing), the call
        is traced as a ``train_step`` span with ``block_wait`` /
        ``input_stage`` / ``dispatch`` sub-spans — the per-phase timing
        ``tools/trace_report.py`` aggregates into the gang-wide step
        breakdown.  Spans observe only; the computation is bitwise
        identical with ``MX_TELEMETRY_SPANS=0``.

        Superstep mode (``MX_SUPERSTEP=K``, docs/PERFORMANCE.md
        §Superstep): ``step()`` transparently buffers the batch and
        returns a lazy per-step view; every K-th call dispatches the
        whole group as ONE compiled ``lax.scan`` over the same step
        program — one device dispatch, one telemetry span, one compile
        event per group size.  Per-step lr schedule values and RNG keys
        are drawn at buffer time in step order, so schedules and losses
        stay faithful to sequential dispatch.  Off by default on CPU
        meshes (see :func:`superstep_k`)."""
        k = superstep_k(self.mesh)
        if k >= 1:
            view, group = self._superstep_enqueue(data, label)
            if len(group.entries) >= k:
                self._dispatch_group(group)
            memwatch.on_step(view.step)
            return view
        if self._open_group is not None and self._open_group.entries:
            # MX_SUPERSTEP flipped off mid-run with steps still buffered:
            # land them first so dispatch order matches call order
            self.flush()
        with telemetry.span("train_step", executor=self._tele_name):
            handle = self._step_impl(data, label)
        self._book_pending_compile()
        memwatch.on_step(self._step_count)
        return handle

    def _book_pending_compile(self) -> None:
        """Land the deferred compile record stamped by the hot dispatch
        body — HERE, outside it: note_compile may retrace for cost
        analysis, which is a once-per-executable fact, not a per-step
        one.  AOT-cache facts (cache_hit, deserialize_ms) ride along; a
        cache-hit executable skips the analysis retrace entirely (the
        python step fn was never traced — that skip IS the win)."""
        pend, self._pending_compile = self._pending_compile, None
        if pend is None:
            return
        memwatch.note_compile(self._tele_name, pend["parts"],
                              pend["wall_s"],
                              site=pend.get("site", "data_parallel"),
                              jitted=pend.get("jitted"), args=pend["args"],
                              **pend.get("extra", {}))

    def _step_impl(self, data, label):
        import jax

        from .. import random as _random
        from ..ndarray import NDArray

        t0 = time.perf_counter()
        datas = tuple(data) if isinstance(data, (tuple, list)) else (data,)
        datas = tuple(d if isinstance(d, NDArray) else NDArray(d, ctx=self._ctx)
                      for d in datas)
        # retrace detection: jit specializes on input shapes/dtypes, so a
        # new signature on an already-built step means XLA recompiles —
        # report it (telemetry warns after the limit) and tag this step's
        # wall time as compile, not steady-state execute.  The AOT path
        # needs the same signature to key its executable, so it pays the
        # tuple build even with detection off.
        name = self._tele_name
        aot_on = aot_cache.enabled()
        sig = (self._sig_of(datas, label)
               if (telemetry.retrace_enabled() or aot_on) else None)
        if telemetry.retrace_enabled():
            traced = telemetry.note_signature(name, sig)
        else:  # detection off: still split the first-call compile out
            traced = self._jitted is None
        if self.plan.accum_steps > 1:
            label_dim0 = (label.shape[0] if hasattr(label, "shape") else
                          np.shape(label)[0])
            for dim0 in [d.shape[0] for d in datas] + [label_dim0]:
                if dim0 % self.plan.accum_steps:
                    raise MXNetError(
                        f"batch {dim0} not divisible by "
                        f"accum_steps={self.plan.accum_steps}")
        self._ensure_state(datas)
        if self._jitted is None:
            self._build()
        # bounded window: block on the OLDEST pending step only when the
        # ring is full, BEFORE paying this batch's placement — the
        # remaining in-flight steps keep the device busy meanwhile
        limit = inflight_limit()
        block_wait_s = 0.0
        if limit > 0:
            bw0 = time.perf_counter()
            # wait_span=False: the interval below IS this step's
            # block_wait span; the inner wait emitting loss_wait over the
            # same wall would double-count the phase breakdown
            block_wait_s = self._inflight.make_room(limit,
                                                    wait_span=False)
            if block_wait_s > 0.0:
                # retro span: a non-blocking make_room (the common case
                # once the pipeline is in steady state with a free slot)
                # must not pay a begin/end event pair for a 0ms fact
                telemetry.record_span("block_wait", bw0,
                                      bw0 + block_wait_s)
        with telemetry.span("input_stage"):
            data_arrs = tuple(d._data for d in datas)
            label_arr = label._data if isinstance(label, NDArray) else label
            data_sh, label_sh, sp_active = self._input_shardings(
                data_arrs, label_arr)
            overlapped = 0
            placed = []
            for a, s in zip(data_arrs, data_sh):
                arr, pre = _maybe_put(a, s)
                placed.append(arr)
                if pre:
                    overlapped += int(getattr(arr, "nbytes", 0))
            data_arrs = tuple(placed)
            label_arr, pre = _maybe_put(label_arr, label_sh)
            if pre:
                overlapped += int(getattr(label_arr, "nbytes", 0))
        key = _random.next_key()
        lr_val = np.float32(self._current_lr(self._step_count + 1))
        with telemetry.span("dispatch", step=self._step_count + 1,
                            traced=traced):
            scaled = self.scaler_state is not None
            call_args = ((self.params, self.opt_state, self.scaler_state,
                          key, lr_val, data_arrs, label_arr) if scaled
                         else (self.params, self.opt_state, key, lr_val,
                               data_arrs, label_arr))
            resolve = ((lambda a, p: self._resolve_aot(sig, a, p))
                       if aot_on else None)
            outs = self._plan_dispatch(
                self._jitted, call_args, (self._step_count + 1,),
                sp_active, resolve,
                f"FusedStep:{type(self.block).__name__}")
            if scaled:
                (self.params, self.opt_state, self.scaler_state,
                 loss) = outs
            else:
                self.params, self.opt_state, loss = outs
        if traced and telemetry.enabled():
            # what step() needs to book the compile once the hot body is
            # done: structural fingerprint parts + arg shape mirrors
            # (metadata only — the placed buffers are not kept alive)
            cache_info = self._last_cache_info
            self._last_cache_info = {}
            self._pending_compile = {
                "parts": self._fingerprint_parts(
                    (), sig if sig is not None
                    else self._sig_of(data_arrs, label_arr)),
                "wall_s": time.perf_counter() - t0,
                "args": memwatch.shape_structs(
                    (self.params, self.opt_state, key, lr_val,
                     data_arrs, label_arr)),
                "site": "data_parallel",
                # a deserialized executable never traced the python step
                # fn — don't pay that trace just for cost analysis
                "jitted": (None if cache_info.get("cache_hit")
                           else self._jitted),
                "extra": cache_info,
            }
        self._step_count += 1
        handle = AsyncLoss(loss, step=self._step_count, executor=name,
                           ring=self._inflight, host_fn=_host_scalar)
        depth = self._inflight.admit(handle) if limit > 0 else 0
        if telemetry.enabled():
            samples = int(np.shape(label_arr)[0]) if np.ndim(label_arr) else 1
            xfer = sum(int(getattr(a, "nbytes", 0))
                       for a in data_arrs + (label_arr,))
            telemetry.record_step(name, step=self._step_count,
                                  wall_s=time.perf_counter() - t0,
                                  samples=samples, transfer_bytes=xfer,
                                  traced=traced, h2d_overlapped=overlapped,
                                  inflight_depth=depth,
                                  block_wait_ms=round(block_wait_s * 1e3, 3))
            # (no record_block_wait here: make_room's internal wait()
            # already recorded the blocked time — recording the returned
            # duration again would double the rollup)
            # heartbeat advances at DISPATCH, not readback: a supervisor
            # watching a deeply pipelined rank must see it making progress
            telemetry.heartbeat(self._step_count)
        if limit == 0:
            handle.wait()  # synchronous mode: errors surface right here
        return handle

    # ------------------------------------------------------------------
    # shared signature/fingerprint/scope helpers
    # ------------------------------------------------------------------
    @staticmethod
    def _sig_of(arrs, label):
        """Canonical (shapes, dtypes) signature of one batch — keys the
        retrace detector, the AOT executable resolution, and the
        restart-stable fingerprint.  Accepts NDArrays or raw arrays."""
        def one(a):
            data = getattr(a, "_data", a)
            return (tuple(np.shape(data)),
                    str(np.dtype(getattr(data, "dtype", np.float32))))

        return (tuple(one(a) for a in arrs), one(label))

    def _fingerprint_parts(self, variant: Tuple, shape_sig) -> Tuple:
        """Structural identity of one step executable (shapes/dtypes/
        static hypers/mesh axes — no object ids, restart-stable: the
        memwatch.fingerprint + AOT-cache key contract).  ``variant``
        distinguishes executable families over the same step program,
        e.g. ``("superstep", K)``."""
        # hypers baked into the trace as CONSTANTS are executable
        # identity too: two steps differing only in momentum (or remat,
        # or the loss class) compile different programs and must not
        # collide on the restart-stable fingerprint
        hyper_sig = (self._momentum, self._wd, self._rescale,
                     self._beta1, self._beta2, self._eps,
                     self._clip_gradient, self._clip_global,
                     self._remat, self.plan.sp_attention,
                     self.plan.pp_microbatches,
                     self.plan.batch_axes, self.plan.seq_axis,
                     type(self.loss_fn).__name__,
                     tuple(sorted(self._mults.items())),
                     # the AMP policy + loss-scale config are executable
                     # identity: a restart under a different MX_AMP /
                     # MX_LOSS_SCALE must MISS the AOT cache, not load
                     # the other precision's program
                     self._precision.signature()
                     if self._precision is not None else None,
                     # the ONE pass-pipeline signature: any config or
                     # order change (pass toggled, fused set grown, AMP
                     # policy swapped) changes the fingerprint
                     self._pipeline.signature())
        return (("DataParallelStep",) + tuple(variant)
                + (type(self.block).__name__,
                   self._optimizer, self.plan.accum_steps, hyper_sig,
                   tuple(self.mesh.shape.items()), shape_sig))

    def _plan_dispatch(self, fn, call_args, step_nos, sp_active,
                       resolve_aot, profile_label):
        """THE unified dispatch body: every compiled-step execution —
        single step or superstep scan, whatever strategy the Plan
        encodes (dp/tp/pp/ring/ulysses and their compositions) — runs
        through here.  Per covered step the chaos/fault hook fires
        (`oom:step=N` raises a synthetic RESOURCE_EXHAUSTED exactly
        where a real HBM exhaustion would); the plan's trace-time
        scopes activate (pallas platform override, ring/ulysses SP
        routing, pipeline microbatch schedule); ``resolve_aot`` swaps
        in the persistent AOT executable when warm (INSIDE the scopes —
        a cache MISS lowers the program here, and the scope flags are
        trace-time facts); the profiler wrap and the OOM post-mortem
        close the loop.  ``step_nos`` are the logical step numbers the
        dispatch covers (one for a single step, K for a superstep)."""
        from ..ops import pallas as _pk

        from .. import profiler

        # Pallas kernels must lower for the platform the MESH runs on
        # (a CPU mesh under a TPU default backend needs interpret
        # mode); the flag is baked in at trace time, so scope the
        # override around the jit call.
        ring_cm, pp_cm = self._dispatch_scopes(sp_active)
        mesh_platform = next(iter(self.mesh.devices.flat)).platform
        try:
            for s in step_nos:
                fault.on_dispatch(s)
            with _pk.compute_on(mesh_platform), ring_cm, pp_cm:
                run = fn
                if resolve_aot is not None:
                    aot = resolve_aot(call_args, mesh_platform)
                    if aot is not None:
                        run = aot
                if profiler.is_recording():
                    base_run = run
                    run = (lambda *a: profiler.timed_call(
                        profile_label, base_run, *a))
                return run(*call_args)
        except Exception as e:
            if memwatch.is_resource_exhausted(e):
                # land the post-mortem (census, largest category, top
                # executables, window depth) on disk before dying
                memwatch.emit_oom_report(
                    executor=self._tele_name, step=step_nos[-1],
                    inflight_depth=self._inflight.depth)
            raise

    def _dispatch_scopes(self, sp_active):
        """(ring_cm, pp_cm) trace-time scopes for one dispatch — shared
        by the single-step and superstep paths so both lower the model
        identically."""
        import contextlib

        from .scope import ring_attention_scope

        # ring routing only when THIS step actually sequence-sharded the
        # inputs (honors seq_axis=-1 / the auto-detect decline); the
        # batch-dim axes travel with the scope so the ring's shard_map
        # spec matches the activations' real sharding (dp batch + tp
        # heads on the collapsed B*H dim)
        if self.plan.sp_attention != "gspmd" and sp_active:
            dim0_axes = tuple(
                a for a in (tuple(x for x in self.plan.batch_axes if x != "sp")
                            + ("tp",))
                if a in self.mesh.axis_names and self.mesh.shape[a] > 1)
            ring_cm = ring_attention_scope(self.mesh, dim0_axes,
                                           mode=self.plan.sp_attention)
        else:
            ring_cm = contextlib.nullcontext()
        # pipeline scope: stacked-encoder models route their layer stack
        # through the GPipe schedule over 'pp'; batch stays dp-sharded
        if ("pp" in self.mesh.axis_names and self.mesh.shape["pp"] > 1
                and not sp_active):
            from .scope import pipeline_parallel_scope

            pp_axes = tuple(a for a in self.plan.batch_axes
                            if a != "sp" and a in self.mesh.axis_names
                            and self.mesh.shape[a] > 1)
            pp_cm = pipeline_parallel_scope(self.mesh, pp_axes,
                                            self.plan.pp_microbatches)
        else:
            pp_cm = contextlib.nullcontext()
        return ring_cm, pp_cm

    def _resolve_aot(self, sig, call_args, mesh_platform):
        """Single-step AOT executable for this input signature, or None
        (cache disabled / AOT unavailable -> plain jit dispatch).  Keyed
        per signature so alternating shapes reuse their executables in
        memory; a failed resolution is negative-cached (False) so the
        plain jit path isn't re-lowered per step; ``_last_cache_info``
        carries the cache facts to the compile booking."""
        cached = self._aot_execs.get(sig)
        if cached is not None:
            return cached if cached is not False else None
        parts = self._fingerprint_parts((), sig)
        exec_, info = aot_cache.get_or_compile(
            self._jitted, call_args,
            fingerprint=memwatch.fingerprint(parts),
            platform=mesh_platform,
            mesh_shape=tuple(self.mesh.shape.items()),
            device_ids=tuple(int(d.id) for d in self.mesh.devices.flat))
        self._last_cache_info = info
        self._aot_execs[sig] = exec_ if exec_ is not None else False
        return exec_

    # ------------------------------------------------------------------
    # superstep mode: K steps per compiled lax.scan dispatch
    # ------------------------------------------------------------------
    def superstep(self, batches) -> StackedAsyncLoss:
        """Run ``len(batches)`` training steps inside ONE compiled
        ``lax.scan`` dispatch (docs/PERFORMANCE.md §Superstep).

        ``batches`` is a sequence of ``(data, label)`` pairs (``data``
        may be a tuple for multi-input blocks).  Per-step scalars — the
        scheduled learning rate, the RNG key — become scanned arrays, so
        lr schedules step exactly as they would under sequential
        dispatch.  Returns ONE lazy :class:`StackedAsyncLoss` carrying
        the (K,) per-step loss vector, flowing through the same bounded
        in-flight window as single steps.

        This explicit API is always available (the ``MX_SUPERSTEP``
        platform gate only covers the transparent ``step()`` routing);
        any transparently-buffered steps are flushed first so dispatch
        order always matches call order."""
        batches = list(batches)
        if not batches:
            raise MXNetError("superstep() needs at least one "
                             "(data, label) batch")
        self.flush()
        group = None
        for data, label in batches:
            _view, group = self._superstep_enqueue(data, label)
            memwatch.on_step(self._step_count)
        return self._dispatch_group(group)

    def flush(self) -> None:
        """Dispatch any partially-filled transparent superstep group now
        (epoch end, pre-checkpoint, mode flip).  A partial group runs as
        a shorter scan — still one dispatch."""
        if self._open_group is not None and self._open_group.entries:
            self._dispatch_group(self._open_group)

    def _superstep_enqueue(self, data, label):
        """Buffer one logical step for the open superstep group: inputs
        are placed on device NOW (the prefetcher handshake holds —
        pre-staged batches skip the H2D), and the RNG key + scheduled lr
        are drawn NOW in step order, keeping losses/weights faithful to
        sequential dispatch.  Returns (per-step view handle, group)."""
        from .. import random as _random
        from ..ndarray import NDArray

        if label is None:
            raise MXNetError("superstep mode requires a label per batch")
        datas = tuple(data) if isinstance(data, (tuple, list)) else (data,)
        datas = tuple(d if isinstance(d, NDArray)
                      else NDArray(d, ctx=self._ctx) for d in datas)
        self._ensure_state(datas)
        if self._jitted is None:
            self._build()
        data_arrs = tuple(d._data for d in datas)
        label_arr = label._data if isinstance(label, NDArray) else label
        sig = self._sig_of(data_arrs, label_arr)
        if (self._open_group is not None and self._open_group.entries
                and self._open_group.sig != sig):
            # shape change mid-group (ragged final batch, bucketed
            # lengths): close the open group as a shorter scan — one
            # stacked group must be shape-uniform
            self._dispatch_group(self._open_group)
        if self.plan.accum_steps > 1:
            for dim0 in [np.shape(a)[0] for a in data_arrs] + \
                    [np.shape(label_arr)[0]]:
                if dim0 % self.plan.accum_steps:
                    raise MXNetError(
                        f"batch {dim0} not divisible by "
                        f"accum_steps={self.plan.accum_steps}")
        data_sh, label_sh, _sp = self._input_shardings(data_arrs, label_arr)
        overlapped = 0
        placed = []
        for a, s in zip(data_arrs, data_sh):
            arr, pre = _maybe_put(a, s)
            placed.append(arr)
            if pre:
                overlapped += int(getattr(arr, "nbytes", 0))
        label_arr, pre = _maybe_put(label_arr, label_sh)
        if pre:
            overlapped += int(getattr(label_arr, "nbytes", 0))
        key = _random.next_key()
        self._step_count += 1
        step_no = self._step_count
        entry = {
            "data": tuple(placed), "label": label_arr, "key": key,
            "lr": np.float32(self._current_lr(step_no)),
            "step": step_no, "overlapped": overlapped,
            "nbytes": sum(int(getattr(a, "nbytes", 0))
                          for a in tuple(placed) + (label_arr,)),
        }
        if self._open_group is None:
            self._open_group = _SuperstepGroup(sig=sig)
        group = self._open_group
        idx = len(group.entries)
        group.entries.append(entry)
        view = SuperstepLossView(
            idx=idx, step=step_no, executor=self._tele_name,
            dispatch_fn=lambda g=group: self._dispatch_group(g))
        return view, group

    def _dispatch_group(self, group) -> StackedAsyncLoss:
        """Dispatch one buffered group as a single scan executable.
        Idempotent: a view forcing an already-dispatched group gets the
        cached handle.  Partial groups (flush/drain/early force) run as
        a shorter scan — every superstep dispatch stays in the scan
        executable family, which is bitwise self-consistent across
        lengths (asserted by tests/test_superstep.py)."""
        if group.handle is not None:
            return group.handle
        if group is self._open_group:
            self._open_group = None
        with telemetry.span("train_step", executor=self._tele_name,
                            superstep=len(group.entries)):
            handle = self._superstep_impl(group)
        # release the K placed input buffers NOW: loss views (and their
        # dispatch closures) outlive the group, and retaining an epoch's
        # worth of staged batches would grow device memory without bound
        group.entries = []
        self._book_pending_compile()
        return handle

    def _superstep_impl(self, group) -> StackedAsyncLoss:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        entries = group.entries
        k = len(entries)
        name = self._tele_name
        first = entries[0]
        last_step = entries[-1]["step"]
        aot_on = aot_cache.enabled()
        sig = (k,) + self._sig_of(first["data"], first["label"])
        if telemetry.retrace_enabled():
            traced = telemetry.note_signature(name, ("superstep",) + sig)
        else:
            traced = k not in self._super_jits
        limit = inflight_limit()
        block_wait_s = 0.0
        if limit > 0:
            bw0 = time.perf_counter()
            block_wait_s = self._inflight.make_room(limit, wait_span=False)
            if block_wait_s > 0.0:
                telemetry.record_span("block_wait", bw0,
                                      bw0 + block_wait_s)
        with telemetry.span("input_stage"):
            datas, label_arr, sp_active = self._stack_group(entries)
            keys = jnp.stack([e["key"] for e in entries])
            # per-step scalars become SCANNED arrays: an lr schedule
            # steps inside the compiled program exactly as it would
            # under sequential dispatch
            lrs = np.array([e["lr"] for e in entries], np.float32)
        mesh_platform = next(iter(self.mesh.devices.flat)).platform
        with telemetry.span("dispatch", step=last_step, traced=traced,
                            superstep=k):
            fn = self._super_fn(k, mesh_platform)
            scaled = self.scaler_state is not None
            call_args = ((self.params, self.opt_state, self.scaler_state,
                          keys, lrs, datas, label_arr) if scaled
                         else (self.params, self.opt_state, keys, lrs,
                               datas, label_arr))
            resolve = ((lambda a, p: self._resolve_super_aot(sig, fn, a, p))
                       if aot_on else None)
            outs = self._plan_dispatch(
                fn, call_args, tuple(e["step"] for e in entries),
                sp_active, resolve,
                f"Superstep:{type(self.block).__name__}")
            if scaled:
                (self.params, self.opt_state, self.scaler_state,
                 losses) = outs
            else:
                self.params, self.opt_state, losses = outs
        if traced and telemetry.enabled():
            cache_info = self._last_cache_info
            self._last_cache_info = {}
            self._pending_compile = {
                "parts": self._fingerprint_parts(("superstep", k),
                                                 sig[1:]),
                "wall_s": time.perf_counter() - t0,
                "args": memwatch.shape_structs(
                    (self.params, self.opt_state, keys, lrs, datas,
                     label_arr)),
                "site": "superstep",
                "jitted": (None if cache_info.get("cache_hit")
                           else self._super_jits.get(k)),
                "extra": cache_info,
            }
        handle = StackedAsyncLoss(
            losses, steps=[e["step"] for e in entries], executor=name,
            ring=self._inflight, host_fn=_host_scalar)
        group.handle = handle
        depth = self._inflight.admit(handle) if limit > 0 else 0
        if telemetry.enabled():
            samples = sum(
                (int(np.shape(e["label"])[0]) if np.ndim(e["label"]) else 1)
                for e in entries)
            telemetry.record_step(
                name, step=last_step, wall_s=time.perf_counter() - t0,
                samples=samples,
                transfer_bytes=sum(e["nbytes"] for e in entries),
                traced=traced,
                h2d_overlapped=sum(e["overlapped"] for e in entries),
                inflight_depth=depth,
                block_wait_ms=round(block_wait_s * 1e3, 3),
                superstep=k)
            telemetry.heartbeat(last_step)
        if limit == 0:
            handle.wait()  # synchronous mode: errors surface right here
        return handle

    def _super_fn(self, k: int, mesh_platform: str):
        """The K-step scan executable: ``lax.scan`` over the SAME
        single-step program ``_build`` produced, carrying (params,
        opt_state) and scanning (keys, lrs, data, label).  Cached per K;
        partial-group lengths get their own entry."""
        fn = self._super_jits.get(k)
        if fn is not None:
            return fn
        import jax
        from jax import lax

        if self._jitted is None:
            self._build()
        inner = self._jitted
        repl = replicated(self.mesh)
        donate = (0, 1) if (self._donate and mesh_platform != "cpu") else ()

        def superstep_body(params, opt_state, keys, lrs, datas, label):
            def body(carry, xs):
                p, o = carry
                key, lr, data, lab = xs
                p2, o2, loss = inner(p, o, key, lr, data, lab)
                return (p2, o2), loss

            (p, o), losses = lax.scan(body, (params, opt_state),
                                      (keys, lrs, datas, label))
            return p, o, losses

        def superstep_body_scaled(params, opt_state, scaler, keys, lrs,
                                  datas, label):
            # loss-scaled twin: the scaler state joins the scan carry,
            # so skip/backoff/regrow transitions happen per scanned step
            # exactly as under sequential dispatch
            def body(carry, xs):
                p, o, s = carry
                key, lr, data, lab = xs
                p2, o2, s2, loss = inner(p, o, s, key, lr, data, lab)
                return (p2, o2, s2), loss

            (p, o, s), losses = lax.scan(
                body, (params, opt_state, scaler),
                (keys, lrs, datas, label))
            return p, o, s, losses

        # built once per scan length K, cached in _super_jits; the
        # loss-scale config is construction-time state
        if self._loss_scale_cfg is None:
            # mxlint: disable=retrace-hazard — built once per K, cached
            fn = jax.jit(superstep_body,
                         out_shardings=(self._shardings, None, repl),
                         donate_argnums=donate)
        else:
            # mxlint: disable=retrace-hazard — built once per K, cached
            fn = jax.jit(superstep_body_scaled,
                         out_shardings=(self._shardings, None, None, repl),
                         donate_argnums=donate)
        self._super_jits[k] = fn
        return fn

    def _resolve_super_aot(self, sig, fn, call_args, mesh_platform):
        """Superstep AOT executable for (scan length, input signature),
        or None.  Failed resolutions are negative-cached so the plain
        jit path isn't re-probed per dispatch."""
        cached = self._super_aot.get(sig)
        if cached is not None:
            return cached if cached is not False else None
        parts = self._fingerprint_parts(("superstep", sig[0]), sig[1:])
        exec_, info = aot_cache.get_or_compile(
            fn, call_args, fingerprint=memwatch.fingerprint(parts),
            platform=mesh_platform,
            mesh_shape=tuple(self.mesh.shape.items()),
            device_ids=tuple(int(d.id) for d in self.mesh.devices.flat))
        self._last_cache_info = info
        self._super_aot[sig] = exec_ if exec_ is not None else False
        return exec_

    def _stack_group(self, entries):
        """Stack K staged per-step batches into the scanned (K, B, ...)
        inputs ON DEVICE, preserving each batch's placement sharding
        under a leading unsharded scan axis — the prefetcher's staged
        arrays are stacked in place, never read back to host."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        first = entries[0]
        data_sh, label_sh, sp_active = self._input_shardings(
            first["data"], first["label"])

        def stack(arrs, sh):
            out_sh = NamedSharding(self.mesh, PartitionSpec(None, *sh.spec))
            key = (len(arrs), tuple(np.shape(arrs[0])),
                   str(arrs[0].dtype), out_sh)
            fn = self._stackers.get(key)
            if fn is None:
                import jax.numpy as jnp

                # mxlint: disable=retrace-hazard — cached per
                # (K, shape, dtype, sharding) in _stackers
                fn = jax.jit(lambda *xs: jnp.stack(xs),
                             out_shardings=out_sh)
                self._stackers[key] = fn
            return fn(*arrs)

        datas = tuple(
            stack([e["data"][j] for e in entries], data_sh[j])
            for j in range(len(first["data"])))
        label = stack([e["label"] for e in entries], label_sh)
        return datas, label, sp_active

    def drain(self) -> None:
        """Force every in-flight step (epoch end, pre-checkpoint, exit);
        dispatches any buffered partial superstep group first; raises
        the first deferred failure."""
        self.flush()
        self._inflight.drain()

    @property
    def inflight_depth(self) -> int:
        """Dispatched-but-unforced steps currently pending."""
        return self._inflight.depth

    def _current_lr(self, num_update: int) -> float:
        if self._lr_scheduler is not None:
            # mxlint: disable=hot-sync — python lr schedule, host scalar
            return float(self._lr_scheduler(num_update))
        # mxlint: disable=hot-sync — host python scalar, never on device
        return float(self._lr)

    @property
    def learning_rate(self) -> float:
        """The lr the NEXT step will use (Trainer.learning_rate analog)."""
        return self._current_lr(self._step_count + 1)

    def set_learning_rate(self, lr: float) -> None:
        """Manual lr override; no retrace (lr is a step argument)."""
        if self._lr_scheduler is not None:
            raise MXNetError(
                "set_learning_rate conflicts with an lr_scheduler "
                "(Trainer semantics: mutate the scheduler instead)")
        self._lr = float(lr)

    # ------------------------------------------------------------------
    def sync_to_block(self) -> None:
        """Write the sharded training state back into the Gluon parameters.
        Drains the in-flight window first so a deferred step failure
        surfaces here (named) instead of as a bare error mid-copy."""
        import jax

        self.drain()
        for name, p in self._param_items:
            host = np.asarray(jax.device_get(self.params[name]))
            p.set_data(host)

    # ------------------------------------------------------------------
    # checkpointable sharded state (docs/FAULT_TOLERANCE.md §Elastic
    # resize): the save-time layout travels with the snapshot so a
    # restore onto a DIFFERENT mesh (N->M ranks, or a reordered device
    # assignment) reshards instead of silently mis-placing shards.
    # ------------------------------------------------------------------
    def _struct_names(self) -> Dict[str, str]:
        """collect_params name -> structural name ('0.weight'), the
        scope-independent scheme checkpoints key on (a fresh process's
        gluon name counters may differ); identity mapping when the block
        doesn't expose structural names."""
        if not hasattr(self.block, "_collect_params_with_prefix"):
            return {n: n for n, _ in self._param_items}
        by_param = {id(p): sname for sname, p in
                    self.block._collect_params_with_prefix().items()}
        return {n: by_param.get(id(p), n) for n, p in self._param_items}

    def layout(self) -> dict:
        """JSON-serializable sharding layout of this step's training
        state: world size, mesh axes, the mesh's device assignment, and
        each parameter's PartitionSpec — what ``checkpoint.py`` records
        in ``meta.json`` and what ``load_state_dict`` compares against
        the current mesh to decide whether a restore must reshard."""
        import jax

        specs = {}
        if self._shardings is not None:
            smap = self._struct_names()
            for name, sh in self._shardings.items():
                specs[smap.get(name, name)] = [
                    list(a) if isinstance(a, tuple) else a
                    for a in tuple(sh.spec)]
        return {
            "world_size": int(jax.process_count()),
            "mesh_axes": [[n, int(s)] for n, s in self.mesh.shape.items()],
            "device_ids": [int(d.id) for d in self.mesh.devices.flat],
            "platform": next(iter(self.mesh.devices.flat)).platform,
            "specs": specs,
            # the full strategy Plan rides with the placement: an elastic
            # restore knows WHICH strategy produced these specs, and
            # Plan.from_json(layout["plan"]) rebuilds it on the new world
            # (docs/FAULT_TOLERANCE.md §Elastic resize)
            "plan": self.plan.to_json(),
            # the pass-pipeline config rides with the layout too: a
            # restore can rebuild descriptor passes
            # (passes.PassPipeline.from_json) and compare fingerprints
            # against the env it restarts under
            "passes": self._pipeline.to_json(),
        }

    def _to_host_full(self, arr, allow_collective: bool = True):
        """Full (global) host value of a possibly-sharded array — the
        gather-to-host correctness baseline of the resharding story.
        Fully-addressable arrays read directly and fully-replicated ones
        read their local shard (both collective-free, hence safe in the
        SIGTERM preemption path); a genuinely sharded multi-process
        array pays ONE compiled allgather (jit identity onto a
        replicated out_sharding), so every rank must call in lockstep —
        which scheduled checkpoints do by construction.
        ``allow_collective=False`` (the preemption path, where only ONE
        rank may be running this) raises instead of hanging the gather."""
        import jax

        if getattr(arr, "is_fully_addressable", True):
            return np.asarray(jax.device_get(arr))
        if getattr(arr, "is_fully_replicated", False):
            return np.asarray(arr.addressable_shards[0].data)
        if not allow_collective:
            raise MXNetError(
                "state_dict: a cross-process-sharded array needs an "
                "allgather, which a rank-local (preemption) snapshot must "
                "not run — resume from the last scheduled checkpoint "
                "instead")
        if self._gather_jit is None:
            # mxlint: disable=retrace-hazard — built once per step object
            self._gather_jit = jax.jit(
                lambda x: x, out_shardings=replicated(self.mesh))
        rep = self._gather_jit(arr)
        return np.asarray(rep.addressable_shards[0].data)

    def snapshot_requires_collective(self) -> bool:
        """Whether :meth:`state_dict` must run a gang-lockstep allgather
        (any cross-process-sharded, non-replicated array).  Non-writer
        ranks of a shared-dir gang consult this to skip building a full
        host snapshot they would only discard — the common replicated-dp
        case never needs their participation."""
        import jax

        arrs = list((self.params or {}).values())
        arrs += jax.tree_util.tree_leaves(self.opt_state)
        return any(
            not getattr(a, "is_fully_addressable", True)
            and not getattr(a, "is_fully_replicated", False)
            for a in arrs)

    def state_dict(self, allow_collective: bool = True) -> dict:
        """Host snapshot of the sharded training state, keyed by
        structural parameter names: ``{"params": {name: ndarray},
        "opt_state": {slot.name: ndarray}, "optimizer": ...}``.  Flushes
        any buffered superstep group first (a buffered step's update is
        not in ``self.params`` yet) but does NOT force the in-flight
        window — jax arrays are futures, and the host reads below block
        on exactly the values the dispatched steps produce."""
        if self.params is None:
            raise MXNetError(
                "state_dict: step holds no state yet (no step/stage ran)")
        self.flush()
        smap = self._struct_names()

        def host(a):
            return self._to_host_full(a, allow_collective=allow_collective)

        params = {smap.get(n, n): host(a) for n, a in self.params.items()}
        opt: Dict[str, np.ndarray] = {}
        if self._optimizer == "sgd":
            for n, a in self.opt_state.items():
                opt[f"mom.{smap.get(n, n)}"] = host(a)
        else:
            import jax

            means, vars_, t = self.opt_state
            for n, a in means.items():
                opt[f"mean.{smap.get(n, n)}"] = host(a)
            for n, a in vars_.items():
                opt[f"var.{smap.get(n, n)}"] = host(a)
            opt["t"] = np.asarray(jax.device_get(t))
        if self.scaler_state is not None:
            # traced loss-scale state rides with the optimizer slots
            # (replicated scalars: collective-free host reads), so a
            # restore — same world or elastically resharded — resumes
            # the scale trajectory instead of restarting at init_scale
            for k in self.scaler_state:
                opt[f"amp.{k}"] = host(self.scaler_state[k])
        return {"params": params, "opt_state": opt,
                "optimizer": self._optimizer}

    def shard_state_dict(self) -> dict:
        """Rank-LOCAL shard snapshot: each entry carries only the shards
        this process's devices hold, plus the full (rank-invariant)
        shard manifest every rank derives from metadata alone.  ZERO
        collectives — unlike :meth:`state_dict` on cross-process-sharded
        state, this never gathers, so it is safe on the preemption path
        and its wall/bytes scale with the per-rank shard set, not the
        global param count (docs/FAULT_TOLERANCE.md §Shard-granular
        checkpoints).

        Returns ``{"params": {name: [(j, ndarray)]}, "opt_state":
        {slot: [(j, ndarray)]}, "manifest": {...}, "optimizer", "rank",
        "nprocs"}`` — slot naming matches :meth:`state_dict`
        (``mom.*``/``mean.*``/``var.*``/``t``/``amp.*``), so restore
        code downstream of either format sees the same key space."""
        if self.params is None:
            raise MXNetError(
                "shard_state_dict: step holds no state yet "
                "(no step/stage ran)")
        self.flush()
        import jax

        rank = int(jax.process_index())
        nprocs = int(jax.process_count())
        smap = self._struct_names()
        manifest: Dict[str, dict] = {"params": {}, "opt_state": {}}
        local: Dict[str, dict] = {"params": {}, "opt_state": {}}

        def add(section, sname, arr):
            shards, payloads = _local_shard_split(arr, rank, nprocs)
            manifest[section][sname] = {
                "shape": [int(s) for s in np.shape(arr)],
                "dtype": str(arr.dtype),
                "shards": shards}
            if payloads:
                local[section][sname] = payloads

        for n, a in self.params.items():
            add("params", smap.get(n, n), a)
        if self._optimizer == "sgd":
            for n, a in self.opt_state.items():
                add("opt_state", f"mom.{smap.get(n, n)}", a)
        else:
            means, vars_, t = self.opt_state
            for n, a in means.items():
                add("opt_state", f"mean.{smap.get(n, n)}", a)
            for n, a in vars_.items():
                add("opt_state", f"var.{smap.get(n, n)}", a)
            add("opt_state", "t", t)
        if self.scaler_state is not None:
            for k in self.scaler_state:
                add("opt_state", f"amp.{k}", self.scaler_state[k])
        return {"params": local["params"], "opt_state": local["opt_state"],
                "manifest": manifest, "optimizer": self._optimizer,
                "rank": rank, "nprocs": nprocs}

    def load_state_dict(self, state: dict,
                        saved_layout: Optional[dict] = None) -> dict:
        """Install a host state snapshot onto THIS step's mesh,
        resharding when the save-time layout differs — the elastic
        N->M resume path (shrink and grow alike).

        Every parameter (and optimizer slot) is placed through
        ``_global_put``, which materializes ONLY the shards addressable
        to this process: on a resized or reordered mesh each rank moves
        exactly the shard set it now owns, nothing else — the
        shard-granular fast path over the gather-to-host baseline the
        snapshot itself is.  When ``saved_layout`` matches the current
        :meth:`layout` the placement is recorded as layout-stable (no
        reshard telemetry); a world-size change additionally records a
        ``resize`` event.  Returns an info dict (``resharded``,
        ``old_world``, ``new_world``, ``n_params``)."""
        saved_opt = state.get("optimizer") or (saved_layout or {}).get(
            "optimizer")
        if saved_opt and saved_opt != self._optimizer:
            raise MXNetError(
                f"checkpoint optimizer state was saved from a "
                f"{saved_opt!r} step but this step runs "
                f"{self._optimizer!r} — restoring would silently "
                "zero-fill every optimizer slot")
        params_host = state["params"]
        smap = self._struct_names()
        local_of = {v: k for k, v in smap.items()}
        # serialized against a DevicePrefetchIter's background stage()
        # racing first-use _ensure_state: whichever runs second must see
        # the other's published state, never interleave half-built dicts
        # (a late _ensure_state overwriting the restored params would
        # silently resume from re-initialized weights)
        with self._state_lock:
            if self._shardings is None:
                # fresh process, no step taken yet: build the shardings
                # from the snapshot's shapes — restore must not require a
                # warm-up step (it would advance the RNG and optimizer
                # state)
                shapes = {local_of.get(sname, sname): tuple(np.shape(v))
                          for sname, v in params_host.items()}
                self._shardings = self.plan.rules.shardings(self.mesh, shapes)
            cur = self.layout()
            same = (saved_layout is not None
                    and _layouts_equal(saved_layout, cur))
            new_params = {}
            for n, p in self._param_items:
                sname = smap.get(n, n)
                if sname not in params_host:
                    raise MXNetError(
                        f"checkpoint missing parameter {sname}")
                raw = params_host[sname]
                if hasattr(raw, "read_slice") and \
                        not self._shardings[n].is_fully_addressable:
                    # sharded-checkpoint lazy value onto a
                    # cross-process-sharded target: place per-shard
                    # straight from the shard files — NO host ever
                    # materializes the full array (the Gluon block keeps
                    # its init data; self.params is the authority, as it
                    # already is for every multi-process run)
                    new_params[n] = _lazy_put(raw, self._shardings[n])
                    continue
                host = np.asarray(raw)
                new_params[n] = _global_put(host, self._shardings[n])
                # keep the Gluon block in agreement (sync_to_block
                # parity, and a later eager forward must see the
                # restored weights)
                p.set_data(host)
            opt = dict(state.get("opt_state") or {})
            # scaler state travels under amp.* keys: pop it out before
            # the per-param slot logic (it is not a parameter slot, and
            # the partial-missing-slot check must not see it)
            amp_state = {k[len("amp."):]: opt.pop(k)
                         for k in list(opt) if k.startswith("amp.")}
            if not opt:
                # legitimate (a params-only / legacy Block checkpoint)
                # but never silent: momentum/Adam moments restart at zero
                import logging

                logging.getLogger("mxnet_tpu.data_parallel").warning(
                    "load_state_dict: checkpoint carries no optimizer "
                    "state — resuming with FRESH (zeroed) %s slots",
                    self._optimizer)

            def slot(prefix, n):
                sname = f"{prefix}.{smap.get(n, n)}"
                if sname in opt:
                    return opt[sname]
                if opt:
                    # a PARTIALLY missing slot is a renamed/mismatched
                    # param, not a fresh start — zero-filling just this
                    # one would silently corrupt the trajectory
                    raise MXNetError(
                        f"checkpoint optimizer state is missing slot "
                        f"{sname!r} (has: {sorted(opt)[:8]}...)")
                return np.zeros(np.shape(new_params[n]), np.float32)

            def place_slot(val, sharding):
                # same lazy fast path as the params loop above
                if hasattr(val, "read_slice") and \
                        not sharding.is_fully_addressable:
                    return _lazy_put(val, sharding)
                return _global_put(np.asarray(val), sharding)

            if self._optimizer == "sgd":
                opt_state = {
                    n: place_slot(slot("mom", n), self._shardings[n])
                    for n, _ in self._param_items}
            else:
                import jax.numpy as jnp

                m = {n: place_slot(slot("mean", n), self._shardings[n])
                     for n, _ in self._param_items}
                v = {n: place_slot(slot("var", n), self._shardings[n])
                     for n, _ in self._param_items}
                t = jnp.asarray(int(np.asarray(opt.get("t", 0))),
                                jnp.int32)
                opt_state = (m, v, t)
            if self._loss_scale_cfg is not None:
                from ..precision import loss_scale as _ls

                import logging

                fresh = _ls.init_scaler_host(self._loss_scale_cfg)
                if not amp_state:
                    # params-only / pre-precision checkpoint: resume
                    # with a fresh scaler, loudly — the scale re-warms
                    # from init_scale instead of its learned value
                    logging.getLogger("mxnet_tpu.data_parallel").warning(
                        "load_state_dict: checkpoint carries no amp.* "
                        "loss-scale state — resuming with a FRESH scaler "
                        "(scale=%s)", fresh["scale"])
                host_scaler = {
                    k: np.asarray(amp_state.get(k, fresh[k])).astype(
                        np.asarray(fresh[k]).dtype)
                    for k in _ls.SCALER_KEYS}
                repl = replicated(self.mesh)
                self.scaler_state = {
                    k: _global_put(v, repl)
                    for k, v in host_scaler.items()}
            elif amp_state:
                import logging

                logging.getLogger("mxnet_tpu.data_parallel").warning(
                    "load_state_dict: checkpoint carries amp.* loss-scale "
                    "state but this step runs without loss scaling — "
                    "ignoring it")
            # publish params LAST (the unlocked _ensure_state fast-path
            # check)
            self.opt_state = opt_state
            self.params = new_params
        old_world = (saved_layout or {}).get("world_size")
        info = {"resharded": bool(saved_layout is not None and not same),
                "old_world": old_world,
                "new_world": cur["world_size"],
                "n_params": len(new_params)}
        if info["resharded"] and telemetry.enabled():
            telemetry.record("reshard", executor=self._tele_name,
                             n_params=len(new_params),
                             old_world=old_world,
                             new_world=cur["world_size"])
            if old_world is not None and old_world != cur["world_size"] \
                    and not os.environ.get("MX_ELASTIC") \
                    and not os.environ.get("MX_PREV_NUM_PROCS"):
                # the segment marker the report tools key on — but ONLY
                # for manual (supervisor-less) resizes.  Under --elastic
                # the rendezvous already recorded it off
                # MX_PREV_NUM_PROCS, and a LATER same-size restart that
                # re-restores the old-world checkpoint (died before its
                # first post-resize save) must not mint a second marker
                # for the same logical resize — the stream already
                # carries the first incarnation's
                telemetry.record("resize", old_world=old_world,
                                 new_world=cur["world_size"],
                                 source="restore")
        return info


def _layouts_equal(a: dict, b: dict) -> bool:
    """Whether two :meth:`DataParallelStep.layout` descriptions denote the
    SAME placement: world size, mesh axes, per-param specs AND the device
    assignment — serialized executables and shard ownership both key on
    device ids (the AOT-cache lesson), so a same-shape mesh over reordered
    devices is a different layout."""
    keys = ("world_size", "mesh_axes", "device_ids", "specs")
    return all(a.get(k) == b.get(k) for k in keys)


def make_train_step(block, loss_fn, mesh=None, **kwargs) -> DataParallelStep:
    return DataParallelStep(block, loss_fn, mesh=mesh, **kwargs)


def compile_step_with_plan(block, loss_fn, plan: Plan, mesh=None,
                           **kwargs) -> DataParallelStep:
    """THE single compile path of the parallelism zoo: consume ANY
    :class:`~mxnet_tpu.parallel.plan.Plan` — dp, tp, pipeline, ring or
    Ulysses SP, or any composition the planner enumerated — and return
    the compiled :class:`DataParallelStep` for it.  Superstep scan mode,
    the persistent AOT executable cache, the async in-flight window,
    telemetry spans and elastic resharding all ride along: they are
    features of the one dispatch body (``_plan_dispatch``), not of any
    single strategy.

    ``mesh`` defaults to ``plan.build_mesh()`` over all devices; pass an
    explicit mesh (it must match the plan's axes) to pin devices.
    Remaining kwargs (optimizer/optimizer_params/donate/remat/
    clip_global_norm) pass through — they are training-config, not
    layout, so they live outside the Plan.

    Records one ``plan`` telemetry event carrying the plan and, when the
    planner chose it, the predicted cost breakdown —
    ``tools/trace_report.py`` can then compare predicted step wall
    against the measured ``step`` events of the same stream
    (docs/PERFORMANCE.md §Plan & planner)."""
    step = DataParallelStep(block, loss_fn, mesh=mesh, plan=plan, **kwargs)
    if telemetry.enabled():
        telemetry.record(
            "plan", executor=step._tele_name, strategy=plan.strategy,
            plan=plan.to_json(),
            # the pass pipeline this step compiles under: names + the
            # shared fingerprint that keys its AOT executables — a trace
            # reader can tie a slow/fast step stream to the exact
            # rewrite config that produced it
            passes=step._pipeline.names(),
            pass_fingerprint=step._pipeline.fingerprint(),
            predicted=plan.predicted)
    return step
