"""Pipeline parallelism: a compiled GPipe-style schedule over the 'pp'
mesh axis.

The capability row the reference leaves empty (SURVEY §2.3: nearest
analog is group2ctx manual placement with no microbatching).  TPU-native
design: all pp ranks run ONE SPMD program; each holds its stage's layer
parameters (leading layer dim sharded over 'pp'), microbatch activations
hop stage-to-stage via `ppermute` (ICI neighbour exchange), and the
whole schedule — warmup bubble, steady state, drain — is a `lax.scan`
inside the surrounding jit, so XLA overlaps the permute with compute.

Uniform-stage restriction: every layer must share one apply function and
parameter structure (true of transformer/BERT encoders, the models this
targets).  Differentiable end-to-end: jax.grad through scan + ppermute
gives the standard 1F1B-equivalent backward bubble.
"""
from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp

from ..base import MXNetError

__all__ = ["pipeline_apply", "pipeline_plan"]


def pipeline_plan(pp, microbatches=4, dp=0, n_devices=None, rules=None,
                  accum_steps=1):
    """Compat shim: the GPipe pipeline strategy as a
    :class:`~mxnet_tpu.parallel.plan.Plan` — stacked-encoder models
    route through :func:`pipeline_apply` when the compiled step
    activates the pp scope (docs/PERFORMANCE.md §Plan & planner)."""
    from .plan import pipeline_plan as _pp

    return _pp(pp, microbatches=microbatches, dp=dp, n_devices=n_devices,
               rules=rules, accum_steps=accum_steps)


def pipeline_apply(mesh, fn: Callable, stacked_params, x_micro,
                   axis: str = "pp", batch_axes=(), param_specs=None):
    """Run L stacked uniform layers as a pp-stage pipeline.

    mesh: jax Mesh with a size-S `axis`; L must be divisible by S.
    fn(params_slice, x) -> y with y.shape == x.shape (one layer).
    stacked_params: pytree whose leaves have leading dim L, sharded over
        `axis` (each stage owns L/S consecutive layers).
    x_micro: (M, b, ...) microbatches; dim 1 (the batch dim) may be
        sharded over `batch_axes` (e.g. ("dp",)) — dp×pp composition
        without the shard_map forcing a batch all-gather.
    param_specs: optional pytree of PartitionSpec matching stacked_params
        for tensor parallelism INSIDE the stage: leaves may shard extra
        dims over 'tp' (Megatron column/row splits), in which case `fn`
        runs on local shards and must psum its row-parallel outputs over
        'tp' itself.  Default: every leaf P(axis) (layer dim only).
    Returns (M, b, ...) outputs, same sharding (valid on every pp rank).

    Schedule: M + S - 1 clock ticks; at tick t, stage r processes
    microbatch t - r (its warmup/drain ticks compute discarded garbage —
    the classic GPipe bubble, fraction (S-1)/(M+S-1)).
    """
    from jax.sharding import PartitionSpec as P

    shape = dict(mesh.shape)
    if axis not in shape:
        raise MXNetError(f"mesh has no {axis!r} axis: {tuple(shape)}")
    batch_axes = tuple(a for a in batch_axes
                       if a in shape and shape[a] > 1 and a != axis)
    S = shape[axis]
    leaves = jax.tree_util.tree_leaves(stacked_params)
    if not leaves:
        raise MXNetError("stacked_params is empty")
    L = leaves[0].shape[0]
    if L % S:
        raise MXNetError(
            f"{L} stacked layers not divisible by {axis}={S} stages")
    M = int(x_micro.shape[0])

    def ranked(params_local, xm):
        # params_local leaves: (L/S, ...) — this rank's stage layers
        r = jax.lax.axis_index(axis)

        def stage(x):
            def body(c, pl):
                return fn(pl, c), None

            out, _ = jax.lax.scan(body, x, params_local)
            return out

        buf = jnp.zeros_like(xm)
        state = jnp.zeros(xm.shape[1:], xm.dtype)

        def tick(carry, t):
            buf, state = carry
            # stage 0 pulls microbatch t from the feed; others take the
            # neighbour's output received at the end of the previous tick
            feed = xm[jnp.clip(t, 0, M - 1)]
            inp = jnp.where(r == 0, feed, state)
            out = stage(inp)
            nxt = jax.lax.ppermute(out, axis,
                                   [(i, (i + 1) % S) for i in range(S)])
            # the LAST stage finished microbatch t-(S-1) this tick
            idx = t - (S - 1)
            valid = jnp.logical_and(r == S - 1,
                                    jnp.logical_and(idx >= 0, idx < M))
            upd = jax.lax.dynamic_update_index_in_dim(
                buf, out, jnp.clip(idx, 0, M - 1), 0)
            buf = jnp.where(valid, upd, buf)
            return (buf, nxt), None

        (buf, _), _ = jax.lax.scan(tick, (buf, state),
                                   jnp.arange(M + S - 1))
        # replicate the last stage's collected outputs to every rank
        return jax.lax.psum(
            jnp.where(r == S - 1, buf, jnp.zeros_like(buf)), axis)

    spec_p = (param_specs if param_specs is not None else
              jax.tree_util.tree_map(lambda _: P(axis), stacked_params))
    spec_x = P(None, batch_axes if len(batch_axes) > 1 else
               (batch_axes[0] if batch_axes else None))
    if not any(isinstance(l, jax.core.Tracer)
               for l in leaves + [x_micro]):
        # eager call: operands are committed to single devices; lay them
        # out on the mesh first (inside a jit the shardings are already
        # the caller's concern — DataParallelStep's rules)
        from jax.sharding import NamedSharding

        stacked_params = jax.tree_util.tree_map(
            lambda l, sp: jax.device_put(l, NamedSharding(mesh, sp)),
            stacked_params, spec_p)
        x_micro = jax.device_put(x_micro, NamedSharding(mesh, spec_x))
    from .sharding import shard_map_compat

    fn_sm = shard_map_compat(ranked, mesh=mesh, in_specs=(spec_p, spec_x),
                             out_specs=spec_x, check_vma=False)
    return fn_sm(stacked_params, x_micro)
