"""Sharding rules: parameter-name patterns -> PartitionSpec.

The reference's model parallelism is manual device placement (group2ctx ->
nnvm PlaceDevice pass); the TPU-native expression is a NamedSharding per
parameter over the mesh axes, with XLA inserting the collectives.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["ShardingRules", "replicated", "shard_batch", "shard_map_compat",
           "tensor_parallel_plan"]


def shard_map_compat(f, mesh, in_specs, out_specs, **kwargs):
    """jax.shard_map across jax versions: 0.4.x only ships it as
    jax.experimental.shard_map.shard_map (top-level jax.shard_map appeared
    later), and the replication-check kwarg was renamed check_rep ->
    check_vma along the way.  Every shard_map in this tree must go through
    here — calling jax.shard_map directly breaks on the pinned jax."""
    import jax

    sm = getattr(jax, "shard_map", None)
    if sm is None:
        from jax.experimental.shard_map import shard_map as sm
    try:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)
    except TypeError:
        for old, new in (("check_rep", "check_vma"),
                         ("check_vma", "check_rep")):
            if old in kwargs:
                kwargs[new] = kwargs.pop(old)
                break
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  **kwargs)


def _P(*args):
    from jax.sharding import PartitionSpec

    return PartitionSpec(*args)


class ShardingRules:
    """Ordered (regex, PartitionSpec) table applied to parameter names.

    Example (transformer TP over axis 'tp')::

        rules = ShardingRules([
            (r".*attention.*proj\\.weight", ("tp", None)),   # row-parallel
            (r".*(query|key|value)\\.weight", (None, "tp")), # col-parallel
            (r".*ffn_1\\.weight", (None, "tp")),
            (r".*ffn_2\\.weight", ("tp", None)),
        ])
    """

    def __init__(self, rules: Optional[Sequence[Tuple[str, Sequence]]] = None):
        self._rules = [(re.compile(pat), tuple(spec)) for pat, spec in (rules or [])]

    def to_json(self):
        """Lossless [[pattern, [spec...]], ...] form — how a Plan carries
        its per-param specs into the checkpoint ``layout`` block."""
        return [[pat.pattern,
                 [list(a) if isinstance(a, tuple) else a for a in spec]]
                for pat, spec in self._rules]

    @classmethod
    def from_json(cls, rec) -> "ShardingRules":
        return cls([(pat, tuple(tuple(a) if isinstance(a, list) else a
                                for a in spec)) for pat, spec in (rec or [])])

    def __eq__(self, other):
        return (isinstance(other, ShardingRules)
                and self.to_json() == other.to_json())

    def __hash__(self):
        # hash the same normalized form __eq__ compares (to_json turns
        # tuple entries into lists, so equal-by-eq instances — and
        # list-typed spec entries — hash consistently)
        return hash(repr(self.to_json()))

    def __bool__(self):
        return bool(self._rules)

    def spec_for(self, name: str, ndim: int):
        for pat, spec in self._rules:
            if pat.match(name):
                spec = tuple(spec)[:ndim]
                spec = spec + (None,) * (ndim - len(spec))
                return _P(*spec)
        return _P()  # replicated

    def shardings(self, mesh, named_shapes: Dict[str, Tuple[int, ...]]):
        from jax.sharding import NamedSharding

        return {
            name: NamedSharding(mesh, self.spec_for(name, len(shape)))
            for name, shape in named_shapes.items()
        }


def replicated(mesh):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, _P())


def shard_batch(mesh, axes=("dp",), ndim=2):
    """Sharding for a batch tensor: batch axis split over data axes."""
    from jax.sharding import NamedSharding

    axis = tuple(a for a in axes if a in mesh.axis_names)
    spec = (axis if len(axis) > 1 else (axis[0] if axis else None),)
    return NamedSharding(mesh, _P(*spec, *([None] * (ndim - 1))))


def tensor_parallel_plan(rules, tp, dp=0, n_devices=None, accum_steps=1):
    """Compat shim: the ShardingRules tensor-parallel strategy as a
    :class:`~mxnet_tpu.parallel.plan.Plan` (docs/PERFORMANCE.md §Plan &
    planner) — build the plan here, compile it through
    ``data_parallel.compile_step_with_plan``."""
    from .plan import tensor_parallel_plan as _tp

    return _tp(rules, tp, dp=dp, n_devices=n_devices,
               accum_steps=accum_steps)


def shard_batch_seq(mesh, ndim=2):
    """Sequence-parallel batch sharding: dim 0 over 'dp', dim 1 (sequence)
    over 'sp'.  Under pjit, GSPMD inserts the cross-device collectives the
    sequence-sharded activations need (attention over the T axis etc.) —
    the compiled analog of the reference-era all-to-all SP schemes."""
    from jax.sharding import NamedSharding

    assert ndim >= 2
    return NamedSharding(mesh, _P("dp", "sp", *([None] * (ndim - 2))))
