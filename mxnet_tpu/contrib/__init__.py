"""Contrib (reference: python/mxnet/contrib/ — amp, quantization, onnx)."""
from . import amp
from . import quantization
from . import onnx
