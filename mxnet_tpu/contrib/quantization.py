"""INT8 post-training quantization.

Reference parity: python/mxnet/contrib/quantization.py (quantize_model /
quantize_net drivers) over src/operator/quantization/ (int8 kernels) and
calibrate.cc (~L100: entropy/KL threshold search) — see ops/quantization.py
for the kernel layer.

TPU-native design: int8 matmul/conv lower onto the MXU with int32
accumulation (preferred_element_type=int32), so the quantized layers are
real int8 compute, not emulation.  Gluon-first driver: `quantize_net`
replaces a net's Conv2D/Dense layers with quantized twins whose activation
ranges come from calibration:

  * calib_mode='naive'   — per-layer min/max over the calibration batches
    (reference: collect_naive);
  * calib_mode='entropy' — KL-divergence-optimal symmetric threshold over
    a 2048-bin histogram (reference: calibrate.cc GetOptimalThreshold);
  * calib_mode='none'    — quantize activations on the fly per batch.

ONNX-style export of quantized graphs is NOT provided (the `onnx` package
is absent from this zero-egress image; see contrib/onnx).
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["quantize_net", "quantize_model", "calib_entropy_threshold",
           "check_calibrated_threshold", "QuantizedDense", "QuantizedConv2D",
           "Int4Dense", "Int4Conv2D"]


def check_calibrated_threshold(path: str, calib_mode: str, minmax,
                               thresh: float) -> None:
    """Reject a zero/degenerate calibration threshold LOUDLY, naming the
    layer and calibration mode.

    A layer whose calibration batches produced only zeros (or whose
    activations were non-finite) yields a floor/garbage threshold; a 0.0
    scale would then silently quantize every activation to zero — the
    quantized net "works" and emits nonsense.  Both ``quantize_net`` and
    ``precision.quantize`` route every per-layer threshold through here.
    """
    mn, mx = (float(minmax[0]), float(minmax[1])) if minmax else (0.0, 0.0)
    amax = max(abs(mn), abs(mx))
    if not np.isfinite(thresh) or not np.isfinite(amax):
        raise MXNetError(
            f"quantization calibration for layer {path!r} "
            f"(calib_mode={calib_mode!r}) observed non-finite activations "
            f"(range [{mn}, {mx}]) — the model is diverging or the "
            f"calibration data is corrupt; quantizing would bake NaN/inf "
            f"scales into the int8 graph")
    if amax <= 0.0 or thresh <= 0.0:
        raise MXNetError(
            f"quantization calibration for layer {path!r} "
            f"(calib_mode={calib_mode!r}) produced a degenerate threshold "
            f"(observed activation range [{mn}, {mx}]): every calibrated "
            f"activation is zero, so int8 quantization would map the "
            f"layer's real inputs to zero.  Calibrate with representative "
            f"data, or exclude the layer (exclude_layers)")


# ---------------------------------------------------------------------------
# calibration (reference: calibrate.cc)
# ---------------------------------------------------------------------------
def calib_entropy_threshold(arr: np.ndarray, num_bins: int = 2048,
                            num_quantized_bins: int = 255) -> float:
    """KL-divergence-optimal symmetric threshold (reference:
    calibrate.cc GetOptimalThreshold ~L100: scan candidate thresholds,
    pick the one whose quantized distribution diverges least)."""
    arr = np.abs(np.asarray(arr, np.float64).ravel())
    amax = float(arr.max()) if arr.size else 0.0
    if amax <= 0:
        return 1e-6
    hist, edges = np.histogram(arr, bins=num_bins, range=(0, amax))
    return _entropy_threshold_from_hist(hist, edges,
                                        num_quantized_bins=num_quantized_bins)


def _entropy_threshold_from_hist(hist: np.ndarray, edges: np.ndarray,
                                 num_quantized_bins: int = 255) -> float:
    num_bins = len(hist)
    amax = float(edges[-1])
    if amax <= 0:
        return 1e-6
    hist = hist.astype(np.float64)
    total = hist.sum()
    if total == 0:
        return amax
    pn_full = hist / total
    best_kl, best_t = np.inf, amax
    # candidate thresholds: bin boundaries from num_quantized_bins upward.
    # KL is measured against the FULL (unclipped) distribution so that
    # clipping real mass costs divergence — otherwise the smallest
    # candidate (255 bins -> 255 levels, lossless) degenerately wins.
    for i in range(num_quantized_bins, num_bins + 1,
                   max(1, (num_bins - num_quantized_bins) // 64)):
        t = edges[i]
        p = hist[:i]
        if p.sum() == 0:
            continue
        # quantize the in-range part into num_quantized_bins, expand back
        factor = i / num_quantized_bins
        q = np.zeros(num_bins)
        for j in range(num_quantized_bins):
            lo = int(np.floor(j * factor))
            hi = max(int(np.floor((j + 1) * factor)), lo + 1)
            chunk = p[lo:hi]
            nz = (chunk > 0).sum()
            if nz:
                q[lo:hi] = np.where(chunk > 0, chunk.sum() / nz, 0)
        qn = q / total  # mass beyond i is clipped away: qn[i:] == 0
        mask = pn_full > 0
        kl = float(np.sum(np.where(
            mask,
            pn_full * np.log(np.maximum(pn_full, 1e-12)
                             / np.maximum(qn, 1e-12)),
            0.0)))
        if kl < best_kl:
            best_kl, best_t = kl, t
    return float(best_t)


class _StreamingHist:
    """Fixed-size |x| histogram accumulated incrementally (reference:
    calibrate.cc keeps a per-layer histogram, never the activations).
    When a new batch exceeds the current range, existing bins are merged
    by an integer factor and the width grows — O(num_bins) memory total,
    vs O(batches x activation size) for buffering samples."""

    def __init__(self, num_bins: int = 2048):
        self.num_bins = num_bins
        self.hist = np.zeros(num_bins, np.float64)
        self.width = None  # bin width; range is [0, num_bins * width)

    def add(self, absarr: np.ndarray) -> None:
        amax = float(absarr.max()) if absarr.size else 0.0
        if self.width is None:
            self.width = max(amax / self.num_bins, 1e-12)
        limit = self.num_bins * self.width
        if amax > limit:
            factor = int(np.ceil(amax / limit))
            merged = np.zeros(self.num_bins, np.float64)
            idx = np.arange(self.num_bins) // factor
            np.add.at(merged, idx, self.hist)
            self.hist = merged
            self.width *= factor
            limit = self.num_bins * self.width
        h, _ = np.histogram(absarr, bins=self.num_bins, range=(0.0, limit))
        self.hist += h

    @property
    def edges(self) -> np.ndarray:
        return np.arange(self.num_bins + 1) * self.width


class _Calibrator:
    def __init__(self, mode: str):
        self.mode = mode
        self.minmax: Dict[str, List[float]] = {}
        self.hists: Dict[str, _StreamingHist] = {}

    def observe(self, name: str, arr) -> None:
        a = np.asarray(arr, np.float32)
        mm = self.minmax.setdefault(name, [np.inf, -np.inf])
        mm[0] = min(mm[0], float(a.min()))
        mm[1] = max(mm[1], float(a.max()))
        if self.mode == "entropy":
            self.hists.setdefault(name, _StreamingHist()).add(
                np.abs(a.ravel()))

    def threshold(self, name: str) -> float:
        if name not in self.minmax:
            raise MXNetError(f"no calibration data observed for {name}")
        if self.mode == "entropy":
            h = self.hists[name]
            return _entropy_threshold_from_hist(h.hist, h.edges)
        mn, mx = self.minmax[name]
        return max(abs(mn), abs(mx), 1e-6)


# ---------------------------------------------------------------------------
# quantized layers
# ---------------------------------------------------------------------------
def _quantize_weight_np(w: np.ndarray):
    t = max(float(np.abs(w).max()), 1e-12)
    q = np.clip(np.round(w * (127.0 / t)), -127, 127).astype(np.int8)
    return q, t


class _QuantizedLayerBase:
    """Shared inference-only behavior: quantize input, run int8 kernel,
    dequantize the int32 accumulator back to f32.  ``_forward`` is
    F-generic so the SAME lowering serves the eager per-call twins here
    and the traced serving rewrite in ``precision/quantize.py`` — the
    int8 call sequence exists exactly once."""

    def _q_input(self, F, x):
        if self._calib_thresh is not None:
            return F.contrib.quantize_v2(
                x, min_calib_range=-self._calib_thresh,
                max_calib_range=self._calib_thresh)
        return F.contrib.quantize_v2(x)

    def __call__(self, x):
        from .. import nd

        return self._forward(nd, x, self._bias)


class QuantizedDense(_QuantizedLayerBase):
    def __init__(self, dense, calib_thresh: Optional[float]):
        from .. import nd

        w = dense.weight.data().asnumpy()
        qw, tw = _quantize_weight_np(w)
        self._qweight = nd.array(qw, dtype=np.int8)
        # constants built ONCE (inference hot path)
        self._w_min = nd.array([-tw])
        self._w_max = nd.array([tw])
        self._w_thresh = float(tw)
        self._no_bias = dense.bias is None
        self._bias = (dense.bias.data() if dense.bias is not None
                      else nd.zeros((dense._units,)))
        self._units = dense._units
        self._flatten = getattr(dense, "_flatten", True)
        self._act_type = dense._act_type
        self._calib_thresh = calib_thresh
        self.orig_nbytes = int(w.nbytes)
        self.nbytes = int(qw.nbytes)

    def _forward(self, F, x, bias):
        qx, mn, mx = self._q_input(F, x)
        acc, amn, amx = F.contrib.quantized_fully_connected(
            qx, self._qweight, bias,
            mn, mx, self._w_min, self._w_max,
            num_hidden=self._units, no_bias=self._no_bias,
            flatten=self._flatten)
        out = F.contrib.dequantize(acc, amn, amx)
        return (F.Activation(out, act_type=self._act_type)
                if self._act_type else out)


class QuantizedConv2D(_QuantizedLayerBase):
    def __init__(self, conv, calib_thresh: Optional[float]):
        from .. import nd

        w = conv.weight.data().asnumpy()
        qw, tw = _quantize_weight_np(w)
        self._qweight = nd.array(qw, dtype=np.int8)
        self._w_min = nd.array([-tw])
        self._w_max = nd.array([tw])
        self._w_thresh = float(tw)
        self._kwargs = dict(conv._kwargs)
        nf = int(self._kwargs["num_filter"])
        self._no_bias = conv.bias is None
        self._bias = (conv.bias.data() if conv.bias is not None
                      else nd.zeros((nf,)))
        self._act_type = conv._act_type
        self._calib_thresh = calib_thresh
        self.orig_nbytes = int(w.nbytes)
        self.nbytes = int(qw.nbytes)

    def _forward(self, F, x, bias):
        qx, mn, mx = self._q_input(F, x)
        k = self._kwargs
        acc, amn, amx = F.contrib.quantized_conv(
            qx, self._qweight, bias,
            mn, mx, self._w_min, self._w_max,
            kernel=k["kernel"], stride=k.get("stride", ()),
            dilate=k.get("dilate", ()), pad=k.get("pad", ()),
            num_filter=int(k["num_filter"]),
            num_group=k.get("num_group", 1),
            no_bias=self._no_bias)
        out = F.contrib.dequantize(acc, amn, amx)
        return (F.Activation(out, act_type=self._act_type)
                if self._act_type else out)


# ---------------------------------------------------------------------------
# int4 weight-only twins (serving; precision/quantize.py int4 path)
# ---------------------------------------------------------------------------
def _quantize_weight_int4_np(w: np.ndarray, group_size: int = 32):
    """Pack a 2-D weight 2-per-byte with group-wise symmetric scales.

    Groups of ``group_size`` run along the input dim (axis 1); the input
    dim is zero-padded to a group multiple (padding quantizes to exact
    zeros, sliced off again by ``_contrib_dequantize_int4``'s ``cols``).
    Per group: thresh = max|w|, scale = thresh / 7, q = round(w / scale)
    clipped to [-7, 7].  Two consecutive columns share a byte (low nibble
    = even column).  Scales are f16 — 2 bytes per ``group_size`` weights,
    so total bytes = 0.5 + 2/group_size per weight (0.5625 at g=32)
    vs 4.0 for f32: the ~0.14x weight-bytes ratio.
    """
    if group_size < 2 or group_size % 2:
        raise MXNetError(
            f"int4 group_size must be even and >= 2, got {group_size}")
    w = np.asarray(w, np.float32)
    if w.ndim != 2:
        raise MXNetError(
            f"_quantize_weight_int4_np packs 2-D weights, got {w.shape}")
    rows, cols = w.shape
    pad = (-cols) % group_size
    if pad:
        w = np.pad(w, ((0, 0), (0, pad)))
    g = w.reshape(rows, -1, group_size)
    thresh = np.maximum(np.abs(g).max(axis=-1), 1e-12)
    # f16 ROUND-TRIPPED before quantizing: the dequant side reads f16
    # scales, so q must be computed against the value it will actually
    # be multiplied by
    scales = (thresh / 7.0).astype(np.float16)
    q = np.clip(np.round(g / scales.astype(np.float32)[..., None]),
                -7, 7).astype(np.int8).reshape(rows, -1)
    lo = q[:, 0::2].astype(np.uint8) & 0x0F
    hi = q[:, 1::2].astype(np.uint8) & 0x0F
    packed = (lo | (hi << 4)).astype(np.uint8)
    return packed, scales, cols


class _Int4LayerBase:
    """Weight-only int4 twin: the packed weight + f16 group scales live
    as device constants of the traced graph; ``_forward`` dequantizes
    IN-TRACE (``F.contrib.dequantize_int4``) and runs the stock f32
    kernel.  No activation quantization, hence no calibration — the
    weight-bytes footprint is the whole point (decode is weight-
    bandwidth bound).  F-generic like the int8 twins: one copy of the
    lowering for eager self-checks and the traced serving rewrite."""

    def _dequant(self, F):
        return F.contrib.dequantize_int4(
            self._packed, self._scales, group_size=self._group,
            cols=self._cols)

    def __call__(self, x):
        from .. import nd

        return self._forward(nd, x, self._bias)


class Int4Dense(_Int4LayerBase):
    def __init__(self, dense, group_size: int = 32):
        from .. import nd

        w = dense.weight.data().asnumpy()
        packed, scales, cols = _quantize_weight_int4_np(w, group_size)
        self._packed = nd.array(packed, dtype=np.uint8)
        self._scales = nd.array(scales, dtype=np.float16)
        self._group = int(group_size)
        self._cols = cols
        self._units = dense._units
        self._flatten = getattr(dense, "_flatten", True)
        self._act_type = dense._act_type
        self._no_bias = dense.bias is None
        self._bias = (dense.bias.data() if dense.bias is not None
                      else nd.zeros((dense._units,)))
        self.orig_nbytes = int(w.nbytes)
        self.nbytes = int(packed.nbytes) + int(scales.nbytes)

    def _forward(self, F, x, bias):
        w = self._dequant(F)
        if self._no_bias:
            out = F.FullyConnected(x, w, num_hidden=self._units,
                                   no_bias=True, flatten=self._flatten)
        else:
            out = F.FullyConnected(x, w, bias, num_hidden=self._units,
                                   no_bias=False, flatten=self._flatten)
        return (F.Activation(out, act_type=self._act_type)
                if self._act_type else out)


class Int4Conv2D(_Int4LayerBase):
    def __init__(self, conv, group_size: int = 32):
        from .. import nd

        w = conv.weight.data().asnumpy()
        self._wshape = tuple(w.shape)
        # pack the OIHW weight as (O, I*kh*kw); _forward reshapes the
        # dequantized matrix back before the conv
        packed, scales, cols = _quantize_weight_int4_np(
            w.reshape(w.shape[0], -1), group_size)
        self._packed = nd.array(packed, dtype=np.uint8)
        self._scales = nd.array(scales, dtype=np.float16)
        self._group = int(group_size)
        self._cols = cols
        self._kwargs = dict(conv._kwargs)
        nf = int(self._kwargs["num_filter"])
        self._no_bias = conv.bias is None
        self._bias = (conv.bias.data() if conv.bias is not None
                      else nd.zeros((nf,)))
        self._act_type = conv._act_type
        self.orig_nbytes = int(w.nbytes)
        self.nbytes = int(packed.nbytes) + int(scales.nbytes)

    def _forward(self, F, x, bias):
        w = F.reshape(self._dequant(F), shape=self._wshape)
        k = self._kwargs
        kw = dict(kernel=k["kernel"], stride=k.get("stride", ()),
                  dilate=k.get("dilate", ()), pad=k.get("pad", ()),
                  num_filter=int(k["num_filter"]),
                  num_group=k.get("num_group", 1))
        if self._no_bias:
            out = F.Convolution(x, w, no_bias=True, **kw)
        else:
            out = F.Convolution(x, w, bias, no_bias=False, **kw)
        return (F.Activation(out, act_type=self._act_type)
                if self._act_type else out)


class _QuantizedWrapper:
    """Replaces a Conv2D/Dense inside its parent Block."""

    def __init__(self, impl):
        self._impl = impl

    def __call__(self, x):
        return self._impl(x)


def _active_blocks(block, found):
    """Every block under ``block`` with a live CachedOp fast path
    (``hybridize()``d).  Forward pre-hooks do not fire through the
    cached graph, so BOTH calibration drivers (``quantize_net`` here,
    ``precision.quantize.calibrate`` for serving) deactivate these for
    the eager calibration pass and restore them after."""
    if getattr(block, "_active", False):
        found.append(block)
    for child in getattr(block, "_children", {}).values():
        _active_blocks(child, found)
    return found


# ---------------------------------------------------------------------------
# drivers
# ---------------------------------------------------------------------------
def quantize_net(network, calib_data=None, calib_mode: str = "naive",
                 quantized_dtype: str = "int8", exclude_layers=None,
                 num_calib_batches: Optional[int] = None, ctx=None):
    """Post-training-quantize a Gluon net's Conv2D/Dense layers to int8
    (reference: quantization.py quantize_net).  Returns a callable net;
    the original is not modified.
    """
    from .. import autograd
    from ..gluon import nn as gnn

    if quantized_dtype != "int8":
        raise MXNetError("only int8 quantization is supported (the "
                         "reference's uint8 'shifted' mode is not carried)")
    if calib_mode not in ("none", "naive", "entropy"):
        raise MXNetError(f"unknown calib_mode {calib_mode!r}")
    if not isinstance(network, (gnn.HybridSequential, gnn.Sequential)):
        raise MXNetError(
            "quantize_net requires a (Hybrid)Sequential root: the "
            "quantized net replays children in order, which is not valid "
            "for a custom-forward block (residual adds etc. would be "
            "silently dropped).  Wrap the sequential portion you want "
            "quantized, or quantize per-layer with the contrib.quantize_* "
            "ops.")
    exclude = set(exclude_layers or [])

    # locate quantizable leaf layers.  Only layers reachable through
    # Sequential-style containers are claimed: the quantized net mirrors
    # the container chain by calling parts in order, which is NOT valid
    # inside arbitrary composite blocks (e.g. a residual block's skip
    # connection) — those stay f32, conservatively.
    targets = []  # (parent, attr_key, layer, path)

    def walk(block, path):
        for key, child in list(block._children.items()):
            p = f"{path}.{key}" if path else str(key)
            if isinstance(child, (gnn.Conv2D, gnn.Dense)) and p not in exclude \
                    and child.name not in exclude:
                targets.append((block, key, child, p))
            elif isinstance(child, (gnn.HybridSequential, gnn.Sequential)):
                walk(child, p)
    walk(network, "")
    if not targets:
        raise MXNetError(
            "no quantizable Conv2D/Dense layers found in Sequential "
            "containers (non-sequential composites stay f32)")

    thresholds: Dict[str, Optional[float]] = {p: None for *_ , p in targets}
    if calib_mode != "none":
        if calib_data is None:
            raise MXNetError(f"calib_mode={calib_mode!r} needs calib_data")
        calib = _Calibrator(calib_mode)
        hooks = []  # (layer, hook) — register returns no handle
        for _, _, layer, p in targets:
            hook = (lambda pp: lambda blk, args: calib.observe(
                pp, args[0].asnumpy()))(p)
            layer.register_forward_pre_hook(hook)
            hooks.append((layer, hook))
        # forward pre-hooks do not fire through the CachedOp fast path —
        # run calibration eagerly, restoring hybridization afterwards
        hybridized = _active_blocks(network, [])
        for b in hybridized:
            b._active = False
        try:
            with autograd.pause():
                for i, batch in enumerate(calib_data):
                    data = batch[0] if isinstance(batch, (list, tuple)) \
                        else batch
                    network(data)
                    if num_calib_batches and i + 1 >= num_calib_batches:
                        break
        finally:
            for layer, hook in hooks:
                layer._forward_pre_hooks.remove(hook)
            for b in hybridized:
                b._active = True
        thresholds = {}
        for *_, p in targets:
            t = calib.threshold(p)
            # a degenerate (all-zero / non-finite) calibration is a data
            # bug, not a preference — fail naming the layer and mode
            check_calibrated_threshold(p, calib_mode, calib.minmax.get(p), t)
            thresholds[p] = t

    # build the quantized net: a thin tree mirror whose quantizable leaves
    # are int8 twins; untouched blocks are SHARED with the original (their
    # parameters are read-only at inference), so nothing is deep-copied
    impls = {}
    for _, _, layer, path in targets:
        impls[path] = _QuantizedWrapper(
            QuantizedConv2D(layer, thresholds[path])
            if isinstance(layer, gnn.Conv2D)
            else QuantizedDense(layer, thresholds[path]))

    class _QuantizedNet:
        def __init__(self, block, path=""):
            self._block = block
            self._parts = []
            for key, child in block._children.items():
                p = f"{path}.{key}" if path else str(key)
                if p in impls:
                    self._parts.append(impls[p])
                elif any(t.startswith(p + ".") for t in impls):
                    self._parts.append(_QuantizedNet(child, p))
                else:
                    self._parts.append(child)

        def __call__(self, x):
            if not self._parts:  # leaf block with no quantized children
                return self._block(x)
            for part in self._parts:
                x = part(x)
            return x

    return _QuantizedNet(network)


def quantize_model(sym, arg_params, aux_params, data_names=("data",),
                   ctx=None, calib_mode="none", **kwargs):
    """Symbolic-API driver (reference: quantize_model rewrites the symbol
    graph with quantized ops).  Not implemented: returning the symbol
    unchanged would be a SILENT f32 no-op masquerading as int8.  Use the
    Gluon driver `quantize_net` (the supported int8 workflow), or compose
    the contrib.quantize_v2 / quantized_conv / quantized_fully_connected
    ops directly in a symbol graph."""
    raise MXNetError(
        "quantize_model (symbolic graph rewrite) is not implemented; use "
        "contrib.quantization.quantize_net on a Gluon block, or the "
        "contrib.quantize_* ops directly")
