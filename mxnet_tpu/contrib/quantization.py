"""INT8 quantization (reference: python/mxnet/contrib/quantization.py over
src/operator/quantization/ — quantize_model, calibration).

TPU status: XLA:TPU serves int8 via native int8 matmul lowering; the
calibration machinery (entropy/KL thresholds, reference calibrate.cc ~L100)
ports naturally but is out of the BASELINE acceptance surface.  The API is
present and raises with a clear message until the int8 path lands.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["quantize_model", "quantize_net"]


def quantize_model(sym, arg_params, aux_params, **kwargs):
    raise MXNetError(
        "int8 quantization is not yet implemented in the TPU build; "
        "bf16 (contrib.amp) is the supported reduced-precision path")


def quantize_net(network, **kwargs):
    raise MXNetError(
        "int8 quantization is not yet implemented in the TPU build; "
        "bf16 (contrib.amp) is the supported reduced-precision path")
