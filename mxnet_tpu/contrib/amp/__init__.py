"""AMP: automatic mixed precision (reference: python/mxnet/contrib/amp/amp.py
— init ~L200, init_trainer, scale_loss + DynamicLossScaler ~L400,
convert_model/convert_hybrid_block ~L500; op lists in lists/symbol_fp16.py).

TPU-native policy (SURVEY §2.3 mixed-precision row): the working dtype is
bfloat16 — same exponent range as fp32, so **no loss scaling is needed**;
the scale_loss API is kept (scale 1.0) so reference training scripts run
unchanged.  bf16 matmuls/convs accumulate in fp32 natively on the TPU MXU,
which is the MXNET_SAFE_ACCUMULATION behavior by default.  fp16 is
supported with a real DynamicLossScaler for API completeness.
"""
from .amp import (init, init_trainer, scale_loss, unscale,
                  convert_hybrid_block, convert_model, DynamicLossScaler)
