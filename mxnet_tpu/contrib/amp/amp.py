"""AMP implementation (see package docstring for the TPU policy)."""
from __future__ import annotations

import contextlib
import logging
import warnings
from typing import Optional

import numpy as np

from ...base import MXNetError

__all__ = ["init", "init_trainer", "scale_loss", "unscale",
           "convert_hybrid_block", "convert_model", "DynamicLossScaler"]

_amp_state = {
    "initialized": False,
    "target_dtype": None,
    "loss_scaler": None,
}


class DynamicLossScaler:
    """Dynamic loss scaling for fp16 (reference ~L400).  Unused for bf16.

    Compatibility shim over the precision subsystem
    (docs/PRECISION.md): the scale/overflow protocol now lives in
    ``mxnet_tpu.precision.loss_scale`` — compiled steps
    (``DataParallelStep`` with a ``Plan.precision``) run it entirely on
    device with NO host readback; this class remains for eager Trainer
    scripts, delegating overflow detection to the same fused reduce."""

    def __init__(self, init_scale=2.0**16, scale_factor=2.0,
                 scale_window=2000, tolerance=0.0):
        self.loss_scale = init_scale
        self.scale_factor = scale_factor
        self.scale_window = scale_window
        self._unskipped = 0

    def has_overflow(self, params) -> bool:
        """ONE fused any-non-finite reduce over every gradient
        (precision.loss_scale.overflow_flag), ONE host readback at this
        python-bool API boundary.  The pre-precision body read every
        gradient back to host individually (O(params) blocking syncs
        per step — the pattern mxlint's hot-sync rule now guards this
        entry point against)."""
        from ...precision.loss_scale import overflow_flag

        grads = []
        for param in params:
            if param.grad_req == "null" or param._grad is None:
                continue
            for g in param.list_grad():
                grads.append(g._data)
        if not grads:
            return False
        flag = overflow_flag(grads)
        # mxlint: disable=hot-sync — the eager API contract returns a
        # python bool: exactly ONE deferred readback for the WHOLE
        # gradient set (the compiled-step path never syncs at all)
        return bool(np.asarray(flag))

    def update_scale(self, overflow: bool) -> None:
        if overflow:
            self.loss_scale = max(self.loss_scale / self.scale_factor, 1.0)
            self._unskipped = 0
        else:
            self._unskipped += 1
            if self._unskipped == self.scale_window:
                self.loss_scale *= self.scale_factor
                self._unskipped = 0


def init(target_dtype="bfloat16", target_precision_ops=None,
         conditional_fp32_ops=None, fp32_ops=None):
    """Enable mixed precision (reference: amp.init ~L200).

    On TPU the default target is bfloat16 (fp16 accepted for compat);
    subsequently created/converted blocks run their compute in the target
    dtype with fp32 accumulation for matmul/conv.
    """
    if target_dtype in ("float16", np.float16):
        target_dtype = "float16"
    elif target_dtype not in ("bfloat16",):
        raise MXNetError(f"AMP target_dtype must be bfloat16 or float16, "
                         f"got {target_dtype}")
    _amp_state["initialized"] = True
    _amp_state["target_dtype"] = target_dtype
    if target_dtype == "float16":
        _amp_state["loss_scaler"] = DynamicLossScaler()
    else:
        _amp_state["loss_scaler"] = None  # bf16: full fp32 exponent range
    logging.info("AMP enabled with target dtype %s", target_dtype)


def init_trainer(trainer) -> None:
    """Attach AMP to a Trainer: turns on fp32 master weights
    (multi-precision optimizer path, reference mp_* ops)."""
    if not _amp_state["initialized"]:
        raise MXNetError("call amp.init() before amp.init_trainer()")
    trainer._optimizer.multi_precision = True
    trainer._amp_loss_scaler = _amp_state["loss_scaler"]


@contextlib.contextmanager
def scale_loss(loss, trainer):
    """Scale the loss for backward (reference: amp.scale_loss).

    bf16: identity (no scaling needed — kept so scripts run unchanged).
    fp16: multiplies by the dynamic scale; Trainer.step's rescale then
    divides it back out.
    """
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        yield loss
        return
    trainer._scale = 1.0 / scaler.loss_scale
    from ... import autograd as _ag

    # the scale-multiply must land on the tape even when scale_loss is
    # used outside the record scope (both styles appear in reference
    # scripts); set_recording appends to the existing tape — entering a
    # fresh record() scope here would DROP it
    prev = _ag.set_recording(True)
    try:
        if isinstance(loss, (list, tuple)):
            scaled = [l * scaler.loss_scale for l in loss]
        else:
            scaled = loss * scaler.loss_scale
    finally:
        _ag.set_recording(prev)
    yield scaled
    overflow = scaler.has_overflow(trainer._params)
    scaler.update_scale(overflow)
    if overflow:
        for param in trainer._params:
            param.zero_grad()


def unscale(trainer) -> None:
    scaler = getattr(trainer, "_amp_loss_scaler", None)
    if scaler is None:
        return
    inv = 1.0 / scaler.loss_scale
    for param in trainer._params:
        if param.grad_req != "null" and param._grad is not None:
            for g in param.list_grad():
                g._set_data(g._data * inv)


def convert_hybrid_block(block, target_dtype=None, target_dtype_ops=None,
                         fp32_ops=None, conditional_fp32_ops=None,
                         excluded_sym_names=None, ctx=None,
                         cast_optional_params=False):
    """Cast a HybridBlock's parameters/compute to the AMP dtype, keeping
    normalization statistics in fp32 (handled inside the norm ops, which
    compute moments in fp32 regardless of input dtype)."""
    dtype = target_dtype or _amp_state["target_dtype"] or "bfloat16"
    block.cast(dtype)
    return block


def convert_model(sym, arg_params, aux_params, target_dtype=None, **kwargs):
    from ...base import dtype_np

    dtype = dtype_np(target_dtype or _amp_state["target_dtype"] or "bfloat16")
    new_args = {k: v.astype(dtype) for k, v in arg_params.items()}
    return sym, new_args, dict(aux_params)
