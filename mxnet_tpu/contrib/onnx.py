"""ONNX interop (reference: python/mxnet/contrib/onnx/ — mx2onnx
export_model + onnx2mx import, ~5k LoC of per-op translators).

DESCOPE (documented, not silent): this build environment has no `onnx`
package and zero network egress, so the protobuf schema the translators
target is unavailable.  The supported interchange paths in this tree are:

  * the symbol-json + params checkpoint (`Symbol.tojson`,
    `model.save_checkpoint`) — the reference's own native format;
  * the legacy MXNet 1.x binary .params format (`nd.save_legacy` /
    `nd.load`) for reference-tooling round-trips;
  * `gluon.SymbolBlock.imports` for re-loading exported graphs.

If an `onnx` wheel is present at runtime these entry points raise with
instructions rather than producing wrong models silently.
"""
from __future__ import annotations

from ..base import MXNetError

__all__ = ["export_model", "import_model", "get_model_metadata"]

_MSG = ("ONNX interop is descoped in the TPU build: the 'onnx' package is "
        "not available in this environment (zero egress). Use symbol-json "
        "+ params checkpoints (Symbol.tojson / model.save_checkpoint), the "
        "legacy binary format (nd.save_legacy), or SymbolBlock.imports. "
        "See mxnet_tpu/contrib/onnx.py for the rationale.")


def export_model(*args, **kwargs):
    raise MXNetError(_MSG)


def import_model(*args, **kwargs):
    raise MXNetError(_MSG)


def get_model_metadata(*args, **kwargs):
    raise MXNetError(_MSG)
