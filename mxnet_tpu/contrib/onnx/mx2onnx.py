"""Symbol-graph -> ONNX ModelProto exporter.

Reference parity: python/mxnet/contrib/onnx/mx2onnx/ (~L1-2500, per-op
`convert_*` translators registered by op name).  Same architecture here —
a translator registry keyed by the symbol op name — but emitting wire
format through ``proto.py`` instead of the onnx package's generated
classes (the wheel does not exist in this image).

Supported surface: the inference graph of every model-zoo family in this
tree (Convolution/BatchNorm/Pooling/FullyConnected/Activation chains,
residual adds, concat, dropout, flatten/reshape/transpose, softmax,
reductions, Split) at opset 11.  Unsupported ops raise with the op name
so the gap is explicit, mirroring the reference's
AttributeError("No conversion function registered for op type ...").
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ...base import MXNetError
from . import proto as P

OPSET = 11


class _Ctx:
    """Per-export state shared by translators."""

    def __init__(self, dtype):
        self.dtype = np.dtype(dtype)
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self.init_names: set = set()
        self.structs: Dict[int, list] = {}  # id(node) -> ShapeDtypeStructs
        self._uid = 0

    def in_struct(self, node, i):
        """ShapeDtypeStruct of node's i-th input (None when inference
        couldn't resolve it)."""
        parent, oidx = node.inputs[i]
        lst = self.structs.get(id(parent))
        if lst is None:
            return None
        return lst[oidx] if oidx < len(lst) else None

    def in_rank(self, node, i):
        s = self.in_struct(node, i)
        return None if s is None else len(s.shape)

    def add_node(self, op_type, inputs, outputs, name="", **attrs):
        self.nodes.append(P.make_node(op_type, inputs, outputs,
                                      name=name, **attrs))

    def add_initializer(self, name, array):
        if name in self.init_names:
            return name
        self.init_names.add(name)
        self.initializers.append(P.make_tensor(name, np.asarray(array)))
        return name

    def scalar(self, value, name_hint, dtype=None):
        if dtype is None:
            # float export dtype governs float constants (a float64 export
            # must emit DOUBLE clip bounds/eps); int input dtypes don't
            dtype = self.dtype if self.dtype.kind == "f" else np.float32
        self._uid += 1
        return self.add_initializer(
            f"{name_hint}_const{self._uid}",
            np.asarray(value, dtype=dtype))

    def tmp(self, base):
        self._uid += 1
        return f"{base}_tmp{self._uid}"


def _pair(attrs, key, ndim, default):
    v = attrs.get(key) or ()
    v = list(v) if isinstance(v, (tuple, list)) else [v]
    return [int(x) for x in (v or [default] * ndim)]


def _pads(pad):  # MXNet symmetric pad -> ONNX begin+end
    return [int(p) for p in pad] * 2


_REGISTRY: Dict[str, callable] = {}


def _register(*op_names):
    def deco(fn):
        for n in op_names:
            _REGISTRY[n] = fn
        return fn
    return deco


# --------------------------------------------------------------------------
# translators — signature: fn(ctx, node, ins, outs, attrs)
#   ins: ONNX names of the node's inputs, outs: names of its outputs
# --------------------------------------------------------------------------


def _conv_common(op, attrs):
    """Shared (De)Convolution attr extraction + channel-first guard."""
    if attrs.get("layout") not in (None, "NCHW", "NCW", "NCDHW"):
        raise MXNetError(f"ONNX export supports channel-first {op} only, "
                         f"got layout={attrs['layout']!r}")
    kernel = [int(k) for k in attrs.get("kernel", ())]
    ndim = len(kernel)
    return dict(kernel_shape=kernel,
                strides=_pair(attrs, "stride", ndim, 1),
                dilations=_pair(attrs, "dilate", ndim, 1),
                pads=_pads(_pair(attrs, "pad", ndim, 0)),
                group=int(attrs.get("num_group", 1)))


@_register("Convolution")
def _conv(ctx, node, ins, outs, attrs):
    ctx.add_node("Conv", ins, outs, name=node.name,
                 **_conv_common("Convolution", attrs))


@_register("Deconvolution")
def _deconv(ctx, node, ins, outs, attrs):
    # transposed conv: MXNet weight layout (C_in, C_out/group, *k) is
    # exactly ONNX ConvTranspose's W layout
    if attrs.get("target_shape"):
        raise MXNetError("ONNX export: Deconvolution target_shape "
                         "unsupported (use adj/output_padding)")
    kw = _conv_common("Deconvolution", attrs)
    ndim = len(kw["kernel_shape"])
    adj = _pair(attrs, "adj", ndim, 0)  # scalar adj broadcasts like the op
    if any(adj):
        kw["output_padding"] = adj
    ctx.add_node("ConvTranspose", ins, outs, name=node.name, **kw)


@_register("BatchNorm")
def _batchnorm(ctx, node, ins, outs, attrs):
    # ONNX BatchNormalization is fixed to channel axis 1
    if int(attrs.get("axis", 1)) != 1:
        raise MXNetError("ONNX export: BatchNorm axis="
                         f"{attrs['axis']} unsupported (ONNX "
                         "BatchNormalization normalizes axis 1 only)")
    # fix_gamma=True (the op default) means scale is semantically all-ones
    # regardless of the stored array — materialize that (reference
    # mx2onnx does the same)
    ctx.add_node(
        "BatchNormalization", ins, outs[:1], name=node.name,
        epsilon=float(attrs.get("eps", 1e-3)),
        momentum=float(attrs.get("momentum", 0.9)))


@_register("FullyConnected")
def _fc(ctx, node, ins, outs, attrs):
    data = ins[0]
    if attrs.get("flatten", True):
        flat = ctx.tmp(node.name)
        ctx.add_node("Flatten", [data], [flat], axis=1)
        ctx.add_node("Gemm", [flat] + list(ins[1:]), outs, name=node.name,
                     alpha=1.0, beta=1.0, transA=0, transB=1)
        return
    # flatten=False: per-position projection on rank>=2 input — Gemm is
    # 2D-only, so emit MatMul(x, W^T) (+ bias); runtimes constant-fold
    # the weight transpose
    wt = ctx.tmp(node.name)
    ctx.add_node("Transpose", [ins[1]], [wt], perm=[1, 0])
    if len(ins) > 2:
        mm = ctx.tmp(node.name)
        ctx.add_node("MatMul", [data, wt], [mm])
        ctx.add_node("Add", [mm, ins[2]], outs, name=node.name)
    else:
        ctx.add_node("MatMul", [data, wt], outs, name=node.name)


@_register("Activation")
def _activation(ctx, node, ins, outs, attrs):
    mapping = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
               "softrelu": "Softplus", "softsign": "Softsign"}
    act = attrs.get("act_type", "relu")
    if act not in mapping:
        raise MXNetError(f"ONNX export: Activation act_type={act!r}")
    ctx.add_node(mapping[act], ins, outs, name=node.name)


@_register("LeakyReLU")
def _leaky(ctx, node, ins, outs, attrs):
    act = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    if act == "leaky":
        ctx.add_node("LeakyRelu", ins, outs, name=node.name, alpha=slope)
    elif act == "elu":
        ctx.add_node("Elu", ins, outs, name=node.name, alpha=slope)
    elif act == "prelu":
        ctx.add_node("PRelu", ins, outs, name=node.name)
    elif act == "gelu":
        # exact gelu: 0.5 * x * (1 + erf(x / sqrt(2))) — no Gelu op in
        # opset 11
        s = ctx.in_struct(node, 0)
        dt = None if s is None else s.dtype
        t = lambda: ctx.tmp(node.name)  # noqa: E731
        div, erf, one, mul = t(), t(), t(), t()
        ctx.add_node("Div", [ins[0], ctx.scalar(np.sqrt(2.0), node.name,
                                                dtype=dt)], [div])
        ctx.add_node("Erf", [div], [erf])
        ctx.add_node("Add", [erf, ctx.scalar(1.0, node.name, dtype=dt)],
                     [one])
        ctx.add_node("Mul", [ins[0], one], [mul])
        ctx.add_node("Mul", [mul, ctx.scalar(0.5, node.name, dtype=dt)],
                     outs, name=node.name)
    else:
        raise MXNetError(f"ONNX export: LeakyReLU act_type={act!r}")


@_register("Pooling")
def _pooling(ctx, node, ins, outs, attrs):
    pool = attrs.get("pool_type", "max")
    if pool not in ("max", "avg"):
        raise MXNetError(f"ONNX export: pool_type={pool!r}")
    if attrs.get("global_pool", False):
        op = "GlobalMaxPool" if pool == "max" else "GlobalAveragePool"
        ctx.add_node(op, ins, outs, name=node.name)
        return
    kernel = [int(k) for k in attrs.get("kernel", ())]
    ndim = len(kernel)
    kw = dict(kernel_shape=kernel,
              strides=_pair(attrs, "stride", ndim, 1),
              pads=_pads(_pair(attrs, "pad", ndim, 0)),
              ceil_mode=int(attrs.get("pooling_convention",
                                      "valid") == "full"))
    if pool == "avg":
        kw["count_include_pad"] = int(attrs.get("count_include_pad", True))
        ctx.add_node("AveragePool", ins, outs, name=node.name, **kw)
    else:
        ctx.add_node("MaxPool", ins, outs, name=node.name, **kw)


@_register("Flatten")
def _flatten(ctx, node, ins, outs, attrs):
    ctx.add_node("Flatten", ins, outs, name=node.name, axis=1)


@_register("Dropout")
def _dropout(ctx, node, ins, outs, attrs):
    ctx.add_node("Dropout", ins, outs[:1], name=node.name,
                 ratio=float(attrs.get("p", 0.5)))


def _check_softmax_axis(node, attrs):
    # ONNX Softmax-11 has coerce-to-2D semantics: it flattens [d0..dk-1],
    # [dk..dn] and normalizes each row, which equals MXNet's single-axis
    # softmax ONLY when the axis is the last one.  axis=-1 is the op
    # default here and what every classifier head uses; other axes would
    # export a silently different model, so they raise.
    axis = int(attrs.get("axis", -1))
    if axis != -1:
        raise MXNetError(
            f"ONNX export: {node.op} axis={axis} differs from ONNX "
            "opset-11 flatten semantics (only axis=-1 is equivalent)")
    return axis


@_register("softmax")
def _softmax(ctx, node, ins, outs, attrs):
    ctx.add_node("Softmax", ins, outs, name=node.name,
                 axis=_check_softmax_axis(node, attrs))


@_register("SoftmaxActivation")
def _softmax_activation(ctx, node, ins, outs, attrs):
    # mode='instance' (the default) softmaxes over ALL non-batch dims —
    # exactly ONNX opset-11 Softmax(axis=1) flatten semantics.
    # mode='channel' (axis-1-only on rank>2) has no opset-11 equivalent.
    if attrs.get("mode", "instance") != "instance":
        raise MXNetError("ONNX export: SoftmaxActivation mode='channel' "
                         "has no opset-11 Softmax equivalent")
    ctx.add_node("Softmax", ins, outs, name=node.name, axis=1)


@_register("log_softmax")
def _log_softmax(ctx, node, ins, outs, attrs):
    ctx.add_node("LogSoftmax", ins, outs, name=node.name,
                 axis=_check_softmax_axis(node, attrs))


@_register("SoftmaxOutput")
def _softmax_output(ctx, node, ins, outs, attrs):
    # inference export: the label input and loss semantics drop away
    # (reference mx2onnx emits plain Softmax).  multi_output=True moves
    # the softmax to axis 1 of a rank-4 tensor (per-pixel heads), which
    # opset-11 flatten semantics cannot express.
    if attrs.get("multi_output", False):
        raise MXNetError("ONNX export: SoftmaxOutput multi_output=True "
                         "has no opset-11 Softmax equivalent")
    ctx.add_node("Softmax", ins[:1], outs, name=node.name, axis=-1)


_BINARY = {"elemwise_add": "Add", "broadcast_add": "Add", "_plus": "Add",
           "elemwise_sub": "Sub", "broadcast_sub": "Sub", "_minus": "Sub",
           "elemwise_mul": "Mul", "broadcast_mul": "Mul", "_mul": "Mul",
           "elemwise_div": "Div", "broadcast_div": "Div", "_div": "Div"}


@_register(*_BINARY)
def _binary(ctx, node, ins, outs, attrs):
    ctx.add_node(_BINARY[node.op], ins, outs, name=node.name)


_SCALAR = {"_plus_scalar": ("Add", False), "_minus_scalar": ("Sub", False),
           "_rminus_scalar": ("Sub", True), "_mul_scalar": ("Mul", False),
           "_div_scalar": ("Div", False), "_rdiv_scalar": ("Div", True)}


@_register(*_SCALAR)
def _scalar_op(ctx, node, ins, outs, attrs):
    op, reverse = _SCALAR[node.op]
    s = ctx.in_struct(node, 0)  # ONNX binaries need matching dtypes
    const = ctx.scalar(attrs.get("scalar", 0.0), node.name,
                       dtype=None if s is None else s.dtype)
    inputs = [const, ins[0]] if reverse else [ins[0], const]
    ctx.add_node(op, inputs, outs, name=node.name)


@_register("add_n", "ElementWiseSum")
def _add_n(ctx, node, ins, outs, attrs):
    ctx.add_node("Sum", ins, outs, name=node.name)


@_register("Concat", "concat")
def _concat(ctx, node, ins, outs, attrs):
    ctx.add_node("Concat", ins, outs, name=node.name,
                 axis=int(attrs.get("dim", 1)))


@_register("Reshape", "reshape")
def _reshape(ctx, node, ins, outs, attrs):
    shape = [int(s) for s in attrs.get("shape", ())]
    if any(s < -1 for s in shape):
        # MXNet's -2/-3/-4 split/merge codes have no ONNX encoding, but
        # under export the shapes are static — emit the node's inferred
        # output shape instead
        lst = ctx.structs.get(id(node))
        if not lst or lst[0] is None:
            raise MXNetError(
                "ONNX export: Reshape special codes -2/-3/-4 need shape "
                "inference (failed upstream); use explicit dims")
        shape = [int(d) for d in lst[0].shape]
    shp = ctx.add_initializer(f"{node.name}_shape",
                              np.asarray(shape, dtype=np.int64))
    ctx.add_node("Reshape", [ins[0], shp], outs, name=node.name)


@_register("transpose")
def _transpose(ctx, node, ins, outs, attrs):
    axes = attrs.get("axes", ())
    kw = {"perm": [int(a) for a in axes]} if axes else {}
    ctx.add_node("Transpose", ins, outs, name=node.name, **kw)


@_register("clip")
def _clip(ctx, node, ins, outs, attrs):
    lo = ctx.scalar(float(attrs["a_min"]), node.name)
    hi = ctx.scalar(float(attrs["a_max"]), node.name)
    ctx.add_node("Clip", [ins[0], lo, hi], outs, name=node.name)


_UNARY = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
          "exp": "Exp", "log": "Log", "sqrt": "Sqrt", "abs": "Abs",
          "negative": "Neg", "erf": "Erf", "floor": "Floor",
          "ceil": "Ceil", "BlockGrad": "Identity", "identity": "Identity",
          "stop_gradient": "Identity"}


@_register(*_UNARY)
def _unary(ctx, node, ins, outs, attrs):
    ctx.add_node(_UNARY[node.op], ins, outs, name=node.name)


@_register("mean", "sum")
def _reduce(ctx, node, ins, outs, attrs):
    op = "ReduceMean" if node.op == "mean" else "ReduceSum"
    if attrs.get("exclude", False):
        raise MXNetError(
            f"ONNX export: {node.op} exclude=True has no ONNX axes "
            "equivalent without rank info; reduce over explicit axes")
    axis = attrs.get("axis", None)
    kw = {"keepdims": int(attrs.get("keepdims", False))}
    if axis is not None:
        kw["axes"] = ([int(axis)] if isinstance(axis, (int, np.integer))
                      else [int(a) for a in axis])
    ctx.add_node(op, ins, outs, name=node.name, **kw)


@_register("SliceChannel", "split")
def _split(ctx, node, ins, outs, attrs):
    axis = int(attrs.get("axis", 1))
    if attrs.get("squeeze_axis", False):
        # MXNet drops the split axis from each part; ONNX Split keeps it —
        # emit a Squeeze per output
        parts = [ctx.tmp(node.name) for _ in outs]
        ctx.add_node("Split", ins, parts, name=node.name, axis=axis)
        for part, out in zip(parts, outs):
            ctx.add_node("Squeeze", [part], [out], axes=[axis])
    else:
        ctx.add_node("Split", ins, outs, name=node.name, axis=axis)


@_register("Cast", "cast")
def _cast(ctx, node, ins, outs, attrs):
    ctx.add_node("Cast", ins, outs, name=node.name,
                 to=P.np_to_onnx_dtype(attrs["dtype"]))


# ---- transformer-family ops ----------------------------------------------


@_register("Embedding")
def _embedding(ctx, node, ins, outs, attrs):
    # table lookup = Gather(weight, indices) on axis 0; MXNet accepts
    # float indices, ONNX does not — cast when inference says float
    indices = ins[0]
    s = ctx.in_struct(node, 0)
    if s is None or np.dtype(s.dtype).kind == "f":
        cast = ctx.tmp(node.name)
        ctx.add_node("Cast", [indices], [cast], to=P.INT32)
        indices = cast
    ctx.add_node("Gather", [ins[1], indices], outs, name=node.name, axis=0)


@_register("LayerNorm")
def _layer_norm(ctx, node, ins, outs, attrs):
    if attrs.get("output_mean_var", False):
        raise MXNetError("ONNX export: LayerNorm output_mean_var=True")
    axis = int(attrs.get("axis", -1))
    rank = ctx.in_rank(node, 0)
    if axis != -1 and (rank is None or axis != rank - 1):
        # gamma/beta are (C,): only last-axis normalization broadcasts them
        # correctly in the decomposition below
        raise MXNetError(f"ONNX export: LayerNorm axis={axis} (only the "
                         "last axis is supported)")
    x, gamma, beta = ins
    t = lambda: ctx.tmp(node.name)  # noqa: E731
    mean, cent, sq, var, veps, std, norm, scaled = (
        t(), t(), t(), t(), t(), t(), t(), t())
    ctx.add_node("ReduceMean", [x], [mean], axes=[-1], keepdims=1)
    ctx.add_node("Sub", [x, mean], [cent])
    ctx.add_node("Mul", [cent, cent], [sq])
    ctx.add_node("ReduceMean", [sq], [var], axes=[-1], keepdims=1)
    ctx.add_node("Add", [var, ctx.scalar(float(attrs.get("eps", 1e-5)),
                                         node.name)], [veps])
    ctx.add_node("Sqrt", [veps], [std])
    ctx.add_node("Div", [cent, std], [norm])
    ctx.add_node("Mul", [norm, gamma], [scaled])
    ctx.add_node("Add", [scaled, beta], outs, name=node.name)


def _maybe_transpose_last2(ctx, node, idx, name_in, flag):
    if not flag:
        return name_in
    rank = ctx.in_rank(node, idx)
    if rank is None:
        raise MXNetError(f"ONNX export: {node.op} transpose flag needs "
                         "rank info (shape inference failed upstream)")
    perm = list(range(rank))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    tmp = ctx.tmp(node.name)
    ctx.add_node("Transpose", [name_in], [tmp], perm=perm)
    return tmp


@_register("batch_dot")
def _matmul(ctx, node, ins, outs, attrs):
    a = _maybe_transpose_last2(ctx, node, 0, ins[0],
                               attrs.get("transpose_a", False))
    b = _maybe_transpose_last2(ctx, node, 1, ins[1],
                               attrs.get("transpose_b", False))
    ctx.add_node("MatMul", [a, b], outs, name=node.name)


@_register("dot")
def _dot(ctx, node, ins, outs, attrs):
    # MXNet dot is TENSORDOT (contracts a's last axis with b's FIRST
    # axis, full cyclic transposes) — only the rank-2 case coincides
    # with ONNX MatMul semantics
    if ctx.in_rank(node, 0) != 2 or ctx.in_rank(node, 1) != 2:
        raise MXNetError("ONNX export: dot is only exportable for 2-D "
                         "operands (rank>2 dot is tensordot, not MatMul); "
                         "use batch_dot for batched matmul")
    _matmul(ctx, node, ins, outs, attrs)


@_register("expand_dims")
def _expand_dims(ctx, node, ins, outs, attrs):
    ctx.add_node("Unsqueeze", ins, outs, name=node.name,
                 axes=[int(attrs["axis"])])


@_register("squeeze")
def _squeeze(ctx, node, ins, outs, attrs):
    axis = attrs.get("axis", None)
    kw = {}
    if axis is not None:
        kw["axes"] = ([int(axis)] if isinstance(axis, (int, np.integer))
                      else [int(a) for a in axis])
    ctx.add_node("Squeeze", ins, outs, name=node.name, **kw)


@_register("broadcast_axis")
def _broadcast_axis(ctx, node, ins, outs, attrs):
    # Expand to the inferred output shape (size-1 dims tile per ONNX
    # broadcast rules, same as the op's semantics)
    lst = ctx.structs.get(id(node))
    if not lst or lst[0] is None:
        raise MXNetError("ONNX export: broadcast_axis needs shape "
                         "inference for its Expand target")
    shp = ctx.add_initializer(
        f"{node.name}_target",
        np.asarray(lst[0].shape, dtype=np.int64))
    ctx.add_node("Expand", [ins[0], shp], outs, name=node.name)


_CMP_SCALAR = {"_greater_scalar": ("Greater", False),
               "_lesser_scalar": ("Less", False),
               "_greater_equal_scalar": ("Less", True),
               "_lesser_equal_scalar": ("Greater", True),
               "_equal_scalar": ("Equal", False),
               "_not_equal_scalar": ("Equal", True)}


@_register(*_CMP_SCALAR)
def _cmp_scalar(ctx, node, ins, outs, attrs):
    # MXNet comparisons return float 0/1; ONNX Greater/Less/Equal return
    # bool — compare, optionally Not (for >= / <= via the negated op),
    # then Cast back to the input dtype to keep arithmetic consumers valid
    op, negate = _CMP_SCALAR[node.op]
    s = ctx.in_struct(node, 0)
    const = ctx.scalar(attrs.get("scalar", 0.0), node.name,
                       dtype=None if s is None else s.dtype)
    raw = ctx.tmp(node.name)
    ctx.add_node(op, [ins[0], const], [raw])
    if negate:
        inv = ctx.tmp(node.name)
        ctx.add_node("Not", [raw], [inv])
        raw = inv
    dtype = np.float32 if s is None else s.dtype
    ctx.add_node("Cast", [raw], outs, name=node.name,
                 to=P.np_to_onnx_dtype(dtype))


@_register("where")
def _where(ctx, node, ins, outs, attrs):
    cond = ctx.tmp(node.name)
    ctx.add_node("Cast", [ins[0]], [cond], to=P.BOOL)
    ctx.add_node("Where", [cond, ins[1], ins[2]], outs, name=node.name)


@_register("broadcast_like")
def _broadcast_like(ctx, node, ins, outs, attrs):
    # static export: Expand to the node's inferred output shape
    lst = ctx.structs.get(id(node))
    if not lst or lst[0] is None:
        raise MXNetError("ONNX export: broadcast_like needs shape "
                         "inference for its Expand target")
    shp = ctx.add_initializer(f"{node.name}_target",
                              np.asarray(lst[0].shape, dtype=np.int64))
    ctx.add_node("Expand", [ins[0], shp], outs, name=node.name)


@_register("ones_like", "zeros_like")
def _fill_like(ctx, node, ins, outs, attrs):
    # shape- and dtype-preserving without materializing a constant tensor:
    # zeros = x * 0, ones = x * 0 + 1
    s = ctx.in_struct(node, 0)
    if s is None:  # a dtype-blind constant would mismatch int inputs
        raise MXNetError(f"ONNX export: {node.op} needs dtype inference")
    dt = s.dtype
    zeros = ctx.tmp(node.name) if node.op == "ones_like" else outs[0]
    ctx.add_node("Mul", [ins[0], ctx.scalar(0.0, node.name, dtype=dt)],
                 [zeros], name=node.name if node.op == "zeros_like" else "")
    if node.op == "ones_like":
        ctx.add_node("Add", [zeros, ctx.scalar(1.0, node.name, dtype=dt)],
                     outs, name=node.name)


@_register("cumsum")
def _cumsum(ctx, node, ins, outs, attrs):
    axis = attrs.get("axis", None)
    if axis is None:
        raise MXNetError("ONNX export: cumsum over the flattened array "
                         "(axis=None) unsupported; pass an axis")
    ax = ctx.add_initializer(f"{node.name}_axis",
                             np.asarray(int(axis), dtype=np.int64))
    ctx.add_node("CumSum", [ins[0], ax], outs, name=node.name)


@_register("linalg_makediag")
def _makediag(ctx, node, ins, outs, attrs):
    # diag(v)[i, j] = v[i] * eye[i, j]; eye is a static initializer
    # (export shapes are fixed), so the translation is one Unsqueeze+Mul
    if int(attrs.get("offset", 0)) != 0:
        raise MXNetError("ONNX export: linalg_makediag offset != 0")
    s = ctx.in_struct(node, 0)
    if s is None or len(s.shape) != 1:
        raise MXNetError("ONNX export: linalg_makediag needs a known "
                         "1-D input shape")
    n = int(s.shape[0])
    eye = ctx.add_initializer(f"{node.name}_eye",
                              np.eye(n, dtype=s.dtype))
    col = ctx.tmp(node.name)
    ctx.add_node("Unsqueeze", [ins[0]], [col], axes=[1])
    ctx.add_node("Mul", [col, eye], outs, name=node.name)


@_register("slice_like")
def _slice_like(ctx, node, ins, outs, attrs):
    # static export: slice input 0 on `axes` down to input 1's inferred
    # dims (dynamic-shape slice_like would need Shape ops; export shapes
    # are fixed, so the static Slice is exact)
    like = ctx.in_struct(node, 1)
    src = ctx.in_struct(node, 0)
    if like is None or src is None:
        raise MXNetError("ONNX export: slice_like needs shape inference")
    axes = [int(a) for a in (attrs.get("axes") or
                             range(min(len(src.shape), len(like.shape))))]
    starts = [0] * len(axes)
    ends = [int(like.shape[a]) for a in axes]
    s = ctx.add_initializer(f"{node.name}_starts",
                            np.asarray(starts, np.int64))
    e = ctx.add_initializer(f"{node.name}_ends",
                            np.asarray(ends, np.int64))
    a = ctx.add_initializer(f"{node.name}_axes",
                            np.asarray(axes, np.int64))
    ctx.add_node("Slice", [ins[0], s, e, a], outs, name=node.name)


@_register("_contrib_flash_attention")
def _flash_attention(ctx, node, ins, outs, attrs):
    """Dense decomposition: softmax(q k^T * sm_scale [+ causal mask]) v —
    numerically the attention the fused Pallas kernel computes
    (ops/pallas/flash_attention.py), expressed in plain ONNX ops."""
    q, k, v = ins
    qs = ctx.in_struct(node, 0)
    ks = ctx.in_struct(node, 1)
    if qs is None or ks is None:
        raise MXNetError("ONNX export: flash_attention needs shape "
                         "inference")
    rank = len(qs.shape)
    head_dim = int(qs.shape[-1])
    scale = attrs.get("sm_scale") or 1.0 / np.sqrt(head_dim)
    dt = qs.dtype
    t = lambda: ctx.tmp(node.name)  # noqa: E731
    perm = list(range(rank))
    perm[-1], perm[-2] = perm[-2], perm[-1]
    kt, sc, scaled = t(), t(), t()
    ctx.add_node("Transpose", [k], [kt], perm=perm)
    ctx.add_node("MatMul", [q, kt], [sc])
    ctx.add_node("Mul", [sc, ctx.scalar(float(scale), node.name,
                                        dtype=dt)], [scaled])
    if attrs.get("causal", False):
        # (Lq, Lk) mask matching the kernel's qpos >= kpos rule — q and
        # k/v sequence lengths may differ (decode steps)
        lq, lk = int(qs.shape[-2]), int(ks.shape[-2])
        mask = ctx.add_initializer(
            f"{node.name}_causal",
            np.triu(np.full((lq, lk), -1e9 if np.dtype(dt).itemsize > 2
                            else -3e4, dtype=dt), k=1))
        masked = t()
        ctx.add_node("Add", [scaled, mask], [masked])
        scaled = masked
    att = t()
    ctx.add_node("Softmax", [scaled], [att], axis=-1)
    ctx.add_node("MatMul", [att, v], outs, name=node.name)


@_register("slice_axis")
def _slice_axis(ctx, node, ins, outs, attrs):
    axis = int(attrs["axis"])
    begin = int(attrs.get("begin", 0) or 0)
    end = attrs.get("end", None)
    end = (1 << 62) if end is None else int(end)
    starts = ctx.add_initializer(f"{node.name}_starts",
                                 np.asarray([begin], np.int64))
    ends = ctx.add_initializer(f"{node.name}_ends",
                               np.asarray([end], np.int64))
    axes = ctx.add_initializer(f"{node.name}_axes",
                               np.asarray([axis], np.int64))
    ctx.add_node("Slice", [ins[0], starts, ends, axes], outs,
                 name=node.name)


@_register("slice")
def _slice(ctx, node, ins, outs, attrs):
    begin = list(attrs.get("begin", ()))
    end = list(attrs.get("end", ()))
    step = attrs.get("step") or ()
    if any(s is not None and int(s) != 1 for s in step):
        raise MXNetError("ONNX export: strided slice unsupported")
    starts = [0 if b is None else int(b) for b in begin]
    ends = [(1 << 62) if e is None else int(e) for e in end]
    axes = list(range(len(begin)))

    s = ctx.add_initializer(f"{node.name}_starts",
                            np.asarray(starts, np.int64))
    e = ctx.add_initializer(f"{node.name}_ends",
                            np.asarray(ends, np.int64))
    a = ctx.add_initializer(f"{node.name}_axes",
                            np.asarray(axes, np.int64))
    ctx.add_node("Slice", [ins[0], s, e, a], outs, name=node.name)


# --------------------------------------------------------------------------
# graph walk
# --------------------------------------------------------------------------


def _out_names(node) -> List[str]:
    if node.num_outputs == 1:
        return [node.name]
    return [f"{node.name}_output{i}" for i in range(node.num_outputs)]


def export_symbol(sym, params: Dict[str, np.ndarray],
                  input_shapes: Sequence[Tuple[int, ...]],
                  input_dtype=np.float32) -> bytes:
    """Serialize `sym` + `params` to ONNX ModelProto bytes (opset 11)."""
    from ...symbol.symbol import _topo_order

    ctx = _Ctx(input_dtype)
    params = {k.split(":", 1)[-1]: np.asarray(
        v.asnumpy() if hasattr(v, "asnumpy") else v) for k, v in
        params.items()}

    order = _topo_order(sym._entries)
    free_inputs = [n for n in order
                   if n.is_variable() and n.name not in params]
    if isinstance(input_shapes, dict):
        missing = [n.name for n in free_inputs if n.name not in input_shapes]
        if missing:
            raise MXNetError(
                f"export_model: input shapes missing for {missing}")
        shape_kwargs = {n.name: tuple(input_shapes[n.name])
                        for n in free_inputs}
    else:
        # positional list: graph (topo/list_arguments) order — for multi-
        # input graphs that order is traversal-dependent, so a dict
        # {input_name: shape} is the unambiguous spelling
        if len(free_inputs) != len(input_shapes):
            raise MXNetError(
                f"export_model: graph has {len(free_inputs)} data inputs "
                f"({[n.name for n in free_inputs]}) but {len(input_shapes)}"
                " input shapes were given")
        shape_kwargs = {n.name: tuple(s)
                        for n, s in zip(free_inputs, input_shapes)}

    # graph-wide shape/dtype inference: per-node structs let translators
    # that need rank/dtype (batch_dot transposes, Embedding index casts,
    # broadcast_axis target shapes) emit correct graphs, and give every
    # graph input/output its real elem_type
    try:
        structs = sym._infer_structs(
            shapes=shape_kwargs,
            dtypes={n.name: np.dtype(input_dtype).name for n in free_inputs
                    if not n.vattrs.get("dtype")},
            partial=True)
        ctx.structs = structs["nodes"]
        var_structs = structs["vars"]
        out_structs = structs["outs"]
    except Exception as e:
        # degraded export: rank/dtype-dependent translators will raise if
        # reached — surface why instead of failing there mysteriously
        import warnings

        warnings.warn(f"ONNX export: graph shape inference failed ({e}); "
                      "exporting without per-node shape info")
        var_structs = {}
        out_structs = [None] * len(sym._entries)

    fix_gamma_inits = {}
    for node in order:
        if node.op == "BatchNorm" and node.attrs.get("fix_gamma", True):
            gamma = node.inputs[1][0]
            if gamma.is_variable() and gamma.name in params:
                fix_gamma_inits[gamma.name] = np.ones_like(
                    params[gamma.name])

    def _var_elem_type(name, default):
        s = var_structs.get(name)
        if s is None:
            return default
        try:
            return P.np_to_onnx_dtype(s.dtype)
        except ValueError:
            return default

    elem_type = P.np_to_onnx_dtype(input_dtype)
    graph_inputs = []
    for node in order:
        if not node.is_variable():
            continue
        if node.name in params:
            # a FLOAT export dtype casts float params with it (a float64
            # export must be type-consistent end to end); an int input
            # dtype (token models) must NOT touch float params
            arr = fix_gamma_inits.get(node.name, params[node.name])
            if ctx.dtype.kind == "f" and arr.dtype.kind == "f":
                arr = arr.astype(ctx.dtype)
            ctx.add_initializer(node.name, arr)
        else:
            graph_inputs.append(P.make_tensor_value_info(
                node.name, _var_elem_type(node.name, elem_type),
                shape_kwargs[node.name]))

    for node in order:
        if node.is_variable():
            continue
        if node.op not in _REGISTRY:
            raise MXNetError(
                f"No ONNX conversion registered for op {node.op!r} "
                f"(node {node.name!r}) — supported: "
                f"{sorted(_REGISTRY)}")
        ins = []
        for parent, oidx in node.inputs:
            ins.append(parent.name if parent.num_outputs == 1
                       else _out_names(parent)[oidx])
        _REGISTRY[node.op](ctx, node, ins, _out_names(node), node.attrs)

    graph_outputs = []
    for (node, oidx), ostruct in zip(sym._entries, out_structs):
        oshape = None if ostruct is None else tuple(ostruct.shape)
        otype = elem_type
        if ostruct is not None:
            try:
                otype = P.np_to_onnx_dtype(ostruct.dtype)
            except ValueError:
                pass
        graph_outputs.append(P.make_tensor_value_info(
            _out_names(node)[oidx] if not node.is_variable() else node.name,
            otype, oshape))

    graph_name = getattr(sym, "name", None) or "mxnet_tpu_graph"
    graph = P.make_graph(ctx.nodes, graph_name,
                         graph_inputs, graph_outputs, ctx.initializers)
    return P.make_model(graph, opset=OPSET)
