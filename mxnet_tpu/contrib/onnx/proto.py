"""Self-contained protobuf wire-format codec for the ONNX message subset.

The build image has no ``onnx`` wheel and zero egress to fetch one, so —
unlike the reference (python/mxnet/contrib/onnx/, which imports the onnx
package for its protobuf classes) — this tree encodes and decodes the
ONNX serialization format directly.  Field numbers and types below follow
the public ONNX schema (onnx/onnx.proto, Apache-2.0) and the protobuf
encoding spec:

    ModelProto:   ir_version=1, producer_name=2, producer_version=3,
                  domain=4, model_version=5, doc_string=6, graph=7,
                  opset_import=8
    OperatorSetIdProto: domain=1, version=2
    GraphProto:   node=1, name=2, initializer=5, doc_string=10,
                  input=11, output=12, value_info=13
    NodeProto:    input=1, output=2, name=3, op_type=4, attribute=5,
                  doc_string=6, domain=7
    AttributeProto: name=1, f=2, i=3, s=4, t=5, floats=7, ints=8,
                  strings=9, type=20
                  (type enum: FLOAT=1, INT=2, STRING=3, TENSOR=4,
                   FLOATS=6, INTS=7, STRINGS=8)
    TensorProto:  dims=1, data_type=2, name=8, raw_data=9
    ValueInfoProto: name=1, type=2
    TypeProto:    tensor_type=1;  TypeProto.Tensor: elem_type=1, shape=2
    TensorShapeProto: dim=1;  Dimension: dim_value=1, dim_param=2

Only the fields the exporter/importer need are modelled; unknown fields
are skipped on decode (forward-compatible, as protobuf prescribes).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

# --------------------------------------------------------------------------
# ONNX TensorProto.DataType <-> numpy
# --------------------------------------------------------------------------

FLOAT, UINT8, INT8, INT32, INT64, BOOL = 1, 2, 3, 6, 7, 9
FLOAT16, DOUBLE, BFLOAT16 = 10, 11, 16

_NP2ONNX = {
    np.dtype(np.float32): FLOAT,
    np.dtype(np.uint8): UINT8,
    np.dtype(np.int8): INT8,
    np.dtype(np.int32): INT32,
    np.dtype(np.int64): INT64,
    np.dtype(np.bool_): BOOL,
    np.dtype(np.float16): FLOAT16,
    np.dtype(np.float64): DOUBLE,
}
_ONNX2NP = {v: k for k, v in _NP2ONNX.items()}

try:  # bfloat16 round-trips through ml_dtypes (always present under jax)
    import ml_dtypes

    _NP2ONNX[np.dtype(ml_dtypes.bfloat16)] = BFLOAT16
    _ONNX2NP[BFLOAT16] = np.dtype(ml_dtypes.bfloat16)
except ImportError:  # pragma: no cover
    pass


def np_to_onnx_dtype(dtype) -> int:
    dt = np.dtype(dtype)
    if dt not in _NP2ONNX:
        raise ValueError(f"dtype {dt} has no ONNX TensorProto mapping")
    return _NP2ONNX[dt]


def onnx_to_np_dtype(code: int):
    if code not in _ONNX2NP:
        raise ValueError(f"ONNX data_type {code} unsupported")
    return _ONNX2NP[code]


# --------------------------------------------------------------------------
# wire-format primitives (encode)
# --------------------------------------------------------------------------


def _varint(n: int) -> bytes:
    if n < 0:  # two's-complement 64-bit, 10-byte varint
        n &= (1 << 64) - 1
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _tag(field: int, wire_type: int) -> bytes:
    return _varint((field << 3) | wire_type)


def enc_int(field: int, value: int) -> bytes:
    return _tag(field, 0) + _varint(int(value))


def enc_bytes(field: int, value: bytes) -> bytes:
    return _tag(field, 2) + _varint(len(value)) + value


def enc_str(field: int, value: str) -> bytes:
    return enc_bytes(field, value.encode("utf-8"))


def enc_float(field: int, value: float) -> bytes:
    return _tag(field, 5) + struct.pack("<f", float(value))


# --------------------------------------------------------------------------
# wire-format primitives (decode)
# --------------------------------------------------------------------------


def _read_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _signed64(n: int) -> int:
    return n - (1 << 64) if n >= (1 << 63) else n


def scan(buf: bytes):
    """Yield (field_number, wire_type, value) over one message's fields.
    value is int for varint/fixed wire types, bytes for length-delimited."""
    pos = 0
    while pos < len(buf):
        key, pos = _read_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == 0:
            v, pos = _read_varint(buf, pos)
        elif wt == 2:
            ln, pos = _read_varint(buf, pos)
            v = buf[pos:pos + ln]
            pos += ln
        elif wt == 5:
            v = struct.unpack_from("<I", buf, pos)[0]
            pos += 4
        elif wt == 1:
            v = struct.unpack_from("<Q", buf, pos)[0]
            pos += 8
        else:  # groups (3/4) don't occur in ONNX
            raise ValueError(f"unsupported wire type {wt}")
        yield field, wt, v


def _unpack_int64s(raw: bytes) -> List[int]:
    out, pos = [], 0
    while pos < len(raw):
        v, pos = _read_varint(raw, pos)
        out.append(_signed64(v))
    return out


# --------------------------------------------------------------------------
# ONNX message builders (encode side)
# --------------------------------------------------------------------------

# AttributeProto.AttributeType
_A_FLOAT, _A_INT, _A_STRING, _A_TENSOR = 1, 2, 3, 4
_A_FLOATS, _A_INTS, _A_STRINGS = 6, 7, 8


def make_tensor(name: str, array: np.ndarray) -> bytes:
    array = np.ascontiguousarray(array)
    body = b"".join(enc_int(1, d) for d in array.shape)
    body += enc_int(2, np_to_onnx_dtype(array.dtype))
    body += enc_str(8, name)
    little = array.astype(array.dtype.newbyteorder("<"), copy=False)
    body += enc_bytes(9, little.tobytes())
    return body


def make_attribute(name: str, value) -> bytes:
    body = enc_str(1, name)
    if isinstance(value, bool):
        body += enc_int(3, int(value)) + enc_int(20, _A_INT)
    elif isinstance(value, int):
        body += enc_int(3, value) + enc_int(20, _A_INT)
    elif isinstance(value, float):
        body += enc_float(2, value) + enc_int(20, _A_FLOAT)
    elif isinstance(value, str):
        body += enc_bytes(4, value.encode()) + enc_int(20, _A_STRING)
    elif isinstance(value, bytes):  # pre-encoded TensorProto
        body += enc_bytes(5, value) + enc_int(20, _A_TENSOR)
    elif isinstance(value, (list, tuple)):
        if all(isinstance(v, (int, np.integer)) for v in value):
            body += b"".join(enc_int(8, int(v)) for v in value)
            body += enc_int(20, _A_INTS)
        elif all(isinstance(v, (float, int, np.floating)) for v in value):
            body += b"".join(enc_float(7, float(v)) for v in value)
            body += enc_int(20, _A_FLOATS)
        elif all(isinstance(v, str) for v in value):
            body += b"".join(enc_bytes(9, v.encode()) for v in value)
            body += enc_int(20, _A_STRINGS)
        else:
            raise ValueError(f"attribute {name}: mixed list {value!r}")
    else:
        raise ValueError(f"attribute {name}: unsupported {type(value)}")
    return body


def make_node(op_type: str, inputs: Sequence[str], outputs: Sequence[str],
              name: str = "", **attrs) -> bytes:
    body = b"".join(enc_str(1, i) for i in inputs)
    body += b"".join(enc_str(2, o) for o in outputs)
    if name:
        body += enc_str(3, name)
    body += enc_str(4, op_type)
    for k in sorted(attrs):
        if attrs[k] is None:
            continue
        body += enc_bytes(5, make_attribute(k, attrs[k]))
    return body


def make_tensor_value_info(name: str, elem_type: int,
                           shape: Optional[Sequence[Union[int, str, None]]]
                           ) -> bytes:
    tensor = enc_int(1, elem_type)
    if shape is not None:
        dims = b""
        for d in shape:
            if isinstance(d, (int, np.integer)):
                dims += enc_bytes(1, enc_int(1, int(d)))
            else:  # symbolic / unknown
                dims += enc_bytes(1, enc_str(2, str(d or "?")))
        tensor += enc_bytes(2, dims)
    type_proto = enc_bytes(1, tensor)
    return enc_str(1, name) + enc_bytes(2, type_proto)


def make_graph(nodes: Sequence[bytes], name: str,
               inputs: Sequence[bytes], outputs: Sequence[bytes],
               initializers: Sequence[bytes] = ()) -> bytes:
    body = b"".join(enc_bytes(1, n) for n in nodes)
    body += enc_str(2, name)
    body += b"".join(enc_bytes(5, t) for t in initializers)
    body += b"".join(enc_bytes(11, i) for i in inputs)
    body += b"".join(enc_bytes(12, o) for o in outputs)
    return body


def make_model(graph: bytes, opset: int = 11, ir_version: int = 6,
               producer_name: str = "mxnet_tpu",
               producer_version: str = "1.0") -> bytes:
    opset_id = enc_str(1, "") + enc_int(2, opset)
    return (enc_int(1, ir_version)
            + enc_str(2, producer_name)
            + enc_str(3, producer_version)
            + enc_bytes(7, graph)
            + enc_bytes(8, opset_id))


# --------------------------------------------------------------------------
# ONNX message parsers (decode side) — return plain dicts
# --------------------------------------------------------------------------


def parse_tensor(buf: bytes) -> Dict:
    dims, data_type, name, raw = [], FLOAT, "", None
    float_data, int32_data, int64_data, double_data = [], [], [], []
    for field, wt, v in scan(buf):
        if field == 1:
            if wt == 2:  # packed
                dims.extend(_unpack_int64s(v))
            else:
                dims.append(_signed64(v))
        elif field == 2:
            data_type = v
        elif field == 4:
            float_data.extend(struct.unpack(f"<{len(v)//4}f", v)
                              if wt == 2 else
                              [struct.unpack("<f", struct.pack("<I", v))[0]])
        elif field == 5:
            int32_data.extend(_unpack_int64s(v) if wt == 2 else [v])
        elif field == 7:
            int64_data.extend(_unpack_int64s(v) if wt == 2
                              else [_signed64(v)])
        elif field == 8:
            name = v.decode("utf-8")
        elif field == 9:
            raw = v
        elif field == 10:
            double_data.extend(struct.unpack(f"<{len(v)//8}d", v)
                               if wt == 2 else
                               [struct.unpack("<d", struct.pack("<Q", v))[0]])
    np_dtype = onnx_to_np_dtype(data_type)
    if raw is not None:
        arr = np.frombuffer(raw, dtype=np_dtype.newbyteorder("<"))
        arr = arr.astype(np_dtype).reshape(dims)
    elif float_data:
        arr = np.asarray(float_data, dtype=np_dtype).reshape(dims)
    elif int64_data:
        arr = np.asarray(int64_data, dtype=np_dtype).reshape(dims)
    elif int32_data:
        arr = np.asarray(int32_data, dtype=np_dtype).reshape(dims)
    elif double_data:
        arr = np.asarray(double_data, dtype=np_dtype).reshape(dims)
    else:
        arr = np.zeros(dims, dtype=np_dtype)
    return {"name": name, "dims": dims, "data_type": data_type,
            "array": arr}


def parse_attribute(buf: bytes) -> Tuple[str, object]:
    name, atype = "", None
    f = i = s = t = None
    floats, ints, strings = [], [], []
    for field, wt, v in scan(buf):
        if field == 1:
            name = v.decode("utf-8")
        elif field == 2:
            f = struct.unpack("<f", struct.pack("<I", v))[0]
        elif field == 3:
            i = _signed64(v)
        elif field == 4:
            s = v
        elif field == 5:
            t = parse_tensor(v)
        elif field == 7:
            floats.extend(struct.unpack(f"<{len(v)//4}f", v) if wt == 2
                          else [struct.unpack("<f", struct.pack("<I", v))[0]])
        elif field == 8:
            ints.extend(_unpack_int64s(v) if wt == 2 else [_signed64(v)])
        elif field == 9:
            strings.append(v.decode("utf-8"))
        elif field == 20:
            atype = v
    if atype == _A_FLOAT:
        return name, f
    if atype == _A_INT:
        return name, i
    if atype == _A_STRING:
        return name, s.decode("utf-8") if s is not None else ""
    if atype == _A_TENSOR:
        return name, t
    if atype == _A_FLOATS:
        return name, list(floats)
    if atype == _A_INTS:
        return name, list(ints)
    if atype == _A_STRINGS:
        return name, strings
    # untyped writers: infer from which member is set
    for val in (i, f, s, t):
        if val is not None:
            return name, val
    return name, ints or floats or strings


def parse_node(buf: bytes) -> Dict:
    node = {"input": [], "output": [], "name": "", "op_type": "",
            "attrs": {}}
    for field, _, v in scan(buf):
        if field == 1:
            node["input"].append(v.decode("utf-8"))
        elif field == 2:
            node["output"].append(v.decode("utf-8"))
        elif field == 3:
            node["name"] = v.decode("utf-8")
        elif field == 4:
            node["op_type"] = v.decode("utf-8")
        elif field == 5:
            k, val = parse_attribute(v)
            node["attrs"][k] = val
    return node


def _parse_shape(buf: bytes) -> List[Union[int, str]]:
    shape = []
    for field, _, dim_buf in scan(buf):
        if field != 1:
            continue
        val: Union[int, str] = "?"
        for f2, _, v2 in scan(dim_buf):
            if f2 == 1:
                val = _signed64(v2) if isinstance(v2, int) else v2
            elif f2 == 2:
                val = v2.decode("utf-8")
        shape.append(val)
    return shape


def parse_value_info(buf: bytes) -> Dict:
    info = {"name": "", "elem_type": None, "shape": None}
    for field, _, v in scan(buf):
        if field == 1:
            info["name"] = v.decode("utf-8")
        elif field == 2:  # TypeProto
            for f2, _, v2 in scan(v):
                if f2 != 1:  # tensor_type
                    continue
                for f3, _, v3 in scan(v2):
                    if f3 == 1:
                        info["elem_type"] = v3
                    elif f3 == 2:
                        info["shape"] = _parse_shape(v3)
    return info


def parse_graph(buf: bytes) -> Dict:
    graph = {"node": [], "name": "", "initializer": [],
             "input": [], "output": [], "value_info": []}
    for field, _, v in scan(buf):
        if field == 1:
            graph["node"].append(parse_node(v))
        elif field == 2:
            graph["name"] = v.decode("utf-8")
        elif field == 5:
            graph["initializer"].append(parse_tensor(v))
        elif field == 11:
            graph["input"].append(parse_value_info(v))
        elif field == 12:
            graph["output"].append(parse_value_info(v))
        elif field == 13:
            graph["value_info"].append(parse_value_info(v))
    return graph


def parse_model(buf: bytes) -> Dict:
    model = {"ir_version": None, "producer_name": "", "graph": None,
             "opset": []}
    for field, _, v in scan(buf):
        if field == 1:
            model["ir_version"] = v
        elif field == 2:
            model["producer_name"] = v.decode("utf-8")
        elif field == 7:
            model["graph"] = parse_graph(v)
        elif field == 8:
            dom, ver = "", 0
            for f2, _, v2 in scan(v):
                if f2 == 1:
                    dom = v2.decode("utf-8")
                elif f2 == 2:
                    ver = v2
            model["opset"].append((dom, ver))
    return model
