"""ONNX interop (reference: python/mxnet/contrib/onnx/ — mx2onnx
export_model + onnx2mx import_model/get_model_metadata, ~5k LoC).

The reference builds on the `onnx` python package for its protobuf
classes; that wheel does not exist in this image (zero egress), so this
package carries a self-contained wire-format codec (`proto.py`) plus the
translator registries (`mx2onnx.py` / `onnx2mx.py`) and speaks the real
ONNX serialization format — files written here load in onnxruntime /
netron, and standard opset-11 inference models import back to Symbol +
params.  Earlier rounds shipped a documented descope stub in this spot;
this is the real subsystem.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ...base import MXNetError
from . import proto
from .mx2onnx import export_symbol
from .onnx2mx import import_onnx_model
from .quant_export import export_quantized_net

__all__ = ["export_model", "import_model", "import_to_gluon",
           "get_model_metadata", "export_quantized_net"]


def _load_symbol(sym):
    from ... import symbol as S

    if isinstance(sym, str):
        return S.load(sym)
    return sym


def _load_params(params):
    from ... import ndarray as nd

    if isinstance(params, str):
        loaded = nd.load(params)
        if isinstance(loaded, dict):
            return loaded
        raise MXNetError(f"params file {params!r} did not hold a dict")
    return dict(params)


def export_model(sym, params, input_shape: Sequence[Tuple[int, ...]],
                 input_type=np.float32,
                 onnx_file_path: str = "model.onnx",
                 verbose: bool = False) -> str:
    """Export an MXNet symbol + params to an ONNX file (opset 11).

    Reference: python/mxnet/contrib/onnx/mx2onnx/export_model.py ~L1-100
    (same signature: `sym`/`params` may be objects or file paths;
    `input_shape` is a list of tuples, one per data input, in the graph's
    list_arguments order — or, unambiguously for multi-input graphs, a
    dict {input_name: shape}).
    """
    sym = _load_symbol(sym)
    params = _load_params(params)
    shapes = (dict(input_shape) if isinstance(input_shape, dict)
              else list(input_shape))
    model_bytes = export_symbol(sym, params, shapes,
                                input_dtype=input_type)
    with open(onnx_file_path, "wb") as f:
        f.write(model_bytes)
    if verbose:
        meta = get_model_metadata(onnx_file_path)
        print(f"exported {onnx_file_path}: {meta}")
    return onnx_file_path


def import_model(model_file: str):
    """ONNX file -> (sym, arg_params, aux_params).

    Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py ~L1-60.
    """
    with open(model_file, "rb") as f:
        return import_onnx_model(f.read())


def import_to_gluon(model_file: str, ctx=None):
    """ONNX file -> gluon.SymbolBlock with parameters set.

    Reference: python/mxnet/contrib/onnx/onnx2mx/import_to_gluon.py.
    """
    from ... import gluon

    sym, arg_params, aux_params = import_model(model_file)
    data_names = [n for n in sym.list_arguments()
                  if n not in arg_params]
    inputs = [_load_symbol_var(n) for n in data_names]
    net = gluon.SymbolBlock(sym, inputs)
    net_params = net.collect_params()
    for name, arr in {**arg_params, **aux_params}.items():
        if name in net_params:
            net_params[name]._load_init(arr, ctx)
    return net


def _load_symbol_var(name):
    from ... import symbol as S

    return S.Variable(name)


def get_model_metadata(model_file: str) -> Dict[str, List]:
    """{'input_tensor_data': [(name, shape)...], 'output_tensor_data': ...}
    for an ONNX file's data inputs (initializers excluded).

    Reference: python/mxnet/contrib/onnx/onnx2mx/import_model.py
    get_model_metadata ~L60-100.
    """
    with open(model_file, "rb") as f:
        model = proto.parse_model(f.read())
    graph = model["graph"]
    if graph is None:
        raise MXNetError(f"{model_file!r}: no graph")
    init_names = {t["name"] for t in graph["initializer"]}
    meta = {
        "input_tensor_data": [
            (i["name"], tuple(i["shape"] or ()))
            for i in graph["input"] if i["name"] not in init_names],
        "output_tensor_data": [
            (o["name"], tuple(o["shape"] or ())) for o in graph["output"]],
    }
    return meta
