"""ONNX export of int8-quantized graphs (docs/PRECISION.md §ONNX).

Two forms, per the deployment scenario ROADMAP item 5 names:

``mode="qdq"`` (default) — the standard ONNX *QDQ* representation:
every quantized layer exports as ``QuantizeLinear -> DequantizeLinear``
around the activation (calibrated scale, int8 zero-point 0) plus an int8
weight initializer behind its own ``DequantizeLinear``.  Backends that
understand QDQ (onnxruntime, TensorRT) fuse these into real int8
kernels; numerically the graph computes exactly what the
``ops/quantization.py`` primitives compute (symmetric 127-level scheme;
the only divergence is QuantizeLinear's -128 saturation point vs our
-127 clip, and the bias fold — our kernels round the bias into int32
accumulator units, QDQ adds it in f32).  Requires calibrated activation
thresholds (``calib_mode`` naive/entropy): dynamic per-batch ranges are
not expressible as static ``QuantizeLinear`` scales.

``mode="dequant"`` — the documented dequantize-fallback: weights are
dequantized at export time (``int8 -> f32`` with the quantization error
baked in) and the graph is plain opset-11 f32 ops.  Loses the int8
size/speed story but round-trips through ANY opset-11 importer —
including this package's own ``import_model``/``import_to_gluon`` — so
it is the interop-maximal form.

Both forms accept the product of ``contrib.quantization.quantize_net``
(the ``_QuantizedNet`` mirror over a (Hybrid)Sequential).  Supported
parts: quantized Dense/Conv2D twins, plain Dense/Activation/Flatten
(Dropout is dropped — inference identity); anything else raises,
loudly — exporting a layer this module cannot faithfully express would
produce a silently-wrong model file.
"""
from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...base import MXNetError
from . import proto

__all__ = ["export_quantized_net"]

_ACT_MAP = {"relu": "Relu", "sigmoid": "Sigmoid", "tanh": "Tanh",
            "softsign": "Softsign", "softrelu": "Softplus"}


class _Builder:
    def __init__(self):
        self.nodes: List[bytes] = []
        self.initializers: List[bytes] = []
        self._n = 0

    def tmp(self, base: str) -> str:
        self._n += 1
        return f"{base}_{self._n}"

    def init(self, name: str, arr: np.ndarray) -> str:
        self.initializers.append(proto.make_tensor(name, arr))
        return name

    def node(self, op_type: str, inputs, outputs, **attrs):
        self.nodes.append(proto.make_node(op_type, inputs, outputs,
                                          name=outputs[0], **attrs))
        return outputs[0]


def _flatten_parts(qnet) -> List:
    out = []
    for part in getattr(qnet, "_parts", ()):
        if hasattr(part, "_parts"):       # nested _QuantizedNet mirror
            out.extend(_flatten_parts(part))
        elif hasattr(part, "_impl"):      # _QuantizedWrapper
            out.append(part._impl)
        else:
            out.append(part)
    return out


def _qdq_input(b: _Builder, x: str, thresh: Optional[float], where: str,
               mode: str) -> str:
    """QuantizeLinear+DequantizeLinear around an activation edge (qdq
    mode), identity in dequant mode."""
    if mode != "qdq":
        return x
    if thresh is None:
        raise MXNetError(
            f"export_quantized_net(mode='qdq'): layer {where!r} has no "
            f"calibrated activation threshold (quantize_net ran with "
            f"calib_mode='none') — QuantizeLinear needs a static scale; "
            f"re-quantize with calib_mode naive/entropy, or export with "
            f"mode='dequant'")
    scale = b.init(b.tmp(f"{where}_xscale"),
                   np.asarray(float(thresh) / 127.0, np.float32))
    zp = b.init(b.tmp(f"{where}_xzp"), np.asarray(0, np.int8))
    q = b.node("QuantizeLinear", [x, scale, zp], [b.tmp(f"{where}_xq")])
    return b.node("DequantizeLinear", [q, scale, zp],
                  [b.tmp(f"{where}_xdq")])


def _weight_input(b: _Builder, qweight, w_thresh: float, where: str,
                  mode: str, transpose_to=None) -> str:
    """The weight edge: int8 initializer + DequantizeLinear (qdq), or a
    dequantized f32 initializer (dequant fallback)."""
    qw = np.asarray(qweight.asnumpy(), np.int8)
    if transpose_to is not None:
        qw = qw.transpose(transpose_to)
    scale = float(w_thresh) / 127.0
    if mode == "qdq":
        wq = b.init(b.tmp(f"{where}_wq"), qw)
        ws = b.init(b.tmp(f"{where}_wscale"),
                    np.asarray(scale, np.float32))
        wzp = b.init(b.tmp(f"{where}_wzp"), np.asarray(0, np.int8))
        return b.node("DequantizeLinear", [wq, ws, wzp],
                      [b.tmp(f"{where}_wdq")])
    return b.init(b.tmp(f"{where}_w"),
                  (qw.astype(np.float32) * scale).astype(np.float32))


def _export_qdense(b: _Builder, qd, x: str, rank: int, idx: int,
                   mode: str):
    where = f"qdense{idx}"
    if qd._flatten and rank > 2:
        x = b.node("Flatten", [x], [b.tmp(f"{where}_flat")], axis=1)
        rank = 2
    xin = _qdq_input(b, x, qd._calib_thresh, where, mode)
    w_thresh = float(qd._w_thresh)
    bias = b.init(b.tmp(f"{where}_b"),
                  np.asarray(qd._bias.asnumpy(), np.float32))
    if rank == 2:
        w = _weight_input(b, qd._qweight, w_thresh, where, mode)
        out = b.node("Gemm", [xin, w, bias], [b.tmp(f"{where}_out")],
                     transB=1)
    else:
        # per-position projection (flatten=False, rank>2): MatMul over
        # the pre-transposed (in, units) weight + bias Add
        w = _weight_input(b, qd._qweight, w_thresh, where, mode,
                          transpose_to=(1, 0))
        mm = b.node("MatMul", [xin, w], [b.tmp(f"{where}_mm")])
        out = b.node("Add", [mm, bias], [b.tmp(f"{where}_out")])
    if qd._act_type:
        out = b.node(_ACT_MAP[qd._act_type], [out],
                     [b.tmp(f"{where}_act")])
    return out, rank


def _export_qconv(b: _Builder, qc, x: str, rank: int, idx: int, mode: str):
    where = f"qconv{idx}"
    k = qc._kwargs
    if (k.get("layout") or "NCHW") != "NCHW":
        raise MXNetError(
            f"export_quantized_net: quantized conv {where!r} uses layout "
            f"{k.get('layout')!r}; only NCHW exports (ONNX Conv is "
            f"channel-first)")
    xin = _qdq_input(b, x, qc._calib_thresh, where, mode)
    w_thresh = float(qc._w_thresh)
    w = _weight_input(b, qc._qweight, w_thresh, where, mode)
    bias = b.init(b.tmp(f"{where}_b"),
                  np.asarray(qc._bias.asnumpy(), np.float32))
    kernel = tuple(k["kernel"])
    n = len(kernel)
    stride = tuple(k.get("stride") or (1,) * n)
    pad = tuple(k.get("pad") or (0,) * n)
    dilate = tuple(k.get("dilate") or (1,) * n)
    out = b.node("Conv", [xin, w, bias], [b.tmp(f"{where}_out")],
                 kernel_shape=list(kernel), strides=list(stride),
                 pads=list(pad) + list(pad), dilations=list(dilate),
                 group=int(k.get("num_group", 1)))
    if qc._act_type:
        out = b.node(_ACT_MAP[qc._act_type], [out],
                     [b.tmp(f"{where}_act")])
    return out, rank


def _export_plain_dense(b: _Builder, layer, x: str, rank: int, idx: int):
    where = f"dense{idx}"
    if getattr(layer, "_flatten", True) and rank > 2:
        x = b.node("Flatten", [x], [b.tmp(f"{where}_flat")], axis=1)
        rank = 2
    w = b.init(b.tmp(f"{where}_w"),
               np.asarray(layer.weight.data().asnumpy(), np.float32))
    units = layer._units
    bias = b.init(
        b.tmp(f"{where}_b"),
        np.asarray(layer.bias.data().asnumpy(), np.float32)
        if layer.bias is not None else np.zeros((units,), np.float32))
    if rank == 2:
        out = b.node("Gemm", [x, w, bias], [b.tmp(f"{where}_out")],
                     transB=1)
    else:
        wt = b.init(b.tmp(f"{where}_wt"),
                    np.ascontiguousarray(
                        np.asarray(layer.weight.data().asnumpy(),
                                   np.float32).T))
        mm = b.node("MatMul", [x, wt], [b.tmp(f"{where}_mm")])
        out = b.node("Add", [mm, bias], [b.tmp(f"{where}_out")])
    if layer._act_type:
        out = b.node(_ACT_MAP[layer._act_type], [out],
                     [b.tmp(f"{where}_act")])
    return out, rank


def export_quantized_net(qnet, input_shape, onnx_file_path: str,
                         mode: str = "qdq") -> str:
    """Export a ``quantize_net`` product to an ONNX file (module
    docstring has the two modes).  ``input_shape`` is the fixed data
    shape (batch included)."""
    from ...contrib.quantization import QuantizedConv2D, QuantizedDense
    from ...gluon import nn as gnn

    if mode not in ("qdq", "dequant"):
        raise MXNetError(f"export_quantized_net: mode must be 'qdq' or "
                         f"'dequant', got {mode!r}")
    parts = _flatten_parts(qnet)
    if not parts:
        raise MXNetError("export_quantized_net: empty quantized net")
    b = _Builder()
    x = "data"
    rank = len(tuple(input_shape))
    qidx = 0
    for part in parts:
        if isinstance(part, QuantizedDense):
            qidx += 1
            x, rank = _export_qdense(b, part, x, rank, qidx, mode)
        elif isinstance(part, QuantizedConv2D):
            qidx += 1
            x, rank = _export_qconv(b, part, x, rank, qidx, mode)
        elif isinstance(part, gnn.Dense):
            qidx += 1
            x, rank = _export_plain_dense(b, part, x, rank, qidx)
        elif isinstance(part, gnn.Activation):
            x = b.node(_ACT_MAP[part._act_type], [x], [b.tmp("act")])
        elif isinstance(part, gnn.Flatten):
            x = b.node("Flatten", [x], [b.tmp("flat")], axis=1)
            rank = 2
        elif isinstance(part, gnn.Dropout):
            continue  # inference identity
        else:
            raise MXNetError(
                f"export_quantized_net: unsupported part "
                f"{type(part).__name__} — only quantized Dense/Conv2D "
                f"twins and plain Dense/Activation/Flatten/Dropout "
                f"export faithfully")
    graph = proto.make_graph(
        b.nodes, "mxnet_tpu_int8",
        inputs=[proto.make_tensor_value_info(
            "data", proto.FLOAT, list(input_shape))],
        outputs=[proto.make_tensor_value_info(x, proto.FLOAT, None)],
        initializers=b.initializers)
    # QDQ ops (QuantizeLinear/DequantizeLinear) entered ONNX at opset 10;
    # the rest of the emitted surface is opset-11 stable
    model = proto.make_model(graph, opset=11)
    with open(onnx_file_path, "wb") as f:
        f.write(model)
    return onnx_file_path
