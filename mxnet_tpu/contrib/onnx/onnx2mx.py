"""ONNX ModelProto -> Symbol graph importer.

Reference parity: python/mxnet/contrib/onnx/onnx2mx/import_onnx.py
(GraphProto.from_onnx ~L1-250 + per-op `_convert_map`).  Same shape
here: decode the wire format with ``proto.py``, then map each ONNX node
to a symbol op; initializers become arg_params (BatchNormalization's
running mean/var become aux_params, matching the executor's aux-state
convention).
"""
from __future__ import annotations

from typing import Dict

import numpy as np

from ...base import MXNetError
from . import proto as P


class _Importer:
    def __init__(self, graph: Dict):
        self.graph = graph
        self.init: Dict[str, np.ndarray] = {
            t["name"]: t["array"] for t in graph["initializer"]}
        self.tensors: Dict[str, object] = {}   # onnx name -> Symbol
        self.aux_names: set = set()
        self.used_params: set = set()
        self._uid = 0

    # -- helpers -----------------------------------------------------------

    def sym(self):
        from ... import symbol as S
        return S

    def get(self, name):
        """Symbol for an ONNX tensor name (variable for params/inputs).
        Param variables carry their initializer's shape so downstream
        infer_shape/simple_bind resolve without the caller re-supplying
        every constant's shape."""
        if name not in self.tensors:
            if name in self.init:
                self.tensors[name] = self.sym().Variable(
                    name, shape=tuple(self.init[name].shape))
                self.used_params.add(name)
            else:
                self.tensors[name] = self.sym().Variable(name)
        return self.tensors[name]

    def const(self, name) -> np.ndarray:
        """A tensor that must be compile-time static (shape vectors,
        clip bounds) — i.e. present as an initializer."""
        if name not in self.init:
            raise MXNetError(
                f"ONNX import: input {name!r} must be an initializer")
        self.used_params.discard(name)  # consumed statically, not a param
        return self.init[name]

    def set_out(self, node, outputs):
        names = node["output"]
        for name, out in zip(names, outputs):
            if name:
                self.tensors[name] = out

    # -- op converters -----------------------------------------------------

    def convert(self, node):
        op = node["op_type"]
        fn = getattr(self, f"op_{op}", None)
        if fn is None:
            raise MXNetError(
                f"No MXNet conversion registered for ONNX op {op!r} "
                f"(node {node['name']!r})")
        fn(node, node["attrs"], [self.get(i) for i in node["input"] if i])

    def op_Conv(self, node, attrs, ins):
        self._check_auto_pad(node, attrs)
        pads = attrs.get("pads")
        kernel = attrs["kernel_shape"]
        ndim = len(kernel)
        if pads and pads[:ndim] != pads[ndim:]:
            raise MXNetError("ONNX import: asymmetric Conv pads unsupported")
        w = self.const_shape(node["input"][1])
        out = self.sym().Convolution(
            *ins, kernel=tuple(kernel),
            stride=tuple(attrs.get("strides", [1] * ndim)),
            dilate=tuple(attrs.get("dilations", [1] * ndim)),
            pad=tuple((pads or [0] * 2 * ndim)[:ndim]),
            num_group=int(attrs.get("group", 1)),
            num_filter=int(w[0]), no_bias=len(ins) == 2,
            name=self._name(node))
        self.set_out(node, [out])

    def const_shape(self, name):
        if name in self.init:
            return self.init[name].shape
        raise MXNetError(f"ONNX import: weight {name!r} must be an "
                         "initializer to infer its layer config")

    @staticmethod
    def _check_auto_pad(node, attrs):
        # SAME_UPPER/SAME_LOWER carry no pads attr; importing them as
        # pad=0 would be silently wrong.  VALID *is* pads=0 — allowed.
        if attrs.get("auto_pad", "NOTSET") not in ("NOTSET", "", "VALID"):
            raise MXNetError(
                f"ONNX import: {node['op_type']} "
                f"auto_pad={attrs['auto_pad']!r} unsupported "
                "(explicit pads only)")

    def op_ConvTranspose(self, node, attrs, ins):
        self._check_auto_pad(node, attrs)
        kernel = attrs["kernel_shape"]
        ndim = len(kernel)
        pads = attrs.get("pads", [0] * 2 * ndim)
        if pads[:ndim] != pads[ndim:]:
            raise MXNetError(
                "ONNX import: asymmetric ConvTranspose pads unsupported")
        if attrs.get("output_shape"):
            raise MXNetError(
                "ONNX import: ConvTranspose output_shape unsupported")
        w = self.const_shape(node["input"][1])
        group = int(attrs.get("group", 1))
        out = self.sym().Deconvolution(
            *ins, kernel=tuple(kernel),
            stride=tuple(attrs.get("strides", [1] * ndim)),
            dilate=tuple(attrs.get("dilations", [1] * ndim)),
            pad=tuple(pads[:ndim]),
            adj=tuple(attrs.get("output_padding", [0] * ndim)),
            num_group=group, num_filter=int(w[1]) * group,
            no_bias=len(ins) == 2, name=self._name(node))
        self.set_out(node, [out])

    def op_BatchNormalization(self, node, attrs, ins):
        for aux in node["input"][3:5]:
            self.aux_names.add(aux)
        out = self.sym().BatchNorm(
            *ins, eps=float(attrs.get("epsilon", 1e-5)),
            momentum=float(attrs.get("momentum", 0.9)),
            fix_gamma=False, name=self._name(node))
        self.set_out(node, [out])

    def op_Gemm(self, node, attrs, ins):
        if (attrs.get("transA", 0) or not attrs.get("transB", 0)
                or attrs.get("alpha", 1.0) != 1.0
                or attrs.get("beta", 1.0) != 1.0):
            raise MXNetError("ONNX import: only Gemm(alpha=1, beta=1, "
                             "transB=1) maps to FullyConnected")
        w = self.const_shape(node["input"][1])
        out = self.sym().FullyConnected(
            *ins, num_hidden=int(w[0]), no_bias=len(ins) == 2,
            flatten=False, name=self._name(node))
        self.set_out(node, [out])

    def op_MatMul(self, node, attrs, ins):
        # ONNX MatMul is batched over leading dims; linalg_gemm2 has the
        # same contract (plain 2D included) — sym.dot would contract the
        # wrong axes for rank>2
        self.set_out(node, [self.sym().linalg.gemm2(
            *ins, name=self._name(node))])

    def op_Gather(self, node, attrs, ins):
        self.set_out(node, [self.sym().take(
            ins[0], ins[1], axis=int(attrs.get("axis", 0)),
            name=self._name(node))])

    def op_Expand(self, node, attrs, ins):
        shape = tuple(int(s) for s in self.const(node["input"][1]))
        self.set_out(node, [self.sym().broadcast_to(
            ins[0], shape=shape, name=self._name(node))])

    def op_Where(self, node, attrs, ins):
        self.set_out(node, [self.sym().where(
            *ins, name=self._name(node))])

    def op_Greater(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_greater")

    def op_Less(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_lesser")

    def op_Equal(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_equal")

    def op_Not(self, node, attrs, ins):
        self.set_out(node, [self.sym().logical_not(
            ins[0], name=self._name(node))])

    def op_CumSum(self, node, attrs, ins):
        if attrs.get("exclusive") or attrs.get("reverse"):
            raise MXNetError("ONNX import: CumSum exclusive/reverse "
                             "unsupported")
        axis = int(np.asarray(self.const(node["input"][1])).flat[0])
        self.set_out(node, [self.sym().cumsum(
            ins[0], axis=axis, name=self._name(node))])

    def op_Slice(self, node, attrs, ins):
        names = node["input"]
        if len(names) >= 3:  # opset 10+: starts/ends[/axes[/steps]] inputs
            starts = [int(v) for v in self.const(names[1])]
            ends = [int(v) for v in self.const(names[2])]
            # axes/steps are optional; "" is the empty-placeholder form
            if len(names) >= 4 and names[3]:
                axes = [int(v) for v in self.const(names[3])]
            else:
                axes = list(range(len(starts)))
            if len(names) >= 5 and names[4]:
                steps = [int(v) for v in self.const(names[4])]
                if any(s != 1 for s in steps):
                    raise MXNetError("ONNX import: strided Slice")
        else:  # opset <10: attributes
            starts = [int(v) for v in attrs["starts"]]
            ends = [int(v) for v in attrs["ends"]]
            axes = [int(v) for v in attrs.get("axes",
                                              range(len(starts)))]
        out = ins[0]
        S = self.sym()
        big = 1 << 60
        for ax, b, e in zip(axes, starts, ends):
            out = S.slice_axis(out, axis=ax, begin=b,
                               end=None if e >= big else e)
        self.set_out(node, [out])

    def _pool(self, node, attrs, ins, pool_type, global_pool=False):
        kw = dict(pool_type=pool_type, global_pool=global_pool,
                  name=self._name(node))
        if not global_pool:
            kernel = attrs["kernel_shape"]
            ndim = len(kernel)
            pads = attrs.get("pads", [0] * 2 * ndim)
            if pads[:ndim] != pads[ndim:]:
                raise MXNetError(
                    "ONNX import: asymmetric Pool pads unsupported")
            kw.update(kernel=tuple(kernel),
                      stride=tuple(attrs.get("strides", [1] * ndim)),
                      pad=tuple(pads[:ndim]),
                      pooling_convention=("full" if attrs.get("ceil_mode")
                                          else "valid"))
            if pool_type == "avg":
                kw["count_include_pad"] = bool(
                    attrs.get("count_include_pad", 0))
        self.set_out(node, [self.sym().Pooling(ins[0], **kw)])

    def op_MaxPool(self, node, attrs, ins):
        self._pool(node, attrs, ins, "max")

    def op_AveragePool(self, node, attrs, ins):
        self._pool(node, attrs, ins, "avg")

    def op_GlobalMaxPool(self, node, attrs, ins):
        self._pool(node, attrs, ins, "max", global_pool=True)

    def op_GlobalAveragePool(self, node, attrs, ins):
        self._pool(node, attrs, ins, "avg", global_pool=True)

    def op_Flatten(self, node, attrs, ins):
        if attrs.get("axis", 1) != 1:
            raise MXNetError("ONNX import: Flatten axis != 1 unsupported")
        self.set_out(node, [self.sym().Flatten(ins[0],
                                               name=self._name(node))])

    def _act(self, node, ins, act_type):
        self.set_out(node, [self.sym().Activation(
            ins[0], act_type=act_type, name=self._name(node))])

    def op_Relu(self, node, attrs, ins):
        self._act(node, ins, "relu")

    def op_Sigmoid(self, node, attrs, ins):
        self._act(node, ins, "sigmoid")

    def op_Tanh(self, node, attrs, ins):
        self._act(node, ins, "tanh")

    def op_Softplus(self, node, attrs, ins):
        self._act(node, ins, "softrelu")

    def op_Softsign(self, node, attrs, ins):
        self._act(node, ins, "softsign")

    def op_LeakyRelu(self, node, attrs, ins):
        self.set_out(node, [self.sym().LeakyReLU(
            ins[0], act_type="leaky",
            slope=float(attrs.get("alpha", 0.01)),
            name=self._name(node))])

    def op_Elu(self, node, attrs, ins):
        self.set_out(node, [self.sym().LeakyReLU(
            ins[0], act_type="elu", slope=float(attrs.get("alpha", 1.0)),
            name=self._name(node))])

    def op_PRelu(self, node, attrs, ins):
        self.set_out(node, [self.sym().LeakyReLU(
            *ins, act_type="prelu", name=self._name(node))])

    def _softmax(self, node, attrs, ins, op):
        # opset<13 Softmax flattens [d0..daxis-1], [daxis..dn] and
        # normalizes rows.  axis=-1 equals single-axis softmax on the last
        # dim; axis=1 (the ONNX default) is reproduced rank-generically by
        # collapsing trailing dims, applying softmax, and restoring the
        # shape; other axes need rank info we don't have — raise.
        S = self.sym()
        axis = int(attrs.get("axis", 1))
        fn = getattr(S, op)
        if axis == -1:
            out = fn(ins[0], axis=-1, name=self._name(node))
        elif axis == 1:
            flat = S.Reshape(ins[0], shape=(0, -1))
            out = S.reshape_like(fn(flat, axis=-1, name=self._name(node)),
                                 ins[0])
        else:
            raise MXNetError(
                f"ONNX import: {node['op_type']} axis={axis} flatten "
                "semantics unsupported (only axis in (1, -1))")
        self.set_out(node, [out])

    def op_Softmax(self, node, attrs, ins):
        self._softmax(node, attrs, ins, "softmax")

    def op_LogSoftmax(self, node, attrs, ins):
        self._softmax(node, attrs, ins, "log_softmax")

    def op_Dropout(self, node, attrs, ins):
        self.set_out(node, [self.sym().Dropout(
            ins[0], p=float(attrs.get("ratio", 0.5)),
            name=self._name(node))])

    def _binary(self, node, ins, op):
        self.set_out(node, [getattr(self.sym(), op)(
            ins[0], ins[1], name=self._name(node))])

    def op_Add(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_add")

    def op_Sub(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_sub")

    def op_Mul(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_mul")

    def op_Div(self, node, attrs, ins):
        self._binary(node, ins, "broadcast_div")

    def op_Sum(self, node, attrs, ins):
        self.set_out(node, [self.sym().add_n(*ins, name=self._name(node))])

    def op_Concat(self, node, attrs, ins):
        self.set_out(node, [self.sym().Concat(
            *ins, dim=int(attrs.get("axis", 1)), name=self._name(node))])

    def op_Reshape(self, node, attrs, ins):
        shape = tuple(int(s) for s in self.const(node["input"][1]))
        self.set_out(node, [self.sym().Reshape(
            ins[0], shape=shape, name=self._name(node))])

    def op_Transpose(self, node, attrs, ins):
        perm = attrs.get("perm")
        kw = {"axes": tuple(int(p) for p in perm)} if perm else {}
        self.set_out(node, [self.sym().transpose(
            ins[0], name=self._name(node), **kw)])

    def op_Clip(self, node, attrs, ins):
        def bound(idx, default):
            # opset 11: min/max are optional inputs; "" is the standard
            # empty-placeholder for an omitted one
            names = node["input"]
            if len(names) > idx and names[idx]:
                return float(np.asarray(self.const(names[idx])).flat[0])
            return default
        if len(node["input"]) >= 2:  # opset 11 form
            lo = bound(1, -np.inf)
            hi = bound(2, np.inf)
        else:  # opset <11: attributes
            lo = float(attrs.get("min", -np.inf))
            hi = float(attrs.get("max", np.inf))
        self.set_out(node, [self.sym().clip(
            ins[0], a_min=lo, a_max=hi, name=self._name(node))])

    def op_Identity(self, node, attrs, ins):
        self.set_out(node, [ins[0]])

    def op_Squeeze(self, node, attrs, ins):
        axes = attrs.get("axes")
        kw = {"axis": tuple(int(a) for a in axes)} if axes else {}
        self.set_out(node, [self.sym().squeeze(
            ins[0], name=self._name(node), **kw)])

    def op_Unsqueeze(self, node, attrs, ins):
        out = ins[0]
        S = self.sym()
        for a in sorted(int(x) for x in attrs["axes"]):
            out = S.expand_dims(out, axis=a)
        self.set_out(node, [out])

    def op_Split(self, node, attrs, ins):
        sizes = attrs.get("split")
        if sizes is None and len(node["input"]) >= 2 and node["input"][1] \
                and node["input"][1] in self.init:
            # opset 13+: split sizes arrive as a second input rather than
            # an attribute; validate when statically known (initializer or
            # Constant) — runtime-computed sizes keep the legacy
            # even-split import
            sizes = [int(s) for s in
                     np.asarray(self.const(node["input"][1])).flatten()]
        if sizes and len(set(int(s) for s in sizes)) > 1:
            # SliceChannel only emits equal parts; importing an uneven
            # split as an even one would silently produce wrong shapes
            raise MXNetError(
                f"ONNX Split node {self._name(node)!r}: uneven split "
                f"sizes {[int(s) for s in sizes]} are not supported "
                "(SliceChannel emits equal parts only); re-export the "
                "model with equal splits")
        out = self.sym().SliceChannel(
            ins[0], num_outputs=len(node["output"]),
            axis=int(attrs.get("axis", 0)), name=self._name(node))
        self.set_out(node, list(out))

    def op_Cast(self, node, attrs, ins):
        self.set_out(node, [self.sym().cast(
            ins[0], dtype=P.onnx_to_np_dtype(attrs["to"]).name,
            name=self._name(node))])

    def op_Constant(self, node, attrs, ins):
        name = node["output"][0]
        t = attrs.get("value")
        if t is None:
            # ONNX allows value_float/value_int/value_floats/... variants;
            # only the tensor form is supported — name the form found
            # instead of dying with a bare KeyError
            present = sorted(k for k in attrs if k.startswith("value")
                             or k == "sparse_value")
            raise MXNetError(
                f"ONNX Constant node {name!r}: only the tensor-valued "
                f"`value` attribute is supported, got "
                f"{present or sorted(attrs)}; re-export the constant as "
                "a tensor")
        self.init[name] = t["array"]
        # materialized lazily (as a param or via const()) on first use

    def _unary(self, node, ins, op):
        self.set_out(node, [getattr(self.sym(), op)(
            ins[0], name=self._name(node))])

    def op_Exp(self, node, attrs, ins):
        self._unary(node, ins, "exp")

    def op_Log(self, node, attrs, ins):
        self._unary(node, ins, "log")

    def op_Sqrt(self, node, attrs, ins):
        self._unary(node, ins, "sqrt")

    def op_Abs(self, node, attrs, ins):
        self._unary(node, ins, "abs")

    def op_Neg(self, node, attrs, ins):
        self._unary(node, ins, "negative")

    def op_Erf(self, node, attrs, ins):
        self._unary(node, ins, "erf")

    def op_Floor(self, node, attrs, ins):
        self._unary(node, ins, "floor")

    def op_Ceil(self, node, attrs, ins):
        self._unary(node, ins, "ceil")

    def _reduce(self, node, attrs, ins, op):
        axes = attrs.get("axes")
        kw = {"keepdims": bool(attrs.get("keepdims", 1))}
        if axes is not None:
            kw["axis"] = tuple(int(a) for a in axes)
        self.set_out(node, [getattr(self.sym(), op)(
            ins[0], name=self._name(node), **kw)])

    def op_ReduceMean(self, node, attrs, ins):
        self._reduce(node, attrs, ins, "mean")

    def op_ReduceSum(self, node, attrs, ins):
        self._reduce(node, attrs, ins, "sum")

    # -- driver ------------------------------------------------------------

    def _name(self, node):
        if node["name"]:
            return node["name"]
        self._uid += 1
        return f"onnx_{node['op_type'].lower()}{self._uid}"

    def run(self):
        from ... import ndarray as nd

        for node in self.graph["node"]:
            self.convert(node)
        outs = [self.tensors[o["name"]] for o in self.graph["output"]]
        S = self.sym()
        sym = outs[0] if len(outs) == 1 else S.Group(outs)
        arg_params, aux_params = {}, {}
        for name in self.used_params:
            arr = nd.array(np.ascontiguousarray(self.init[name]))
            (aux_params if name in self.aux_names else arg_params)[name] = arr
        return sym, arg_params, aux_params


def import_onnx_model(model_bytes: bytes):
    model = P.parse_model(model_bytes)
    if model["graph"] is None:
        raise MXNetError("ONNX import: no graph in model file")
    return _Importer(model["graph"]).run()
