"""BaseModule: the symbolic-era training loop.

Reference parity: python/mxnet/module/base_module.py (fit() epoch loop
~L450-600, score/predict/forward_backward helpers).
"""
from __future__ import annotations

import logging
import time
from collections import namedtuple

from ..base import MXNetError
from .. import metric as _metric

__all__ = ["BaseModule", "BatchEndParam"]

# `loss` (default None): optional LAZY loss handle — see model.BatchEndParam
BatchEndParam = namedtuple("BatchEndParam",
                           ["epoch", "nbatch", "eval_metric", "locals",
                            "loss"])
BatchEndParam.__new__.__defaults__ = (None,)


class BaseModule:
    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = False
        self.for_training = False
        self.params_initialized = False
        self.optimizer_initialized = False
        self._symbol = None

    # -- abstract interface (Module implements) ----------------------------
    def bind(self, *args, **kwargs):
        raise NotImplementedError

    def init_params(self, *args, **kwargs):
        raise NotImplementedError

    def init_optimizer(self, *args, **kwargs):
        raise NotImplementedError

    def forward(self, data_batch, is_train=None):
        raise NotImplementedError

    def backward(self, out_grads=None):
        raise NotImplementedError

    def update(self):
        raise NotImplementedError

    def get_outputs(self):
        raise NotImplementedError

    def get_params(self):
        raise NotImplementedError

    def update_metric(self, eval_metric, labels):
        raise NotImplementedError

    @property
    def symbol(self):
        return self._symbol

    # -- shared helpers -----------------------------------------------------
    def forward_backward(self, data_batch):
        self.forward(data_batch, is_train=True)
        self.backward()

    def score(self, eval_data, eval_metric, num_batch=None, reset=True,
              epoch=0, batch_end_callback=None):
        if not self.binded or not self.params_initialized:
            raise MXNetError("module must be binded and initialized")
        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        if reset:
            eval_data.reset()
        eval_metric.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            if batch_end_callback is not None:
                param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                      eval_metric=eval_metric, locals=locals())
                for cb in _as_list(batch_end_callback):
                    cb(param)
        return eval_metric.get_name_value()

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True):
        from ..ndarray import concat

        if reset:
            eval_data.reset()
        out_batches = []
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch == num_batch:
                break
            self.forward(batch, is_train=False)
            outs = [o.copy() for o in self.get_outputs()]
            pad = batch.pad or 0
            if pad:
                outs = [o[: o.shape[0] - pad] for o in outs]
            out_batches.append(outs)
        if not out_batches:
            return []
        num_outputs = len(out_batches[0])
        if merge_batches:
            merged = [concat(*[b[i] for b in out_batches], dim=0)
                      for i in range(num_outputs)]
            return merged[0] if num_outputs == 1 else merged
        return out_batches

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None,
            kvstore="local", optimizer="sgd",
            optimizer_params=(("learning_rate", 0.01),),
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None, sparse_row_id_fn=None):
        """The classic Module.fit epoch loop (reference ~L450-600)."""
        from .. import initializer as _init

        if num_epoch is None:
            raise MXNetError("num_epoch must be specified")
        if initializer is None:
            initializer = _init.Uniform(0.01)

        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        self.init_params(initializer=initializer, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)
        self.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                            optimizer_params=optimizer_params)

        if not isinstance(eval_metric, _metric.EvalMetric):
            eval_metric = _metric.create(eval_metric)
        if validation_metric is None:
            validation_metric = eval_metric
        if monitor is not None:
            self.install_monitor(monitor)

        for epoch in range(begin_epoch, num_epoch):
            tic = time.perf_counter()
            eval_metric.reset()
            train_data.reset()
            for nbatch, data_batch in enumerate(train_data):
                if monitor is not None:
                    monitor.tic()
                self.forward_backward(data_batch)
                self.update()
                if monitor is not None:
                    monitor.toc_print()
                self.update_metric(eval_metric, data_batch.label)
                if batch_end_callback is not None:
                    param = BatchEndParam(epoch=epoch, nbatch=nbatch,
                                          eval_metric=eval_metric,
                                          locals=locals())
                    for cb in _as_list(batch_end_callback):
                        cb(param)
            drain = getattr(self, "drain", None)
            if drain is not None:
                # epoch exhaustion lands every in-flight update (and
                # surfaces any deferred failure) before params are read
                drain()
            for name, val in eval_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f", epoch,
                             time.perf_counter() - tic)

            if epoch_end_callback is not None:
                arg_p, aux_p = self.get_params()
                for cb in _as_list(epoch_end_callback):
                    cb(epoch, self.symbol, arg_p, aux_p)

            if eval_data is not None:
                res = self.score(eval_data, validation_metric,
                                 batch_end_callback=eval_batch_end_callback,
                                 epoch=epoch)
                for name, val in res:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)


def _as_list(x):
    return x if isinstance(x, (list, tuple)) else [x]
