"""Module: the symbolic-era trainer over one bound Executor.

Reference parity: python/mxnet/module/module.py (Module.bind ~L400,
forward/backward, update via kvstore push/pull ~L600) and
executor_group.py (DataParallelExecutorGroup ~L1-700).

TPU-native design: the reference shards each batch across a `context` list
of GPUs with one executor per device plus kvstore reduce.  Under XLA the
same data parallelism is a sharding annotation on ONE executable (see
mxnet_tpu.parallel), so Module binds a single whole-graph executor on
ctx[0]; multi-chip training goes through `DataParallelStep`/`Trainer`, not
through per-device executor groups.
"""
from __future__ import annotations

import logging
from typing import Dict, List, Optional

from ..base import MXNetError
from .base_module import BaseModule
from ..io.io import DataDesc

__all__ = ["Module"]


class Module(BaseModule):
    def __init__(self, symbol, data_names=("data",), label_names=("softmax_label",),
                 logger=logging, context=None, work_load_list=None,
                 fixed_param_names=None, state_names=None,
                 group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        from ..context import current_context

        self._symbol = symbol
        self._data_names = list(data_names or [])
        self._label_names = list(label_names or [])
        self._fixed_param_names = list(fixed_param_names or [])
        ctx = context or current_context()
        self._context = ctx[0] if isinstance(ctx, (list, tuple)) else ctx
        arg_names = symbol.list_arguments()
        input_names = set(self._data_names) | set(self._label_names)
        self._param_names = [n for n in arg_names if n not in input_names]
        self._exec = None
        self._updater = None
        self._optimizer = None
        self._data_shapes = None
        self._label_shapes = None

    # -- binding -----------------------------------------------------------
    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        data_shapes = [_as_desc(d) for d in data_shapes]
        label_shapes = [_as_desc(l) for l in (label_shapes or [])]
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes

        shapes = {d.name: d.shape for d in data_shapes + label_shapes}
        self._inputs_need_grad = inputs_need_grad
        req: Dict[str, str] = {}
        for name in self._symbol.list_arguments():
            if name in self._data_names:
                req[name] = grad_req if (inputs_need_grad
                                         and for_training) else "null"
            elif name in self._label_names:
                req[name] = "null"
            elif name in self._fixed_param_names or not for_training:
                req[name] = "null"
            else:
                req[name] = grad_req
        prev_exec = self._exec
        self._exec = self._symbol.simple_bind(ctx=self._context,
                                              grad_req=req, **shapes)
        if getattr(self, "_monitor", None) is not None:
            # force_rebind: keep the monitor on the LIVE executor
            self._monitor.replace(prev_exec, self._exec)
        if shared_module is not None and shared_module._exec is not None:
            # share parameter arrays with another module (reference:
            # BucketingModule's shared executor groups): same NDArray objects
            for name, arr in shared_module._exec.arg_dict.items():
                if name in self._exec.arg_dict and name in self._param_names:
                    self._exec.arg_dict[name] = arr
            for name, arr in shared_module._exec.aux_dict.items():
                if name in self._exec.aux_dict:
                    self._exec.aux_dict[name] = arr
            for name, arr in shared_module._exec.grad_dict.items():
                if name in self._exec.grad_dict:
                    self._exec.grad_dict[name] = arr
        self.binded = True

    # -- parameters --------------------------------------------------------
    def install_monitor(self, mon) -> None:
        """Watch this module's executor arrays (reference: install per-op
        output callbacks; here the observable arg/grad/aux/output arrays —
        see mxnet_tpu/monitor.py docstring)."""
        if self._exec is None:
            raise MXNetError("bind() before install_monitor")
        self._monitor = mon
        mon.install(self._exec)

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        if not self.binded:
            raise MXNetError("call bind before init_params")
        preloaded = getattr(self, "_preloaded", None)
        if preloaded is not None and arg_params is None:
            arg_params, aux_params = preloaded
        from .. import initializer as _init

        default_init = initializer or _init.Uniform(0.01)
        # per-variable init attrs (e.g. mx.rnn LSTMCell forget-gate bias)
        # override the module-level default, as in the reference
        from ..symbol.symbol import _topo_order

        var_inits = {}
        for node in _topo_order(self._symbol._entries):
            if node.is_variable():
                init_attr = (node.vattrs or {}).get("init")
                if init_attr is not None:
                    var_inits[node.name] = (
                        _init.create(init_attr) if isinstance(init_attr, str)
                        else init_attr)
        for name in self._param_names:
            arr = self._exec.arg_dict[name]
            if arg_params and name in arg_params:
                arr._set_data(arg_params[name].copyto(self._context)._data)
            elif arg_params is not None and not allow_missing:
                raise MXNetError(
                    f"param {name!r} missing from arg_params "
                    f"(pass allow_missing=True to initialize it)")
            else:
                var_inits.get(name, default_init)(name, arr)
        for name, arr in self._exec.aux_dict.items():
            if aux_params and name in aux_params:
                arr._set_data(aux_params[name].copyto(self._context)._data)
            elif aux_params is not None and not allow_missing:
                raise MXNetError(
                    f"aux state {name!r} missing from aux_params "
                    f"(pass allow_missing=True to initialize it)")
            else:
                default_init(name, arr)
        self.params_initialized = True

    def get_params(self):
        arg = {n: self._exec.arg_dict[n].copyto(self._context)
               for n in self._param_names}
        aux = {n: a.copyto(self._context)
               for n, a in self._exec.aux_dict.items()}
        return arg, aux

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init)

    # -- optimizer ---------------------------------------------------------
    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        if self.optimizer_initialized and not force_init:
            return
        from .. import optimizer as _opt

        if isinstance(optimizer, str):
            optimizer = _opt.create(optimizer, **dict(optimizer_params))
        self._optimizer = optimizer
        self._updater = _opt.get_updater(optimizer)
        states_file = getattr(self, "_preloaded_states", None)
        if states_file is not None:
            self.load_optimizer_states(states_file)
            self._preloaded_states = None
        self.optimizer_initialized = True

    # -- execution ---------------------------------------------------------
    def forward(self, data_batch, is_train=None):
        if not self.binded:
            raise MXNetError("call bind before forward")
        if is_train is None:
            is_train = self.for_training
        feeds = {}
        for name, arr in zip(self._data_names, data_batch.data or []):
            feeds[name] = arr
        if data_batch.label:
            for name, arr in zip(self._label_names, data_batch.label):
                if name in self._exec.arg_dict:
                    feeds[name] = arr
        self._exec.forward(is_train=is_train, **feeds)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def update(self):
        if not self.optimizer_initialized:
            raise MXNetError("call init_optimizer before update")
        # bounded async dispatch (docs/PERFORMANCE.md §Async pipeline):
        # the executor's forward/backward and this update all queue
        # asynchronously in jax; the window keeps the host at most
        # MX_ASYNC_INFLIGHT un-synced steps ahead (0 = no fences)
        from ..parallel.async_loss import (InflightRing, StepFence,
                                           inflight_limit)

        limit = inflight_limit()
        if limit > 0:
            if getattr(self, "_inflight", None) is None:
                self._inflight = InflightRing("Module")
            self._inflight.make_room(limit)
        entries = []
        for i, name in enumerate(self._param_names):
            grad = self._exec.grad_dict.get(name)
            if grad is None:
                continue
            entries.append((i, grad, self._exec.arg_dict[name]))
        from ..optimizer.fused import FusedUpdater

        apply_batch = (self._updater.apply
                       if isinstance(self._updater, FusedUpdater) else None)
        if apply_batch is not None:
            # fused path: every dense param updates in one jitted call
            # (executor-owned buffers stay undonated — rebind aliases them)
            info = apply_batch(entries)
            from .. import telemetry

            if telemetry.enabled() and info.get("n_fused"):
                telemetry.record_fused_update(
                    n_params=info["n_params"], n_buckets=0,
                    nbytes=info["nbytes"],
                    n_jitted_calls=info["n_jitted_calls"])
        else:
            for i, grad, weight in entries:
                self._updater(i, grad, weight)
        if limit > 0 and entries:
            self._inflight.admit(StepFence(
                [w._data for _i, _g, w in entries],
                step=getattr(self, "_update_count", 0) + 1,
                executor="Module", ring=self._inflight))
            self._update_count = getattr(self, "_update_count", 0) + 1

    def drain(self) -> None:
        """Block until every in-flight update has landed (pre-checkpoint
        / end-of-fit sync)."""
        ring = getattr(self, "_inflight", None)
        if ring is not None:
            ring.drain()

    def get_outputs(self, merge_multi_context=True):
        return self._exec.outputs

    def get_input_grads(self, merge_multi_context=True):
        return [self._exec.grad_dict.get(n) for n in self._data_names]

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        eval_metric.update(labels, self.get_outputs())

    # -- checkpointing -----------------------------------------------------
    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        from ..model import save_checkpoint

        arg, aux = self.get_params()
        save_checkpoint(prefix, epoch, self._symbol, arg, aux)
        if save_optimizer_states and self._updater is not None:
            with open(f"{prefix}-{epoch:04d}.states", "wb") as f:
                f.write(self._updater.get_states())

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        from ..model import load_checkpoint

        sym, arg, aux = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._preloaded = (arg, aux)
        mod._preloaded_states = (f"{prefix}-{epoch:04d}.states"
                                 if load_optimizer_states else None)
        return mod

    def load_optimizer_states(self, fname):
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())

    @property
    def data_names(self):
        return self._data_names

    @property
    def label_names(self):
        return self._label_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_names(self):
        return self._symbol.list_outputs()

    @property
    def output_shapes(self):
        return [(n, tuple(o.shape)) for n, o in
                zip(self._symbol.list_outputs(), self._exec.outputs)]


def _as_desc(d):
    if isinstance(d, DataDesc):
        return d
    name, shape = d[0], d[1]
    return DataDesc(name=name, shape=tuple(shape))
