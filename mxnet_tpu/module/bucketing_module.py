"""BucketingModule: per-bucket executors sharing parameters.

Reference parity: python/mxnet/module/bucketing_module.py (~L1-500) — one
Module per bucket key, all sharing the same parameter arrays, switched by
each batch's bucket_key.

TPU-native note: one XLA executable per bucket shape is the natural mapping
(SURVEY.md §2.3 bucketing row); sharing the *same NDArray objects* across
modules makes parameter sharing free since executors read them at call time.
"""
from __future__ import annotations

import logging

from ..base import MXNetError
from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None, group2ctxs=None, compression_params=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise MXNetError("default_bucket_key must be given")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._context = context
        self._fixed_param_names = fixed_param_names
        self._buckets = {}
        self._curr_module = None
        self._curr_bucket_key = None
        self._opt_args = None

    @property
    def symbol(self):
        return self._curr_module.symbol if self._curr_module else None

    def _gen_module(self, bucket_key):
        sym, data_names, label_names = self._sym_gen(bucket_key)
        return Module(sym, data_names=data_names, label_names=label_names,
                      logger=self.logger, context=self._context,
                      fixed_param_names=self._fixed_param_names)

    def install_monitor(self, mon) -> None:
        """Watch every bucket's executor, including ones created later
        (reference: BucketingModule installs on all executor groups).
        May be called before bind(): bind installs on the default bucket.
        """
        self._monitor = mon
        for m in self._buckets.values():
            m.install_monitor(mon)

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            return
        self.for_training = for_training
        mod = self._gen_module(self._default_bucket_key)
        mod.bind(data_shapes, label_shapes, for_training=for_training,
                 grad_req=grad_req)
        self._buckets[self._default_bucket_key] = mod
        if getattr(self, "_monitor", None) is not None:
            mod.install_monitor(self._monitor)  # pre-bind install_monitor
        self._curr_module = mod
        self._curr_bucket_key = self._default_bucket_key
        self.binded = True

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        if not self.binded:
            raise MXNetError("call bind before switch_bucket")
        if bucket_key not in self._buckets:
            default_mod = self._buckets[self._default_bucket_key]
            mod = self._gen_module(bucket_key)
            mod.bind(data_shapes, label_shapes,
                     for_training=self.for_training,
                     shared_module=default_mod)
            mod.params_initialized = self.params_initialized
            mod._updater = default_mod._updater
            mod._optimizer = default_mod._optimizer
            mod.optimizer_initialized = default_mod.optimizer_initialized
            if getattr(self, "_monitor", None) is not None:
                mod.install_monitor(self._monitor)  # lazily created bucket
            self._buckets[bucket_key] = mod
        self._curr_module = self._buckets[bucket_key]
        self._curr_bucket_key = bucket_key

    def init_params(self, **kwargs):
        self._buckets[self._default_bucket_key].init_params(**kwargs)
        self.params_initialized = True
        for mod in self._buckets.values():
            mod.params_initialized = True

    def init_optimizer(self, **kwargs):
        default = self._buckets[self._default_bucket_key]
        default.init_optimizer(**kwargs)
        for mod in self._buckets.values():
            mod._updater = default._updater
            mod._optimizer = default._optimizer
            mod.optimizer_initialized = True
        self.optimizer_initialized = True

    def forward(self, data_batch, is_train=None):
        key = data_batch.bucket_key
        if key is None:
            key = self._curr_bucket_key
        default_mod = self._buckets[self._default_bucket_key]
        data_shapes = data_batch.provide_data or list(
            zip(default_mod.data_names,
                [d.shape for d in (data_batch.data or [])]))
        label_shapes = data_batch.provide_label or (
            list(zip(default_mod.label_names,
                     [l.shape for l in data_batch.label]))
            if data_batch.label else None)
        self.switch_bucket(key, data_shapes, label_shapes)
        self._curr_module.forward(data_batch, is_train=is_train)

    def backward(self, out_grads=None):
        self._curr_module.backward(out_grads)

    def update(self):
        self._curr_module.update()

    def get_outputs(self, merge_multi_context=True):
        return self._curr_module.get_outputs()

    def get_params(self):
        return self._buckets[self._default_bucket_key].get_params()

    def set_params(self, arg_params, aux_params, **kwargs):
        self._buckets[self._default_bucket_key].set_params(
            arg_params, aux_params, **kwargs)
        self.params_initialized = True

    def update_metric(self, eval_metric, labels, pre_sliced=False):
        self._curr_module.update_metric(eval_metric, labels)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._buckets[self._default_bucket_key].save_checkpoint(
            prefix, epoch, save_optimizer_states)
