"""Step-granular asynchronous checkpointing.

Reference posture (SURVEY §5.3): the reference's only recovery story is
epoch-granularity save_checkpoint callbacks; a dead worker stalls
dist_sync.  TPU-native upgrade: first-class step-granular checkpoints
written by a background thread (the training loop never blocks on disk),
atomic rename-into-place, rotation, and a manifest for resume — the
checkpoint/restart pattern pods use for preemption recovery.

Includes the RNG key (the reference's noted gap: "RNG state NOT
checkpointed") so a restored run continues the exact sample sequence.
"""
from __future__ import annotations

import json
import os
import queue
import shutil
import threading
from typing import Dict, Optional

import numpy as np

from .base import MXNetError

__all__ = ["AsyncCheckpointer", "load_checkpoint_state", "restore"]


def _snapshot_params(net_or_params) -> Dict[str, np.ndarray]:
    """Host-side copy keyed by STRUCTURAL names when a Block is given
    ('0.weight', 'body.1.bias' — scope-independent, so a fresh process
    whose global name counters differ can still restore; the same scheme
    save_parameters uses).  Device->host transfer happens here; DISK I/O
    is what the background thread takes off the critical path."""
    if hasattr(net_or_params, "_collect_params_with_prefix"):
        params = net_or_params._collect_params_with_prefix()
    else:
        params = net_or_params
    out = {}
    for name, p in params.items():
        out[name] = p.data().asnumpy().copy()
    return out


class AsyncCheckpointer:
    """Write training state every `save_every` steps without blocking.

    Usage::

        ckpt = AsyncCheckpointer(dir, save_every=100, keep=2)
        start = checkpoint.restore(dir, net, trainer)  # 0 if none yet
        for batch in loader:
            ...train...
            ckpt.step(net, trainer=trainer)
        ckpt.close()

    A new checkpointer on a non-empty directory continues the step
    numbering from the latest checkpoint (otherwise a resumed run's
    step-N dirs would collide with and rotate against stale pre-crash
    ones); pass initial_step to override.
    """

    def __init__(self, directory: str, save_every: int = 100, keep: int = 2,
                 initial_step: Optional[int] = None):
        if save_every < 1:
            raise MXNetError("save_every must be >= 1")
        self.dir = directory
        self.save_every = save_every
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        if initial_step is None:
            latest = os.path.join(directory, "latest")
            if os.path.exists(latest):
                with open(latest) as f:
                    initial_step = int(f.read().strip())
            else:
                initial_step = 0
        self._step = int(initial_step)
        # garbage-collect tmp dirs a crashed writer left behind
        for d in os.listdir(directory):
            if d.startswith(".tmp-"):
                shutil.rmtree(os.path.join(directory, d), ignore_errors=True)
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._error: Optional[BaseException] = None
        self._writer = threading.Thread(target=self._writer_loop, daemon=True)
        self._writer.start()

    # ------------------------------------------------------------------
    def step(self, params, trainer=None, extra: Optional[dict] = None) -> bool:
        """Count one training step; snapshot + enqueue a write when due.
        Returns True when a checkpoint was enqueued."""
        if self._error is not None:
            raise MXNetError(f"checkpoint writer failed: {self._error}")
        self._step += 1
        if self._step % self.save_every != 0:
            return False
        snap = {
            "step": self._step,
            "params": _snapshot_params(params),
            "trainer": None,
            "rng": self._rng_state(),
            "extra": extra or {},
        }
        if trainer is not None:
            snap["trainer"] = self._trainer_states(trainer)
        # block briefly if two writes are already in flight (bounded queue:
        # snapshot memory can't grow without limit if disk is slow)
        self._queue.put(snap)
        return True

    def wait(self) -> None:
        """Block until all enqueued checkpoints are on disk."""
        self._queue.join()
        if self._error is not None:
            raise MXNetError(f"checkpoint writer failed: {self._error}")

    def close(self) -> None:
        self.wait()
        self._queue.put(None)
        self._writer.join()

    # ------------------------------------------------------------------
    @staticmethod
    def _rng_state():
        from . import random as mx_random

        key = mx_random._state.key
        return None if key is None else np.asarray(key).tolist()

    @staticmethod
    def _trainer_states(trainer) -> bytes:
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if trainer._update_on_kvstore:
            updater = trainer._kvstore._updater
        else:
            updater = trainer._updaters[0]
        return updater.get_states(dump_optimizer=False)

    def _writer_loop(self):
        while True:
            snap = self._queue.get()
            if snap is None:
                self._queue.task_done()
                return
            try:
                self._write(snap)
            except BaseException as e:  # surfaced on the next step()/wait()
                self._error = e
            finally:
                self._queue.task_done()

    def _write(self, snap):
        from .ndarray import utils as nd_utils
        from . import ndarray as nd

        step = snap["step"]
        tmp = os.path.join(self.dir, f".tmp-{step}")
        final = os.path.join(self.dir, f"step-{step}")
        if os.path.exists(tmp):
            # leftover from a crashed writer: its stale contents must not
            # be published into this checkpoint
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        nd_utils.save(os.path.join(tmp, "params.nd"),
                      {k: nd.array(v, dtype=v.dtype)
                       for k, v in snap["params"].items()})
        if snap["trainer"] is not None:
            with open(os.path.join(tmp, "trainer.states"), "wb") as f:
                f.write(snap["trainer"])
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump({"step": step, "rng": snap["rng"],
                       "extra": snap["extra"]}, f)
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        with open(os.path.join(self.dir, ".latest.tmp"), "w") as f:
            f.write(str(step))
        os.replace(os.path.join(self.dir, ".latest.tmp"),
                   os.path.join(self.dir, "latest"))
        # rotate
        steps = sorted(
            int(d.split("-")[1]) for d in os.listdir(self.dir)
            if d.startswith("step-"))
        for old in steps[: -self.keep]:
            shutil.rmtree(os.path.join(self.dir, f"step-{old}"),
                          ignore_errors=True)


def load_checkpoint_state(directory: str):
    """Load the newest checkpoint: dict(step, params (name->NDArray),
    trainer (bytes or None), extra) — or None when none exists.  Restores
    the RNG key as a side effect (reference gap closed)."""
    latest = os.path.join(directory, "latest")
    if not os.path.exists(latest):
        return None
    with open(latest) as f:
        step = int(f.read().strip())
    d = os.path.join(directory, f"step-{step}")
    from .ndarray import utils as nd_utils

    params = nd_utils.load(os.path.join(d, "params.nd"))
    with open(os.path.join(d, "meta.json")) as f:
        meta = json.load(f)
    trainer_states = None
    tpath = os.path.join(d, "trainer.states")
    if os.path.exists(tpath):
        with open(tpath, "rb") as f:
            trainer_states = f.read()
    if meta.get("rng") is not None:
        import jax.numpy as jnp

        from . import random as mx_random

        mx_random._state.key = jnp.asarray(
            np.asarray(meta["rng"], np.uint32))
    return {"step": step, "params": params, "trainer": trainer_states,
            "extra": meta.get("extra", {})}


def restore(directory: str, net, trainer=None) -> int:
    """Apply the newest checkpoint to `net` (structural names) and
    `trainer`; restores the RNG key.  Returns the restored step (0 when
    no checkpoint exists) — the working end of the resume recipe."""
    state = load_checkpoint_state(directory)
    if state is None:
        return 0
    params = net._collect_params_with_prefix() if hasattr(
        net, "_collect_params_with_prefix") else dict(net)
    for name, p in params.items():
        if name not in state["params"]:
            raise MXNetError(f"checkpoint missing parameter {name}")
        p.set_data(state["params"][name].asnumpy())
    if trainer is not None and state["trainer"] is not None:
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        updaters = (trainer._updaters if not trainer._update_on_kvstore
                    else [trainer._kvstore._updater])
        for upd in updaters or []:
            upd.set_states(state["trainer"])
            upd.optimizer = trainer._optimizer
    return state["step"]
