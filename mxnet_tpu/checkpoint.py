"""Step-granular asynchronous checkpointing with verified restore.

Reference posture (SURVEY §5.3): the reference's only recovery story is
epoch-granularity save_checkpoint callbacks; a dead worker stalls
dist_sync.  TPU-native upgrade: first-class step-granular checkpoints
written by a background thread (the training loop never blocks on disk),
atomic rename-into-place, rotation, and a manifest for resume — the
checkpoint/restart pattern pods use for preemption recovery.

Integrity (docs/FAULT_TOLERANCE.md): every payload file's SHA-256 digest
is recorded in ``meta.json``; loads verify digests and fall back to the
next-newest *valid* ``step-*`` directory when the newest one is torn,
truncated, or missing — a preempted pod must never be unrecoverable
because it died mid-write.  ``mxnet_tpu.fault`` hooks are threaded through
the writer so every one of those failure shapes is reproducible on demand
(``MX_FAULT_SPEC``).

Includes the RNG key (the reference's noted gap: "RNG state NOT
checkpointed") so a restored run continues the exact sample sequence.

Shard-granular format (format 2, ``MX_CKPT_SHARDED`` or
``AsyncCheckpointer(sharded=True)``): every rank writes ONLY its
locally-addressable shards (``params-shard-R.nd`` / ``optstate-shard-R.nd``
plus an atomic ``shard-R.json`` digest marker), and ``meta.json`` carries a
rank-invariant shard manifest next to ``layout`` — ZERO collectives on the
save path, so scheduled saves never gang-lockstep an allgather and the
SIGTERM preemption path can snapshot cross-process-sharded state
rank-locally (docs/FAULT_TOLERANCE.md §Shard-granular checkpoints).
"""
from __future__ import annotations

import hashlib
import json
import logging
import os
import queue
import shutil
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from . import fault
from . import memwatch
from . import telemetry
from .base import MXNetError

__all__ = ["AsyncCheckpointer", "load_checkpoint_state", "restore",
           "latest_valid_step", "agree_resume_step"]

_LOG = logging.getLogger("mxnet_tpu.checkpoint")


def _env_sharded_default() -> bool:
    """``MX_CKPT_SHARDED`` (off unless truthy): the constructor default
    for shard-granular (format 2) checkpoints."""
    return os.environ.get("MX_CKPT_SHARDED", "").lower() not in (
        "", "0", "false", "off")


def _shard_wait_s() -> float:
    """How long the leader rank waits for peer shard commit markers
    before publishing a (possibly incomplete) step
    (``MX_CKPT_SHARD_WAIT_S``, seconds).  An incomplete publish is not a
    corruption: validation rejects it and restore falls back to the
    previous step."""
    try:
        return float(os.environ.get("MX_CKPT_SHARD_WAIT_S", "60"))
    except (TypeError, ValueError):
        return 60.0


def _is_step_target(obj) -> bool:
    """Duck-type check for a ``DataParallelStep``-like target: owns
    sharded state (``state_dict``/``load_state_dict``) plus a
    :meth:`layout` describing its placement."""
    return (hasattr(obj, "state_dict") and hasattr(obj, "load_state_dict")
            and hasattr(obj, "layout"))


def _snapshot_params(net_or_params) -> Dict[str, np.ndarray]:
    """Host-side copy keyed by STRUCTURAL names when a Block is given
    ('0.weight', 'body.1.bias' — scope-independent, so a fresh process
    whose global name counters differ can still restore; the same scheme
    save_parameters uses).  Device->host transfer happens here; DISK I/O
    is what the background thread takes off the critical path."""
    if hasattr(net_or_params, "_collect_params_with_prefix"):
        params = net_or_params._collect_params_with_prefix()
    else:
        params = net_or_params
    out = {}
    for name, p in params.items():
        out[name] = p.data().asnumpy().copy()
    return out


def _snapshot_target(target, allow_collective: bool = True):
    """(host params, host optimizer slots or None, layout or None) for a
    checkpoint target — a Gluon Block / params dict (legacy shape,
    layout-free: those snapshots are full replicated host arrays and are
    world-size independent by construction), or a ``DataParallelStep``,
    whose sharded state gathers through its own ``state_dict`` and whose
    save-time :meth:`layout` travels into ``meta.json`` so a restore on
    a different mesh knows it must reshard.  ``allow_collective=False``
    (the rank-local preemption path) makes a gather-requiring snapshot
    raise instead of hanging a one-rank collective."""
    if _is_step_target(target):
        state = target.state_dict(allow_collective=allow_collective)
        layout = target.layout()
        layout["optimizer"] = state.get("optimizer")
        return state["params"], state.get("opt_state"), layout
    return _snapshot_params(target), None, None


class AsyncCheckpointer:
    """Write training state every `save_every` steps without blocking.

    Usage::

        ckpt = AsyncCheckpointer(dir, save_every=100, keep=2)
        start = checkpoint.restore(dir, net, trainer)  # 0 if none yet
        for batch in loader:
            ...train...
            ckpt.step(net, trainer=trainer)
        ckpt.close()

    A new checkpointer on a non-empty directory continues the step
    numbering from the latest checkpoint (otherwise a resumed run's
    step-N dirs would collide with and rotate against stale pre-crash
    ones); pass initial_step to override.

    ``params`` may also be a :class:`~mxnet_tpu.parallel.DataParallelStep`:
    its sharded params AND optimizer state snapshot to host (optimizer
    slots land in ``opt_state.nd``) and its sharding layout (mesh shape,
    per-param PartitionSpecs, world size) is recorded in ``meta.json`` —
    the metadata ``restore()`` needs to reshard the state onto a
    different mesh after an elastic gang resize
    (docs/FAULT_TOLERANCE.md §Elastic resize).

    ``writer=False`` makes this rank a NON-WRITING member of a gang that
    shares ONE checkpoint directory (rank 0 writes, peers read): step
    counting, heartbeats, and the chaos-harness hooks still run, and a
    due snapshot is still TAKEN (a sharded ``state_dict``'s allgather
    must stay lockstep across the gang) but never persisted or pruned —
    without this, N ranks racing rename-into-place on shared storage
    would tear each other's publishes.

    ``sharded=True`` (default from ``MX_CKPT_SHARDED``) switches a
    DataParallelStep target to the shard-granular format: EVERY rank —
    ``writer=False`` included — persists the shards it owns
    (``writer=False`` narrows to "does not publish meta/latest or
    rotate"), with zero collectives on the save path.  The leader waits
    up to ``MX_CKPT_SHARD_WAIT_S`` for peer commit markers before
    publishing; a step missing a peer's shards simply fails validation
    and restore falls back.  Non-step targets (a Gluon Block) ignore the
    flag — their snapshots are host-replicated already.
    """

    def __init__(self, directory: str, save_every: int = 100, keep: int = 2,
                 initial_step: Optional[int] = None, writer: bool = True,
                 sharded: Optional[bool] = None):
        if save_every < 1:
            raise MXNetError("save_every must be >= 1")
        self.dir = directory
        self.save_every = save_every
        self.keep = keep
        self.writer = bool(writer)
        self.sharded = (_env_sharded_default() if sharded is None
                        else bool(sharded))
        os.makedirs(directory, exist_ok=True)
        if initial_step is None:
            # continue numbering from the newest step on disk; a torn
            # `latest` file must not reset numbering to 0 (collision +
            # rotation against the pre-crash dirs), so fall back to the
            # step-* dir names when it is unreadable
            candidates = _candidate_steps(directory)
            initial_step = candidates[0] if candidates else 0
        elif self.writer:
            # explicit resume step (gang-agreed): step dirs ABOVE it are
            # an abandoned timeline — e.g. the previous incarnation's
            # preemption checkpoint the gang agreed NOT to resume from.
            # Left in place they would poison rotation ("newest" by
            # number) and latest_valid_step would resurrect them after
            # the next crash, restoring state this run never reached.
            # (Non-writer ranks of a shared-dir gang never delete: the
            # one writer owns the timeline.)
            for s in _candidate_steps(directory):
                if s > initial_step:
                    shutil.rmtree(os.path.join(directory, f"step-{s}"),
                                  ignore_errors=True)
            latest = os.path.join(directory, "latest")
            try:
                with open(latest) as f:
                    if int(f.read().strip()) > initial_step:
                        os.remove(latest)
            except (OSError, ValueError):
                pass
        self._step = int(initial_step)
        if self.writer:
            # garbage-collect staging leftovers a crashed writer left behind
            for d in os.listdir(directory):
                if d.startswith(".tmp-"):
                    shutil.rmtree(os.path.join(directory, d),
                                  ignore_errors=True)
                elif d.startswith(".latest.tmp"):
                    try:
                        os.remove(os.path.join(directory, d))
                    except OSError:
                        pass
        self._queue: "queue.Queue" = queue.Queue(maxsize=2)
        self._error: Optional[BaseException] = None
        self._closed = False
        # live-array census: queued host snapshots are the "checkpoint"
        # category (host bytes — the params were copied off device)
        memwatch.register("checkpoint", self, _queued_snapshot_arrays)
        self._writer = None
        if self.writer or self.sharded:
            # sharded mode: every rank persists its own shard files, so
            # writer=False peers run the background thread too
            self._writer = threading.Thread(target=self._writer_loop,
                                            daemon=True)
            self._writer.start()

    # ------------------------------------------------------------------
    def step(self, params, trainer=None, extra: Optional[dict] = None) -> bool:
        """Count one training step; snapshot + enqueue a write when due.
        Returns True when a checkpoint was enqueued."""
        if self._error is not None:
            raise MXNetError(f"checkpoint writer failed: {self._error}")
        self._step += 1
        # chaos harness: `crash:step=N` dies HERE, before step N's
        # checkpoint can be enqueued — deterministic for tests
        fault.on_train_step(self._step)
        # the supervisor's liveness signal: rate-limited, atomic-renamed,
        # no-op without MX_TELEMETRY_DIR
        telemetry.heartbeat(self._step)
        # memory watchdog: a step boundary on the host, safely outside
        # any dispatch body (samples every MX_MEMWATCH_EVERY calls)
        memwatch.on_step(self._step)
        if self._step % self.save_every != 0:
            return False
        if self.sharded and hasattr(params, "shard_state_dict"):
            # shard-granular: EVERY rank (writer or not) snapshots and
            # persists exactly the shards it owns — no collective, no
            # full-state D2H sweep on any rank
            self._queue.put(self._sharded_snap(params, trainer, extra))
            return True
        if not self.writer:
            # non-writer rank of a shared-dir gang: participate in the
            # snapshot ONLY when it runs a lockstep collective (a
            # cross-process-sharded state_dict's allgather must match on
            # every rank) — the common replicated/addressable case skips
            # the full D2H sweep this rank would only discard
            needs = getattr(params, "snapshot_requires_collective", None)
            if needs is not None and needs():
                _snapshot_target(params)
            return False
        host_params, opt, layout = _snapshot_target(params)
        snap = {
            "step": self._step,
            "params": host_params,
            "opt": opt,
            "layout": layout,
            "trainer": None,
            "rng": self._rng_state(),
            "extra": extra or {},
        }
        if trainer is not None:
            snap["trainer"] = self._trainer_states(trainer)
        # block briefly if two writes are already in flight (bounded queue:
        # snapshot memory can't grow without limit if disk is slow)
        self._queue.put(snap)
        return True

    def wait(self) -> None:
        """Block until all enqueued checkpoints are on disk."""
        if self._writer is None:
            return  # non-writer rank: nothing can be in flight
        self._queue.join()
        if self._error is not None:
            raise MXNetError(f"checkpoint writer failed: {self._error}")

    def close(self) -> None:
        """Flush pending writes and stop the writer thread.

        The thread is ALWAYS sent its sentinel and joined, even when a
        pending write failed — only then is the writer error re-raised
        (previously an error in wait() leaked the thread forever)."""
        if self._closed:
            if self._error is not None:
                raise MXNetError(f"checkpoint writer failed: {self._error}")
            return
        self._closed = True
        try:
            self.wait()
        finally:
            if self._writer is not None:
                self._queue.put(None)
                self._writer.join()

    def save_now(self, params, trainer=None, extra: Optional[dict] = None,
                 drain_timeout: float = 5.0) -> int:
        """Synchronously checkpoint the CURRENT step on the calling thread
        (the preemption path: fault.install_preemption_handler calls this
        from the SIGTERM handler, then exits).  Returns the step written,
        0 when no step has been taken yet.

        Runs inside a signal handler, so it must not touch the queue's
        (non-reentrant) lock — SIGTERM can land while the main thread is
        inside put()/join() holding it.  In-flight async writes are
        drained by a bounded lock-free poll of unfinished_tasks instead;
        on timeout we write anyway: staging dirs are thread-unique, a
        same-step double publish is two snapshots of identical logical
        state, and validation tolerates a racy `latest`.

        Shard-granular mode (``sharded=True``, or AUTOMATICALLY whenever
        the target's state is cross-process-sharded): the snapshot writes
        rank-local shard files with zero collectives, so this path no
        longer raises on TP/SP-sharded state — and non-writer ranks
        persist their own shards too (whole-gang preemption completes the
        checkpoint; a rank-local SIGTERM leaves an incomplete step that
        validation rejects and restore falls back past).  Gathered mode
        keeps the old contract: non-writer ranks return 0 without
        snapshotting (SIGTERM is rank-local, a collective gather here
        could never be assumed lockstep)."""
        if self._step == 0:
            return 0
        needs = getattr(params, "snapshot_requires_collective", None)
        use_sharded = hasattr(params, "shard_state_dict") and (
            self.sharded or (needs is not None and needs()))
        if not self.writer and not use_sharded:
            return 0
        if use_sharded:
            snap = self._sharded_snap(params, trainer, extra)
            # a SIGTERM handler cannot sit out the full peer-marker wait
            # (the supervisor's kill window is short); an incomplete
            # publish is rejected by validation, never mis-restored
            snap["wait_s"] = 2.0
        else:
            host_params, opt, layout = _snapshot_target(
                params, allow_collective=False)
            snap = {
                "step": self._step,
                "params": host_params,
                "opt": opt,
                "layout": layout,
                "trainer": (self._trainer_states(trainer)
                            if trainer is not None else None),
                "rng": self._rng_state(),
                "extra": extra or {},
            }
        deadline = time.monotonic() + drain_timeout
        while self._queue.unfinished_tasks and time.monotonic() < deadline:
            time.sleep(0.01)
        if self._queue.unfinished_tasks and self._step % self.save_every == 0:
            # the writer thread is still persisting THIS very step; racing
            # it on the same final dir publishes nothing new (identical
            # logical state) and could only corrupt — let it finish
            return 0
        self._write(snap)
        return self._step

    # ------------------------------------------------------------------
    def _sharded_snap(self, params, trainer, extra) -> dict:
        """Rank-local shard snapshot dict for the writer queue: the
        target's ``shard_state_dict`` (zero collectives) plus the
        save-time layout; trainer states ride with the leader only."""
        state = params.shard_state_dict()
        layout = params.layout()
        layout["optimizer"] = state.get("optimizer")
        return {
            "step": self._step,
            "sharded": state,
            "layout": layout,
            "trainer": (self._trainer_states(trainer)
                        if trainer is not None and self.writer else None),
            "rng": self._rng_state(),
            "extra": extra or {},
        }

    @staticmethod
    def _rng_state():
        from . import random as mx_random

        key = mx_random._state.key
        return None if key is None else np.asarray(key).tolist()

    @staticmethod
    def _trainer_states(trainer) -> bytes:
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        if trainer._update_on_kvstore:
            updater = trainer._kvstore._updater
        else:
            updater = trainer._updaters[0]
        return updater.get_states(dump_optimizer=False)

    def _writer_loop(self):
        while True:
            snap = self._queue.get()
            if snap is None:
                self._queue.task_done()
                return
            try:
                self._write(snap)
            except BaseException as e:  # surfaced on the next step()/wait()
                self._error = e
            finally:
                self._queue.task_done()

    def _write(self, snap):
        # span runs on the writer thread (its own trace track): checkpoint
        # wall never hides inside the training thread's step spans
        with telemetry.span("checkpoint_save", paired=True,
                            step=snap["step"]):
            self._write_impl(snap)
        # sample while the snapshot buffers are still resident — the
        # checkpoint category's high-water moment
        memwatch.on_checkpoint("save", snap["step"])

    def _write_impl(self, snap):
        if "sharded" in snap:
            return self._write_sharded_impl(snap)
        from .ndarray import utils as nd_utils
        from . import ndarray as nd

        step = snap["step"]
        t0 = time.perf_counter()
        fault.on_write_begin(step)
        # thread-unique staging dir: save_now (signal handler, main
        # thread) may race the writer thread on the SAME step when the
        # drain timed out — two writers must never share a tmp dir
        tmp = os.path.join(self.dir,
                           f".tmp-{step}-{threading.get_ident()}")
        final = os.path.join(self.dir, f"step-{step}")
        if os.path.exists(tmp):
            # leftover from a crashed writer: its stale contents must not
            # be published into this checkpoint
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        digests = {}
        nd_utils.save(os.path.join(tmp, "params.nd"),
                      {k: nd.array(v, dtype=v.dtype)
                       for k, v in snap["params"].items()})
        digests["params.nd"] = _sha256_file(os.path.join(tmp, "params.nd"))
        if snap.get("opt") is not None:
            # optimizer slots of a DataParallelStep target (momenta /
            # Adam moments), host-gathered like the params
            nd_utils.save(os.path.join(tmp, "opt_state.nd"),
                          {k: nd.array(v, dtype=v.dtype)
                           for k, v in snap["opt"].items()})
            digests["opt_state.nd"] = _sha256_file(
                os.path.join(tmp, "opt_state.nd"))
        if snap["trainer"] is not None:
            with open(os.path.join(tmp, "trainer.states"), "wb") as f:
                f.write(snap["trainer"])
            digests["trainer.states"] = _sha256_file(
                os.path.join(tmp, "trainer.states"))
        fault.on_write_mid(step)
        # meta.json is written LAST and carries the payload digests: a
        # parseable meta whose digests verify is the definition of a
        # valid checkpoint (load_checkpoint_state).  `layout` is the
        # save-time sharding layout (mesh shape, per-param
        # PartitionSpecs, world size) — what restore() compares against
        # the restoring mesh to decide whether to reshard (elastic gang
        # resize, docs/FAULT_TOLERANCE.md §Elastic resize).
        meta = {"step": step, "rng": snap["rng"],
                "extra": snap["extra"], "digests": digests}
        if snap.get("layout") is not None:
            meta["layout"] = snap["layout"]
            meta["world_size"] = snap["layout"].get("world_size")
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        self._publish(step, tmp, final)
        if telemetry.enabled():
            try:
                nbytes = sum(os.path.getsize(os.path.join(final, f))
                             for f in os.listdir(final))
            except OSError:
                nbytes = 0
            telemetry.record_checkpoint(
                "save", step=step, wall_s=time.perf_counter() - t0,
                nbytes=nbytes)
        fault.on_write_published(step, final)

    def _publish(self, step, tmp, final):
        """Atomically publish a complete staging dir and rotate."""
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)  # atomic publish
        # thread-unique staging for `latest` too: save_now (main thread)
        # and the writer thread may publish different steps concurrently
        latest_tmp = os.path.join(
            self.dir, f".latest.tmp-{threading.get_ident()}")
        with open(latest_tmp, "w") as f:
            f.write(str(step))
        os.replace(latest_tmp, os.path.join(self.dir, "latest"))
        # rotate.  Off-cycle steps (save_now preemption checkpoints) must
        # never evict a scheduled save_every multiple: the gang's agreed
        # resume step is always a scheduled one, and deleting it on one
        # rank would make restore(step=agreed) raise on the next restart —
        # an unrecoverable job.  An off-cycle step is itself retained only
        # until the next scheduled checkpoint supersedes it.
        steps = sorted(
            int(d.split("-")[1]) for d in os.listdir(self.dir)
            if d.startswith("step-"))
        scheduled = [s for s in steps if s % self.save_every == 0]
        extra = [s for s in steps if s % self.save_every != 0]
        drop = scheduled[: -self.keep]
        drop += extra[:-1]
        if extra and scheduled and extra[-1] < scheduled[-1]:
            drop.append(extra[-1])  # superseded by a newer scheduled step
        for old in drop:
            shutil.rmtree(os.path.join(self.dir, f"step-{old}"),
                          ignore_errors=True)

    def _write_sharded_impl(self, snap):
        """Format-2 write: this rank persists ONLY the shards it owns
        into a gang-shared fixed-name staging dir, committing them with
        an atomic per-rank ``shard-R.json`` digest marker.  The leader
        (``writer=True``) additionally waits for peer markers, writes
        ``meta.json`` (format, manifest, layout, rng) and publishes.
        No collective anywhere: cross-rank coordination is filesystem
        polling against a bounded deadline, and a timeout publishes an
        incomplete step that validation simply rejects."""
        from .ndarray import utils as nd_utils
        from . import ndarray as nd

        step = snap["step"]
        state = snap["sharded"]
        rank = int(state["rank"])
        t0 = time.perf_counter()
        fault.on_write_begin(step)
        # FIXED-name staging dir shared by the whole gang (unlike the
        # gathered path's thread-unique tmp): every rank must agree on
        # where step N stages.  Per-file writes stay private until the
        # rank's marker commits them.
        tmp = os.path.join(self.dir, f".tmp-{step}-shard")
        final = os.path.join(self.dir, f"step-{step}")
        os.makedirs(tmp, exist_ok=True)
        digests = {}

        def dump(fname, section):
            arrs = {}
            for sname, payloads in section.items():
                for j, a in payloads:
                    arrs[f"{sname}#{j}"] = nd.array(a, dtype=a.dtype)
            if not arrs:
                return
            path = os.path.join(tmp, fname)
            nd_utils.save(path, arrs)
            digests[fname] = _sha256_file(path)

        dump(f"params-shard-{rank}.nd", state["params"])
        dump(f"optstate-shard-{rank}.nd", state["opt_state"])
        nbytes_local = sum(
            os.path.getsize(os.path.join(tmp, f)) for f in digests)
        # the rank's commit marker, written LAST and atomically: its
        # presence means "rank R's shard files are complete", and its
        # digests are what load-time validation verifies
        marker = {"rank": rank, "step": step, "digests": digests}
        mpath = os.path.join(tmp, f"shard-{rank}.json")
        mtmp = f"{mpath}.tmp-{threading.get_ident()}"
        with open(mtmp, "w") as f:
            json.dump(marker, f)
        os.replace(mtmp, mpath)
        if not self.writer:
            # peer rank: shards committed, the leader publishes.  nbytes
            # is LOCAL shard bytes — the zero-collective scaling signal
            # (per-rank save cost tracks per-rank shard bytes, not
            # global param bytes)
            if telemetry.enabled():
                telemetry.record_checkpoint(
                    "save", step=step, wall_s=time.perf_counter() - t0,
                    nbytes=nbytes_local, sharded=True, rank=rank)
            return
        meta_digests = {}
        if snap["trainer"] is not None:
            with open(os.path.join(tmp, "trainer.states"), "wb") as f:
                f.write(snap["trainer"])
            meta_digests["trainer.states"] = _sha256_file(
                os.path.join(tmp, "trainer.states"))
        fault.on_write_mid(step)
        manifest = state["manifest"]
        peers = _manifest_ranks(manifest) - {rank}
        deadline = time.monotonic() + snap.get("wait_s", _shard_wait_s())
        missing = []
        while True:
            missing = [r for r in sorted(peers) if not os.path.exists(
                os.path.join(tmp, f"shard-{r}.json"))]
            if not missing or time.monotonic() >= deadline:
                break
            time.sleep(0.02)
        if missing:
            _LOG.warning(
                "sharded checkpoint step %d: no commit marker from "
                "rank(s) %s within the wait window — publishing anyway "
                "(the step will fail validation and restore falls back)",
                step, missing)
        meta = {"step": step, "format": 2, "rng": snap["rng"],
                "extra": snap["extra"], "digests": meta_digests,
                "manifest": manifest,
                "layout": snap["layout"],
                "world_size": (snap["layout"] or {}).get("world_size")}
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        self._publish(step, tmp, final)
        if telemetry.enabled():
            try:
                total = sum(os.path.getsize(os.path.join(final, f))
                            for f in os.listdir(final))
            except OSError:
                total = 0
            telemetry.record_checkpoint(
                "save", step=step, wall_s=time.perf_counter() - t0,
                nbytes=nbytes_local, sharded=True, rank=rank,
                total_nbytes=total)
        fault.on_write_published(step, final)


def _queued_snapshot_arrays(ckpt):
    """memwatch provider: host param copies waiting on the writer queue
    (numpy arrays — counted as the checkpoint category's host bytes)."""
    out = []
    try:
        items = list(ckpt._queue.queue)
    except Exception:
        return out
    for snap in items:
        if isinstance(snap, dict):
            out.extend(snap.get("params", {}).values())
            sharded = snap.get("sharded")
            if sharded:
                for payloads in sharded.get("params", {}).values():
                    out.extend(a for _, a in payloads)
                for payloads in sharded.get("opt_state", {}).values():
                    out.extend(a for _, a in payloads)
    return out


def _manifest_ranks(manifest: dict) -> set:
    """Every rank the manifest says owns at least one shard — the set
    whose shard files + commit markers a valid format-2 step must hold."""
    ranks = set()
    for section in ("params", "opt_state"):
        for ent in (manifest.get(section) or {}).values():
            for sh in ent.get("shards", []):
                ranks.add(int(sh["rank"]))
    return ranks


class _ShardReader:
    """Per-rank shard-file cache over one format-2 checkpoint dir: loads
    ``params-shard-R.nd`` / ``optstate-shard-R.nd`` at most once each,
    and only when some :class:`_LazyShardedArray` actually reads a slice
    a shard of that rank covers."""

    _PREFIX = {"params": "params-shard", "opt_state": "optstate-shard"}

    def __init__(self, directory: str, meta: dict):
        self.dir = directory
        self.manifest = meta.get("manifest") or {}
        self._files: Dict[tuple, dict] = {}

    def rank_file(self, section: str, rank: int) -> dict:
        key = (section, rank)
        if key not in self._files:
            from .ndarray import utils as nd_utils

            self._files[key] = nd_utils.load(os.path.join(
                self.dir, f"{self._PREFIX[section]}-{rank}.nd"))
        return self._files[key]

    def section(self, section: str) -> dict:
        return {name: _LazyShardedArray(self, section, name, ent)
                for name, ent in (self.manifest.get(section) or {}).items()}


class _LazyShardedArray:
    """One logical array of a shard-granular checkpoint, readable by
    GLOBAL slice without ever composing the full value: ``read_slice``
    copies only the manifest shards that intersect the request — what
    ``_lazy_put`` feeds ``jax.make_array_from_callback`` so an N->M
    elastic restore moves per-device shard bytes, not whole arrays.
    ``asnumpy()``/``__array__`` compose the full array for the legacy
    (host-gathered) consumers — small single-host cases only."""

    def __init__(self, reader: _ShardReader, section: str, name: str,
                 ent: dict):
        self._reader = reader
        self._section = section
        self.name = name
        self.shape = tuple(int(s) for s in ent["shape"])
        self.dtype = np.dtype(ent["dtype"])
        self._shards = ent["shards"]

    def read_slice(self, idx) -> np.ndarray:
        want = []
        for dim, s in enumerate(idx):
            start = 0 if s.start is None else int(s.start)
            stop = (self.shape[dim] if s.stop is None else int(s.stop))
            want.append((start, stop))
        out = np.empty(tuple(b - a for a, b in want), self.dtype)
        for sh in self._shards:
            src = [tuple(int(x) for x in p) for p in sh["slice"]]
            inter = [(max(a, c), min(b, d))
                     for (a, b), (c, d) in zip(want, src)]
            if any(a >= b for a, b in inter):
                continue
            data = self._reader.rank_file(self._section, int(sh["rank"]))[
                f"{self.name}#{int(sh['j'])}"].asnumpy()
            dst = tuple(slice(a - w, b - w)
                        for (a, b), (w, _) in zip(inter, want))
            sel = tuple(slice(a - s0, b - s0)
                        for (a, b), (s0, _) in zip(inter, src))
            out[dst] = data[sel]
        return out

    def asnumpy(self) -> np.ndarray:
        return self.read_slice(tuple(slice(0, s) for s in self.shape))

    def __array__(self, dtype=None):
        a = self.asnumpy()
        return a.astype(dtype) if dtype is not None else a


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _candidate_steps(directory: str) -> List[int]:
    """Step numbers worth trying, newest first: the `latest` pointer (when
    readable) plus every step-* dir — so a torn/missing `latest` never
    hides an intact checkpoint."""
    steps = set()
    try:
        with open(os.path.join(directory, "latest")) as f:
            steps.add(int(f.read().strip()))
    except (OSError, ValueError):
        pass
    try:
        names = os.listdir(directory)
    except OSError:
        names = []
    for dname in names:
        if dname.startswith("step-"):
            try:
                steps.add(int(dname.split("-", 1)[1]))
            except ValueError:
                pass
    return sorted(steps, reverse=True)


def _read_meta_if_valid(d: str):
    """Parsed meta.json iff the checkpoint dir is complete and every
    recorded digest verifies; None for any torn/corrupt/missing shape."""
    try:
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(meta, dict) or "step" not in meta:
        return None
    if int(meta.get("format", 1)) >= 2:
        return meta if _shard_files_valid(d, meta) else None
    digests = meta.get("digests")
    if digests is None:
        # pre-digest checkpoint (older layout): existence check only
        return meta if os.path.exists(os.path.join(d, "params.nd")) else None
    for fname, want in digests.items():
        try:
            if _sha256_file(os.path.join(d, fname)) != want:
                return None
        except OSError:
            return None
    return meta


def _shard_files_valid(d: str, meta: dict) -> bool:
    """Format-2 validity: every meta-level digest (trainer.states)
    verifies, every shard-owning rank's commit marker parses, the
    marker's digests verify, and each rank that the manifest assigns
    shards to actually committed the corresponding shard file.  A torn
    write, a missing peer (leader published on wait timeout), or a
    corrupted single shard all fail HERE — so restore's existing
    next-newest-step fallback covers them."""
    for fname, want in (meta.get("digests") or {}).items():
        try:
            if _sha256_file(os.path.join(d, fname)) != want:
                return False
        except OSError:
            return False
    manifest = meta.get("manifest") or {}
    for r in sorted(_manifest_ranks(manifest)):
        try:
            with open(os.path.join(d, f"shard-{r}.json")) as f:
                marker = json.load(f)
        except (OSError, ValueError):
            return False
        digests = marker.get("digests") or {}
        for section, prefix in (("params", "params-shard"),
                                ("opt_state", "optstate-shard")):
            owns = any(
                any(int(sh["rank"]) == r for sh in ent.get("shards", []))
                for ent in (manifest.get(section) or {}).values())
            if owns and f"{prefix}-{r}.nd" not in digests:
                return False
        for fname, want in digests.items():
            try:
                if _sha256_file(os.path.join(d, fname)) != want:
                    return False
            except OSError:
                return False
    return True


def latest_valid_step(directory: str,
                      multiple_of: Optional[int] = None) -> int:
    """Newest step whose checkpoint verifies (digests + parseable meta);
    0 when the directory holds no valid checkpoint.

    With ``multiple_of=save_every`` only SCHEDULED steps are considered —
    the inventory every rank of a gang is guaranteed to share.  Gang
    resume (agree_resume_step) must run on this: off-cycle preemption
    checkpoints land at rank-specific steps (wherever SIGTERM caught each
    rank), so an off-cycle step can never be a common resume point."""
    for s in _candidate_steps(directory):
        if multiple_of and s % multiple_of != 0:
            continue
        if _read_meta_if_valid(os.path.join(directory, f"step-{s}")) is not None:
            return s
    return 0


def agree_resume_step(local_step: int, kv=None) -> int:
    """Gang-consistent resume step: the MINIMUM over all ranks' local
    steps.  After a supervised restart (tools/launch.py --max-restarts)
    ranks hold checkpoints at different steps — a preemption-handler
    checkpoint lands wherever SIGTERM caught that rank — but sync-SGD
    requires every rank to resume from the SAME step.

    Callers MUST pass ``latest_valid_step(dir, multiple_of=save_every)``
    (scheduled steps only): under whole-gang preemption EVERY rank writes
    an off-cycle final checkpoint at a slightly different step, and the
    minimum of those exists on one rank only — restore(step=min) would
    raise everywhere else.  Every rank holds the scheduled minimum with
    keep >= 2: lock-step training bounds the cross-rank skew to one save
    interval, and rotation never lets an off-cycle preemption checkpoint
    evict a scheduled one."""
    if kv is None or getattr(kv, "num_workers", 1) <= 1:
        return int(local_step)
    from . import ndarray as nd

    vec = np.zeros(kv.num_workers, np.float32)
    vec[kv.rank] = float(local_step)
    summed = kv._global_sum(nd.array(vec)).asnumpy()
    return int(round(summed.min()))


def load_checkpoint_state(directory: str, step: Optional[int] = None):
    """Load the newest VALID checkpoint: dict(step, params (name->NDArray),
    opt_state (name->NDArray or None), trainer (bytes or None), extra,
    layout (the save-time sharding layout, or None for Block-style
    checkpoints)) — or None when no valid one exists.
    Restores the RNG key as a side effect (reference gap closed).

    Integrity: a candidate whose meta.json is torn, whose digests
    mismatch, or whose payload fails to decode is skipped (with a warning)
    in favor of the next-newest step — a crash mid-write must never make
    the job unrecoverable.  With ``step=N`` the exact step is demanded and
    an invalid/missing step-N raises (gang-consistent resume must not
    silently diverge)."""
    with telemetry.span("checkpoint_load", paired=True):
        state = _load_checkpoint_state(directory, step)
    memwatch.on_checkpoint("load", state["step"] if state else 0)
    return state


def _load_checkpoint_state(directory: str, step: Optional[int] = None):
    from .ndarray import utils as nd_utils

    explicit = step is not None
    t0 = time.perf_counter()
    candidates = [int(step)] if explicit else _candidate_steps(directory)
    for s in candidates:
        d = os.path.join(directory, f"step-{s}")
        meta = _read_meta_if_valid(d)
        if meta is None:
            if explicit:
                raise MXNetError(
                    f"checkpoint step {s} in {directory} is missing or "
                    "corrupt (demanded via step=)")
            _LOG.warning("checkpoint %s is torn/corrupt; falling back to "
                         "the next-newest step", d)
            telemetry.record_checkpoint("fallback", step=s,
                                        reason="digest-or-meta")
            continue
        if int(meta.get("format", 1)) >= 2:
            # shard-granular checkpoint: hand back LAZY per-array views
            # over the shard files — consumers that can place per-shard
            # (DataParallelStep.load_state_dict) never compose a full
            # array on this host; legacy consumers call .asnumpy()
            reader = _ShardReader(d, meta)
            params = reader.section("params")
            opt_state = reader.section("opt_state") or None
            trainer_states = None
            tpath = os.path.join(d, "trainer.states")
            if os.path.exists(tpath):
                with open(tpath, "rb") as f:
                    trainer_states = f.read()
            if meta.get("rng") is not None:
                import jax.numpy as jnp

                from . import random as mx_random

                mx_random._state.key = jnp.asarray(
                    np.asarray(meta["rng"], np.uint32))
            telemetry.record_checkpoint(
                "load", step=s, wall_s=time.perf_counter() - t0,
                sharded=True)
            return {"step": s, "params": params, "opt_state": opt_state,
                    "trainer": trainer_states,
                    "extra": meta.get("extra", {}),
                    "layout": meta.get("layout")}
        try:
            params = nd_utils.load(os.path.join(d, "params.nd"))
        except Exception as e:  # undecodable payload (pre-digest torn file)
            if explicit:
                raise MXNetError(
                    f"checkpoint step {s} in {directory} failed to load: "
                    f"{e}") from e
            _LOG.warning("checkpoint %s failed to load (%s); falling back",
                         d, e)
            telemetry.record_checkpoint("fallback", step=s,
                                        reason="payload-decode")
            continue
        opt_state = None
        opath = os.path.join(d, "opt_state.nd")
        if os.path.exists(opath):
            try:
                opt_state = nd_utils.load(opath)
            except Exception as e:  # same fallback contract as params.nd
                if explicit:
                    raise MXNetError(
                        f"checkpoint step {s} in {directory} failed to "
                        f"load optimizer state: {e}") from e
                _LOG.warning("checkpoint %s optimizer state failed to load "
                             "(%s); falling back", d, e)
                telemetry.record_checkpoint("fallback", step=s,
                                            reason="payload-decode")
                continue
        trainer_states = None
        tpath = os.path.join(d, "trainer.states")
        if os.path.exists(tpath):
            with open(tpath, "rb") as f:
                trainer_states = f.read()
        if meta.get("rng") is not None:
            import jax.numpy as jnp

            from . import random as mx_random

            mx_random._state.key = jnp.asarray(
                np.asarray(meta["rng"], np.uint32))
        telemetry.record_checkpoint("load", step=s,
                                    wall_s=time.perf_counter() - t0)
        return {"step": s, "params": params, "opt_state": opt_state,
                "trainer": trainer_states, "extra": meta.get("extra", {}),
                "layout": meta.get("layout")}
    return None


def restore(directory: str, net, trainer=None,
            step: Optional[int] = None) -> int:
    """Apply the newest valid checkpoint (or exactly ``step=N``) to `net`
    (structural names) and `trainer`; restores the RNG key.  Returns the
    restored step (0 when no valid checkpoint exists) — the working end of
    the resume recipe.

    ``net`` may also be a :class:`~mxnet_tpu.parallel.DataParallelStep`:
    its params AND optimizer slots restore onto the step's CURRENT mesh,
    **resharding** when the checkpoint's recorded layout (mesh shape,
    per-param PartitionSpecs, device assignment, world size) differs from
    the restoring one — the elastic N->M resume path, shrink and grow
    alike (docs/FAULT_TOLERANCE.md §Elastic resize).  Each rank
    materializes only the shards it now owns."""
    state = load_checkpoint_state(directory, step=step)
    if state is None:
        return 0
    if _is_step_target(net):
        # lazy shard views pass through untouched: load_state_dict
        # places them per-shard (never composing the full array);
        # eager NDArrays from gathered checkpoints read to host here
        host = {"params": {k: (v if hasattr(v, "read_slice")
                               else v.asnumpy())
                           for k, v in state["params"].items()},
                "opt_state": {k: (v if hasattr(v, "read_slice")
                                  else v.asnumpy())
                              for k, v in (state["opt_state"] or {}).items()}}
        net.load_state_dict(host, saved_layout=state.get("layout"))
        return state["step"]
    params = net._collect_params_with_prefix() if hasattr(
        net, "_collect_params_with_prefix") else dict(net)
    for name, p in params.items():
        if name not in state["params"]:
            raise MXNetError(f"checkpoint missing parameter {name}")
        p.set_data(state["params"][name].asnumpy())
    if trainer is not None and state["trainer"] is not None:
        if not trainer._kv_initialized:
            trainer._init_kvstore()
        updaters = (trainer._updaters if not trainer._update_on_kvstore
                    else [trainer._kvstore._updater])
        for upd in updaters or []:
            upd.set_states(state["trainer"])
            upd.optimizer = trainer._optimizer
    return state["step"]
