"""Image API (reference: python/mxnet/image/image.py ~L1-1500 — imdecode,
imresize, augmenters, ImageIter; backed by src/operator/image/ ops) and the
detection pipeline (python/mxnet/image/detection.py — ImageDetIter)."""
from .image import (imdecode, imencode, imread, imresize, resize_short,
                    fixed_crop, center_crop, random_crop, random_size_crop,
                    color_normalize,
                    CreateAugmenter, Augmenter, ResizeAug, ForceResizeAug,
                    RandomCropAug, CenterCropAug, RandomSizedCropAug,
                    RandomOrderAug, HorizontalFlipAug,
                    CastAug, BrightnessJitterAug, ContrastJitterAug,
                    SaturationJitterAug, HueJitterAug, ColorJitterAug,
                    LightingAug, RandomGrayAug, ColorNormalizeAug, ImageIter)
from .detection import (DetAugmenter, DetBorrowAug, DetRandomSelectAug,
                        DetHorizontalFlipAug, DetRandomCropAug,
                        DetRandomPadAug, CreateDetAugmenter, ImageDetIter)
