"""Detection image iterator with bbox-aware augmentation.

Reference parity: python/mxnet/image/detection.py (~L1-900): Det* augmenter
family (DetBorrowAug, DetRandomSelectAug, DetHorizontalFlipAug,
DetRandomCropAug with IoU/coverage constraints, DetRandomPadAug),
CreateDetAugmenter, and ImageDetIter — the input path of the SSD-512 /
Faster-RCNN configs (BASELINE config 5).

Label convention (the reference's packed det format): a flat label vector
  [header_width A, object_width B, (A-2 extra header values), obj0, obj1...]
where each object is [id, xmin, ymin, xmax, ymax, ...] with coordinates
normalized to [0, 1].  ImageDetIter.next() emits labels shaped
(batch, max_objects, object_width) padded with -1.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional

import numpy as np

from ..base import MXNetError
from . import image as _img

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """Base detection augmenter: __call__(src, label) -> (src, label)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        return json.dumps([type(self).__name__, self._kwargs],
                          default=lambda o: o.tolist()
                          if isinstance(o, np.ndarray) else str(o))

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a pixel-only Augmenter (labels pass through unchanged)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """Randomly apply one of `aug_list` (or skip) — reference ~L120."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob or not self.aug_list:
            return src, label
        return pyrandom.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Flip image AND boxes (xmin' = 1-xmax, xmax' = 1-xmin)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            src = src[:, ::-1]
            label = label.copy()
            tmp = 1.0 - label[:, 3]
            label[:, 3] = 1.0 - label[:, 1]
            label[:, 1] = tmp
        return src, label


def _box_coverage(crop, boxes):
    """Fraction of each box's area covered by `crop` [x0,y0,x1,y1]
    (the reference's object-coverage criterion — NOT IoU: a crop fully
    containing a small box must count as coverage 1.0)."""
    ix = np.maximum(
        0, np.minimum(crop[2], boxes[:, 2]) - np.maximum(crop[0], boxes[:, 0]))
    iy = np.maximum(
        0, np.minimum(crop[3], boxes[:, 3]) - np.maximum(crop[1], boxes[:, 1]))
    inter = ix * iy
    area_b = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
    return np.where(area_b > 0, inter / np.maximum(area_b, 1e-12), 0)


class DetRandomCropAug(DetAugmenter):
    """SSD-style constrained random crop (reference ~L200): sample crops
    until one achieves the min IoU with some ground-truth box; objects
    whose centers fall outside the crop are dropped, the rest re-clipped
    and re-normalized."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75, 1.33),
                 area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__(min_object_covered=min_object_covered,
                         area_range=area_range)
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _sample_crop(self):
        area = pyrandom.uniform(*self.area_range)
        ratio = pyrandom.uniform(*self.aspect_ratio_range)
        w = min(np.sqrt(area * ratio), 1.0)
        h = min(np.sqrt(area / ratio), 1.0)
        x0 = pyrandom.uniform(0, 1 - w)
        y0 = pyrandom.uniform(0, 1 - h)
        return np.array([x0, y0, x0 + w, y0 + h], np.float32)

    def __call__(self, src, label):
        if label.shape[0] == 0:
            return src, label
        boxes = label[:, 1:5]
        for _ in range(self.max_attempts):
            crop = self._sample_crop()
            coverage = _box_coverage(crop, boxes)
            if coverage.max() < self.min_object_covered:
                continue
            # keep objects whose center lies inside the crop AND that keep
            # at least min_eject_coverage of their area (reference eject
            # rule for heavily clipped boxes)
            cx = (boxes[:, 0] + boxes[:, 2]) / 2
            cy = (boxes[:, 1] + boxes[:, 3]) / 2
            keep = ((cx >= crop[0]) & (cx <= crop[2])
                    & (cy >= crop[1]) & (cy <= crop[3])
                    & (coverage >= self.min_eject_coverage))
            if not keep.any():
                continue
            new_label = label[keep].copy()
            w, h = crop[2] - crop[0], crop[3] - crop[1]
            new_label[:, 1] = np.clip((new_label[:, 1] - crop[0]) / w, 0, 1)
            new_label[:, 3] = np.clip((new_label[:, 3] - crop[0]) / w, 0, 1)
            new_label[:, 2] = np.clip((new_label[:, 2] - crop[1]) / h, 0, 1)
            new_label[:, 4] = np.clip((new_label[:, 4] - crop[1]) / h, 0, 1)
            ih, iw = src.shape[:2]
            x0, y0 = int(crop[0] * iw), int(crop[1] * ih)
            x1, y1 = max(int(crop[2] * iw), x0 + 1), max(int(crop[3] * ih),
                                                         y0 + 1)
            return src[y0:y1, x0:x1], new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Zoom-out: place the image on a larger canvas (reference ~L300)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33), area_range=(1.0, 3.0),
                 max_attempts=50, pad_val=(127, 127, 127)):
        super().__init__(area_range=area_range)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        area = pyrandom.uniform(*self.area_range)
        if area <= 1.0:
            return src, label
        h, w = src.shape[:2]
        scale = np.sqrt(area)
        new_h, new_w = int(h * scale), int(w * scale)
        y0 = pyrandom.randint(0, new_h - h)
        x0 = pyrandom.randint(0, new_w - w)
        canvas = np.empty((new_h, new_w, src.shape[2]), src.dtype)
        canvas[...] = np.asarray(self.pad_val, src.dtype)
        canvas[y0:y0 + h, x0:x0 + w] = src
        label = label.copy()
        label[:, 1] = (label[:, 1] * w + x0) / new_w
        label[:, 3] = (label[:, 3] * w + x0) / new_w
        label[:, 2] = (label[:, 2] * h + y0) / new_h
        label[:, 4] = (label[:, 4] * h + y0) / new_h
        return canvas, label


class _DetForceResize(DetAugmenter):
    def __init__(self, size, interp=1):
        super().__init__(size=size)
        self.size = size  # (w, h)
        self.interp = interp

    def __call__(self, src, label):
        return _img.imresize(src, self.size[0], self.size[1],
                             self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=1, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmentation list (reference ~L700)."""
    auglist: List[DetAugmenter] = []
    if resize > 0:
        auglist.append(DetBorrowAug(_img.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(area_range[1], 1.0)),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(area_range[0], 1.0), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(_DetForceResize((data_shape[2], data_shape[1]),
                                   inter_method))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(
            _img.ColorJitterAug(brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(_img.HueJitterAug(hue)))
    if pca_noise > 0:
        auglist.append(DetBorrowAug(_img.LightingAug(
            pca_noise, eigval=np.array([55.46, 4.794, 1.148]),
            eigvec=np.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]]))))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(_img.RandomGrayAug(rand_gray)))
    if mean is not None or std is not None:
        if mean is True or mean is None:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True or std is None:
            std = np.array([58.395, 57.12, 57.375])
        auglist.append(DetBorrowAug(_img.ColorNormalizeAug(mean, std)))
    auglist.append(DetBorrowAug(_img.CastAug()))
    return auglist


def _parse_det_label(flat: np.ndarray):
    """Unpack the flat packed det label -> (objects, object_width)."""
    flat = np.asarray(flat, np.float32).ravel()
    if flat.size < 2:
        return np.zeros((0, 5), np.float32), 5
    header = int(flat[0])
    obj_w = int(flat[1])
    if header < 2 or obj_w < 5 or flat.size <= header:
        # unpacked form: flat list of 5-wide objects
        obj_w = 5
        n = flat.size // 5
        return flat[: n * 5].reshape(n, 5).copy(), 5
    body = flat[header:]
    n = body.size // obj_w
    objs = body[: n * obj_w].reshape(n, obj_w).copy()
    return objs[objs[:, 0] >= 0], obj_w


def pack_det_label(objects, header_width=2):
    """(N, W) objects -> flat packed label [A, B, objects...]."""
    objects = np.asarray(objects, np.float32)
    obj_w = objects.shape[1] if objects.ndim == 2 else 5
    return np.concatenate([
        np.array([header_width, obj_w], np.float32), objects.ravel()])


class ImageDetIter(_img.ImageIter):
    """Detection iterator (reference: image/detection.py ImageDetIter).

    Yields DataBatch with data (B, C, H, W) and label
    (B, max_objects, object_width) padded with -1.
    """

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", shuffle=False,
                 aug_list=None, imglist=None, **kwargs):
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, shuffle=shuffle,
                         aug_list=[], imglist=imglist, **kwargs)
        self.auglist = (aug_list if aug_list is not None
                        else CreateDetAugmenter(data_shape))
        # scan a few records to size the label pad
        self._max_objects, self._obj_width = self._estimate_label_shape()
        from ..io import DataDesc

        self.provide_label = [DataDesc(
            "label", (batch_size, self._max_objects, self._obj_width))]

    def _estimate_label_shape(self):
        max_obj, obj_w = 1, 5
        for i in range(min(len(self._items), 100)):
            _img_arr, flat = self._read_raw(i)
            objs, w = _parse_det_label(flat)
            max_obj = max(max_obj, objs.shape[0])
            obj_w = max(obj_w, w)
        return max_obj, obj_w

    def _read_raw(self, i):
        from .. import recordio

        if self._records is not None:
            raw = self._records.read_idx(self._items[i])
            header, buf = recordio.unpack(raw)
            img = _img.imdecode(buf, to_ndarray=False)
            flat = np.atleast_1d(np.asarray(header.label, np.float32))
        else:
            flat, path = self._items[i]
            img = _img.imread(path, to_ndarray=False)
        return img, flat

    def next(self):
        from .. import ndarray as nd
        from ..io import DataBatch

        if self._cursor >= len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        batch = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.full((self.batch_size, self._max_objects,
                          self._obj_width), -1.0, np.float32)
        pad = 0
        for slot in range(self.batch_size):
            if self._cursor >= len(self._order):
                pad += 1
                continue
            img, flat = self._read_raw(self._order[self._cursor])
            self._cursor += 1
            objs, _ = _parse_det_label(flat)
            for aug in self.auglist:
                img, objs = aug(img, objs)
                from ..ndarray import NDArray

                if isinstance(img, NDArray):
                    img = img.asnumpy()
            if img.shape[:2] != (h, w):
                img = _img.imresize(img, w, h)
                if hasattr(img, "asnumpy"):
                    img = img.asnumpy()
            batch[slot] = np.transpose(np.asarray(img, np.float32), (2, 0, 1))
            n = min(objs.shape[0], self._max_objects)
            if n:
                labels[slot, :n, :objs.shape[1]] = objs[:n]
        return DataBatch(data=[nd.array(batch)], label=[nd.array(labels)],
                         pad=pad, provide_data=self.provide_data,
                         provide_label=self.provide_label)

    def draw_next(self, *a, **k):
        raise MXNetError("draw_next requires display support; use next()")

    def reshape(self, data_shape=None, label_shape=None):
        from ..io import DataDesc

        if data_shape is not None:
            self.data_shape = tuple(data_shape)
            self.provide_data = [DataDesc(
                "data", (self.batch_size,) + self.data_shape)]
        if label_shape is not None:
            self._max_objects, self._obj_width = label_shape
            self.provide_label = [DataDesc(
                "label", (self.batch_size,) + tuple(label_shape))]
