"""Image decode/augment utilities (reference: python/mxnet/image/image.py).

Host-side decode/augment uses OpenCV (the reference links OpenCV in C++);
the resulting batches are device_put as NDArrays.  The throughput-critical
RecordIO path lives in mxnet_tpu.io.ImageRecordIter.
"""
from __future__ import annotations

import os
import random as pyrandom
from typing import List, Optional

import numpy as np

from ..base import MXNetError

__all__ = ["imdecode", "imencode", "imread", "imresize", "resize_short",
           "fixed_crop", "center_crop", "random_crop", "color_normalize",
           "CreateAugmenter", "Augmenter", "ResizeAug", "ForceResizeAug",
           "RandomCropAug", "CenterCropAug", "HorizontalFlipAug", "CastAug",
           "BrightnessJitterAug", "ContrastJitterAug", "SaturationJitterAug",
           "HueJitterAug", "ColorJitterAug", "LightingAug", "RandomGrayAug",
           "ColorNormalizeAug", "ImageIter"]


def _cv2():
    import cv2

    return cv2


def _wrap(arr, to_ndarray=True):
    if not to_ndarray:
        return arr
    from .. import ndarray as nd

    return nd.array(arr, dtype=arr.dtype)


def imdecode(buf, flag=1, to_rgb=True, to_ndarray=True):
    """Decode an encoded image buffer to HWC uint8 (reference: mx.image.imdecode)."""
    cv2 = _cv2()
    arr = np.frombuffer(buf, dtype=np.uint8)
    img = cv2.imdecode(arr, cv2.IMREAD_COLOR if flag else
                       cv2.IMREAD_GRAYSCALE)
    if img is None:
        raise MXNetError("imdecode failed: invalid image data")
    if flag and to_rgb:
        img = img[:, :, ::-1]
    if not flag:
        img = img[:, :, None]
    return _wrap(np.ascontiguousarray(img), to_ndarray)


def imencode(img, fmt=".jpg", quality=95):
    cv2 = _cv2()
    from ..ndarray import NDArray

    if isinstance(img, NDArray):
        img = img.asnumpy()
    bgr = img[:, :, ::-1] if img.shape[-1] == 3 else img
    params = [int(cv2.IMWRITE_JPEG_QUALITY), quality] if fmt in (".jpg", ".jpeg") else []
    ok, enc = cv2.imencode(fmt, bgr, params)
    if not ok:
        raise MXNetError("imencode failed")
    return enc.tobytes()


def imread(filename, flag=1, to_rgb=True, to_ndarray=True):
    with open(filename, "rb") as f:
        return imdecode(f.read(), flag, to_rgb, to_ndarray)


def imresize(src, w, h, interp=1):
    cv2 = _cv2()
    from ..ndarray import NDArray

    arr = src.asnumpy() if isinstance(src, NDArray) else src
    out = cv2.resize(arr, (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return _wrap(out, isinstance(src, NDArray))


def resize_short(src, size, interp=2):
    from ..ndarray import NDArray

    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    if h > w:
        new_w, new_h = size, int(size * h / w)
    else:
        new_w, new_h = int(size * w / h), size
    return imresize(src, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0: y0 + h, x0: x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp)
    return out


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = size
    x0 = max((w - new_w) // 2, 0)
    y0 = max((h - new_h) // 2, 0)
    return fixed_crop(src, x0, y0, min(new_w, w), min(new_h, h), size,
                      interp), (x0, y0, new_w, new_h)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = min(size[0], w), min(size[1], h)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
        (x0, y0, new_w, new_h)


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop with size/aspect jitter then resize (reference:
    image.py random_size_crop — the Inception/ResNet train crop)."""
    import math

    h, w = src.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
        new_ratio = math.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(math.sqrt(target_area * new_ratio)))
        new_h = int(round(math.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            return fixed_crop(src, x0, y0, new_w, new_h, size, interp), \
                (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json

        # mean/std kwargs are ndarrays (reference: image.py Augmenter.dumps
        # converts them via tolist())
        return json.dumps([type(self).__name__, self._kwargs],
                          default=lambda o: o.tolist()
                          if isinstance(o, np.ndarray) else str(o))

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    """Random size + aspect crop (reference RandomSizedCropAug)."""

    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class RandomOrderAug(Augmenter):
    """Apply child augmenters in random order (reference RandomOrderAug)."""

    def __init__(self, ts):
        super().__init__()
        self.ts = list(ts)

    def dumps(self):
        """Embed child dumps (reference overrides dumps the same way)."""
        import json

        return json.dumps(["RandomOrderAug",
                           [json.loads(t.dumps()) for t in self.ts]])

    def __call__(self, src):
        order = list(self.ts)
        pyrandom.shuffle(order)
        for t in order:
            src = t(src)
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            return src[:, ::-1]
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        from ..ndarray import NDArray

        if isinstance(src, NDArray):
            return src.astype(self.typ)
        return src.astype(self.typ)


def _as_float(src):
    return np.asarray(src, np.float32)


class BrightnessJitterAug(Augmenter):
    """src *= 1 + U(-b, b) (reference: image.py BrightnessJitterAug)."""

    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return _as_float(src) * alpha


class ContrastJitterAug(Augmenter):
    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast
        self._coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __call__(self, src):
        src = _as_float(src)
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray.mean() * (1 - alpha)


class SaturationJitterAug(Augmenter):
    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation
        self._coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __call__(self, src):
        src = _as_float(src)
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        gray = (src * self._coef).sum(axis=2, keepdims=True)
        return src * alpha + gray * (1 - alpha)


class HueJitterAug(Augmenter):
    """Rotate RGB about the gray axis by U(-hue, hue)*180deg (reference:
    image.py HueJitterAug yiq-rotation formulation)."""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self._tyiq = np.array([[0.299, 0.587, 0.114],
                               [0.596, -0.274, -0.321],
                               [0.211, -0.523, 0.311]], np.float32)
        self._ityiq = np.array([[1.0, 0.956, 0.621],
                                [1.0, -0.272, -0.647],
                                [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        src = _as_float(src)
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u, w = np.cos(alpha * np.pi), np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = self._ityiq @ bt @ self._tyiq
        return src @ t.T


class ColorJitterAug(Augmenter):
    def __init__(self, brightness, contrast, saturation):
        super().__init__(brightness=brightness, contrast=contrast,
                         saturation=saturation)
        self._augs = [a for a in (
            BrightnessJitterAug(brightness) if brightness else None,
            ContrastJitterAug(contrast) if contrast else None,
            SaturationJitterAug(saturation) if saturation else None) if a]

    def __call__(self, src):
        augs = list(self._augs)
        pyrandom.shuffle(augs)
        for a in augs:
            src = a(src)
        return src


class LightingAug(Augmenter):
    """PCA lighting noise (reference: image.py LightingAug / AlexNet)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,)).astype(np.float32)
        rgb = (self.eigvec * alpha * self.eigval).sum(axis=1)
        return _as_float(src) + rgb.reshape(1, 1, 3)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self._coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __call__(self, src):
        if pyrandom.random() < self.p:
            gray = (_as_float(src) * self._coef).sum(axis=2, keepdims=True)
            return np.broadcast_to(gray, src.shape).copy()
        return src


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32) if std is not None else None

    def __call__(self, src):
        return color_normalize(_as_float(src), self.mean, self.std)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmentation list (reference ~L800)."""
    auglist: List[Augmenter] = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        # Inception-style random area+aspect crop (implies rand_crop)
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3 / 4.0, 4 / 3.0), inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        auglist.append(LightingAug(
            pca_noise,
            eigval=np.array([55.46, 4.794, 1.148]),
            eigvec=np.array([[-0.5675, 0.7192, 0.4009],
                             [-0.5808, -0.0045, -0.8140],
                             [-0.5836, -0.6948, 0.4203]])))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is not None or std is not None:
        if mean is True or mean is None:
            mean = np.array([123.68, 116.28, 103.53])
        if std is True or std is None:
            std = np.array([58.395, 57.12, 57.375])
        if mean is not False:
            auglist.append(ColorNormalizeAug(mean, std))
    auglist.append(CastAug())
    return auglist


class ImageIter:
    """Python image iterator over .rec or .lst files (reference ~L1000)."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 shuffle=False, aug_list=None, imglist=None, **kwargs):
        from ..io import DataDesc

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self._shuffle = shuffle
        self.auglist = aug_list if aug_list is not None else []
        self._records = None
        self._items = []
        if path_imgrec:
            from .. import recordio

            idx = os.path.splitext(path_imgrec)[0] + ".idx"
            self._records = recordio.MXIndexedRecordIO(idx, path_imgrec, "r")
            self._items = list(self._records.keys)
        elif path_imglist or imglist is not None:
            if path_imglist:
                with open(path_imglist) as fin:
                    for line in fin:
                        parts = line.strip().split("\t")
                        label = np.array(parts[1:-1], dtype=np.float32)
                        self._items.append((label, os.path.join(path_root,
                                                                parts[-1])))
            else:
                for entry in imglist:
                    self._items.append((np.asarray(entry[:-1], np.float32),
                                        os.path.join(path_root, entry[-1])))
        else:
            raise MXNetError("need path_imgrec, path_imglist, or imglist")
        self.provide_data = [DataDesc("data",
                                      (batch_size,) + self.data_shape)]
        self.provide_label = [DataDesc("softmax_label",
                                       (batch_size, label_width))]
        self.reset()

    def reset(self):
        self._order = list(range(len(self._items)))
        if self._shuffle:
            pyrandom.shuffle(self._order)
        self._cursor = 0

    def _read_one(self, i):
        from .. import recordio

        if self._records is not None:
            raw = self._records.read_idx(self._items[i])
            header, buf = recordio.unpack(raw)
            img = imdecode(buf, to_ndarray=False)
            label = np.atleast_1d(np.asarray(header.label, np.float32))
        else:
            label, path = self._items[i]
            img = imread(path, to_ndarray=False)
            label = np.atleast_1d(label)
        for aug in self.auglist:
            img = aug(img)
            from ..ndarray import NDArray

            if isinstance(img, NDArray):
                img = img.asnumpy()
        return img, label

    def next(self):
        from .. import ndarray as nd
        from ..io import DataBatch

        if self._cursor >= len(self._order):
            raise StopIteration
        c, h, w = self.data_shape
        batch = np.zeros((self.batch_size, c, h, w), np.float32)
        labels = np.zeros((self.batch_size, self.label_width), np.float32)
        pad = 0
        for slot in range(self.batch_size):
            if self._cursor >= len(self._order):
                pad += 1
                continue
            img, label = self._read_one(self._order[self._cursor])
            self._cursor += 1
            if img.shape[:2] != (h, w):
                img = imresize(img, w, h)
            batch[slot] = np.transpose(img.astype(np.float32), (2, 0, 1))
            labels[slot, :len(label)] = label[: self.label_width]
        return DataBatch(data=[nd.array(batch)], label=[nd.array(labels)],
                         pad=pad)

    def __next__(self):
        return self.next()

    def __iter__(self):
        return self
