"""Legacy ``mx.rnn`` API: symbolic RNN cells, bucketing iterator, RNN
checkpoints (reference: python/mxnet/rnn/ — rnn_cell.py, io.py, rnn.py).

The cells compose registered ops through the shared op registry, so they
work with both ``mx.sym`` and ``mx.nd`` spellings, and an unrolled graph
compiles to a single XLA program through the symbolic executor — the
TPU-native replacement for the reference's per-timestep engine pushes.
"""
from .rnn_cell import (BaseRNNCell, RNNParams, RNNCell, LSTMCell, GRUCell,
                       FusedRNNCell, SequentialRNNCell, BidirectionalCell,
                       ModifierCell, DropoutCell, ZoneoutCell, ResidualCell)
from .io import BucketSentenceIter, encode_sentences
from .rnn import (save_rnn_checkpoint, load_rnn_checkpoint,
                  do_rnn_checkpoint)

__all__ = ["BaseRNNCell", "RNNParams", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "ModifierCell", "DropoutCell", "ZoneoutCell", "ResidualCell",
           "BucketSentenceIter", "encode_sentences", "save_rnn_checkpoint",
           "load_rnn_checkpoint", "do_rnn_checkpoint"]
