"""Bucketing sentence iterator (reference: python/mxnet/rnn/io.py ~L1-220).

Buckets pad variable-length sentences to a small set of fixed lengths so
every bucket compiles ONCE on TPU (static shapes per bucket — exactly the
role bucketing plays for the reference's per-length cached graphs).
"""
from __future__ import annotations

import random

import numpy as np

from ..base import MXNetError
from ..io.io import DataBatch, DataDesc, DataIter

__all__ = ["BucketSentenceIter", "encode_sentences"]


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0, unknown_token=None):
    """Encode tokenized sentences into integer ids, building the vocab
    on the fly (reference io.py encode_sentences)."""
    idx = start_label
    if vocab is None:
        vocab = {invalid_key: invalid_label}
        new_vocab = True
    else:
        new_vocab = False
    res = []
    for sent in sentences:
        coded = []
        for word in sent:
            if word not in vocab:
                if not new_vocab:
                    if unknown_token:
                        word = unknown_token
                    else:
                        raise MXNetError(f"unknown token {word}")
                else:
                    if idx == invalid_label:
                        idx += 1
                    vocab[word] = idx
                    idx += 1
            coded.append(vocab[word])
        res.append(coded)
    return res, vocab


class BucketSentenceIter(DataIter):
    """Pads each encoded sentence into the smallest bucket that fits and
    yields fixed-shape batches with per-batch bucket_key — feeds
    BucketingModule (reference io.py BucketSentenceIter ~L60)."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label", dtype="float32",
                 layout="NT"):
        super().__init__(batch_size=batch_size)
        if not buckets:
            buckets = [i for i, j in enumerate(
                np.bincount([len(s) for s in sentences]))
                if j >= batch_size]
        buckets.sort()
        ndiscard = 0
        self.data = [[] for _ in buckets]
        for sent in sentences:
            buck = np.searchsorted(buckets, len(sent))
            if buck == len(buckets):
                ndiscard += 1
                continue
            buff = np.full((buckets[buck],), invalid_label, dtype=dtype)
            buff[:len(sent)] = sent
            self.data[buck].append(buff)
        # keep empty buckets 2-D so reset()'s label shift is well-formed
        self.data = [np.asarray(i, dtype=dtype).reshape(-1, blen)
                     for i, blen in zip(self.data, buckets)]
        if ndiscard:
            import logging

            logging.getLogger("mxnet_tpu").warning(
                "discarded %d sentences longer than the largest bucket",
                ndiscard)

        self.batch_size = batch_size
        self.buckets = buckets
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.nddata = []
        self.ndlabel = []
        self.major_axis = layout.find("N")
        self.layout = layout
        self.default_bucket_key = max(buckets)

        if self.major_axis == 0:
            self.provide_data = [DataDesc(
                name=self.data_name,
                shape=(batch_size, self.default_bucket_key))]
            self.provide_label = [DataDesc(
                name=self.label_name,
                shape=(batch_size, self.default_bucket_key))]
        elif self.major_axis == 1:
            self.provide_data = [DataDesc(
                name=self.data_name,
                shape=(self.default_bucket_key, batch_size))]
            self.provide_label = [DataDesc(
                name=self.label_name,
                shape=(self.default_bucket_key, batch_size))]
        else:
            raise MXNetError(f"invalid layout {layout}: must contain N")

        self.idx = []
        for i, buck in enumerate(self.data):
            self.idx.extend([(i, j) for j in range(
                0, len(buck) - batch_size + 1, batch_size)])
        self.curr_idx = 0
        self.reset()

    def reset(self):
        from .. import ndarray as nd

        self.curr_idx = 0
        random.shuffle(self.idx)
        for buck in self.data:
            np.random.shuffle(buck)

        self.nddata = []
        self.ndlabel = []
        for buck in self.data:
            label = np.empty_like(buck)
            label[:, :-1] = buck[:, 1:]
            label[:, -1] = self.invalid_label
            self.nddata.append(nd.array(buck, dtype=self.dtype))
            self.ndlabel.append(nd.array(label, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        i, j = self.idx[self.curr_idx]
        self.curr_idx += 1

        if self.major_axis == 1:
            data = self.nddata[i][j:j + self.batch_size].T
            label = self.ndlabel[i][j:j + self.batch_size].T
        else:
            data = self.nddata[i][j:j + self.batch_size]
            label = self.ndlabel[i][j:j + self.batch_size]

        return DataBatch(
            [data], [label], pad=0, bucket_key=self.buckets[i],
            provide_data=[DataDesc(name=self.data_name, shape=data.shape)],
            provide_label=[DataDesc(name=self.label_name,
                                    shape=label.shape)])
