"""Symbolic RNN cells (reference: python/mxnet/rnn/rnn_cell.py ~L1-1500).

Each cell is a small factory of registered ops; ``unroll`` builds the
whole sequence graph eagerly in python — under the symbolic executor the
unrolled graph is ONE jit (XLA rolls the repeated cell body back up), and
``FusedRNNCell`` maps onto the lax.scan-based ``RNN`` op directly, the
TPU analog of the reference's cuDNN/MIOpen fused path.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError


def _np_prod(shape):
    return int(_np.prod(shape)) if shape else 1

__all__ = ["RNNParams", "BaseRNNCell", "RNNCell", "LSTMCell", "GRUCell",
           "FusedRNNCell", "SequentialRNNCell", "BidirectionalCell",
           "ModifierCell", "DropoutCell", "ZoneoutCell", "ResidualCell"]


class RNNParams(object):
    """Container for hold-and-share cell parameters (reference ~L40)."""

    def __init__(self, prefix=""):
        self._prefix = prefix
        self._params = {}

    def get(self, name, **kwargs):
        from .. import symbol

        name = self._prefix + name
        if name not in self._params:
            self._params[name] = symbol.Variable(name, **kwargs)
        return self._params[name]


class BaseRNNCell(object):
    """Abstract RNN cell (reference BaseRNNCell ~L80)."""

    def __init__(self, prefix="", params=None):
        if params is None:
            params = RNNParams(prefix)
            self._own_params = True
        else:
            self._own_params = False
        self._prefix = prefix
        self._params = params
        self._modified = False
        self.reset()

    def reset(self):
        self._init_counter = -1
        self._counter = -1

    def __call__(self, inputs, states):
        raise NotImplementedError

    @property
    def params(self):
        self._own_params = False
        return self._params

    @property
    def state_info(self):
        raise NotImplementedError

    @property
    def state_shape(self):
        return [ele["shape"] for ele in self.state_info]

    @property
    def _gate_names(self):
        return ()

    def begin_state(self, func=None, **kwargs):
        """Initial states.  Default: free Variables named
        ``{prefix}begin_state_{i}`` (bind them, or let ``unroll`` derive
        zero states from the inputs — the common path)."""
        assert not self._modified, \
            "After applying modifier cells the base cell cannot be called"
        from .. import symbol

        states = []
        for info in self.state_info:
            self._init_counter += 1
            if func is None:
                state = symbol.Variable(
                    f"{self._prefix}begin_state_{self._init_counter}")
            else:
                state = func(
                    name=f"{self._prefix}begin_state_{self._init_counter}",
                    **{k: v for k, v in dict(info, **kwargs).items()
                       if k not in ("__layout__",)})
            states.append(state)
        return states

    def _zeros_states(self, first_input, batch_axis=0):
        """Zero initial states derived from an input symbol's batch dim
        (TPU-native replacement for the reference's shape-0 zeros)."""
        F = _infer_ns(first_input)
        states = []
        for info in self.state_info:
            num_hidden = info["shape"][-1]
            states.append(F._begin_state_zeros(first_input,
                                               num_hidden=num_hidden,
                                               batch_axis=batch_axis))
        return states

    def unpack_weights(self, args):
        """Unpack fused packed weights into per-gate arrays
        (reference ~L200)."""
        args = dict(args)
        if not self._gate_names:
            return args
        h = self._num_hidden
        for group_name in ("i2h", "h2h"):
            weight = args.pop(f"{self._prefix}{group_name}_weight")
            bias = args.pop(f"{self._prefix}{group_name}_bias")
            for j, gate in enumerate(self._gate_names):
                wname = f"{self._prefix}{group_name}{gate}_weight"
                args[wname] = weight[j * h:(j + 1) * h].copy()
                bname = f"{self._prefix}{group_name}{gate}_bias"
                args[bname] = bias[j * h:(j + 1) * h].copy()
        return args

    def pack_weights(self, args):
        """Inverse of unpack_weights (reference ~L230)."""
        from .. import ndarray as nd

        args = dict(args)
        if not self._gate_names:
            return args
        for group_name in ("i2h", "h2h"):
            weight = []
            bias = []
            for gate in self._gate_names:
                weight.append(args.pop(f"{self._prefix}{group_name}{gate}_weight"))
                bias.append(args.pop(f"{self._prefix}{group_name}{gate}_bias"))
            args[f"{self._prefix}{group_name}_weight"] = nd.Concat(
                *weight, dim=0)
            args[f"{self._prefix}{group_name}_bias"] = nd.Concat(
                *bias, dim=0)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        """Unroll the cell over `length` steps (reference ~L260)."""
        self.reset()
        inputs, axis, F = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._zeros_states(inputs[0])
        states = begin_state
        outputs = []
        for i in range(length):
            output, states = self(inputs[i], states)
            outputs.append(output)
        if merge_outputs is True:
            outputs, _, _ = _normalize_sequence(length, outputs, layout,
                                                True)
        return outputs, states

    def _get_activation(self, inputs, activation, **kwargs):
        F = _infer_ns(inputs)
        if isinstance(activation, str):
            return F.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)


def _infer_ns(x):
    """mx.sym or mx.nd, depending on the value flowing through the cell."""
    from .. import ndarray as nd
    from .. import symbol as sym
    from ..symbol.symbol import Symbol

    return sym if isinstance(x, Symbol) else nd


def _normalize_sequence(length, inputs, layout, merge, in_layout=None):
    """list <-> merged-tensor conversion for unroll IO (reference ~L700)."""
    assert layout in ("NTC", "TNC"), f"invalid layout {layout}"
    axis = layout.find("T")
    if isinstance(inputs, (list, tuple)):
        F = _infer_ns(inputs[0])
        assert len(inputs) == length
        if merge is True:
            seq = [F.expand_dims(i, axis=axis) for i in inputs]
            return F.Concat(*seq, dim=axis), axis, F
        return list(inputs), axis, F
    F = _infer_ns(inputs)
    in_axis = in_layout.find("T") if in_layout else axis
    if merge is False:
        outs = F.SliceChannel(inputs, num_outputs=length, axis=in_axis,
                              squeeze_axis=True)
        # nd returns a list; sym returns a multi-output Symbol
        outs = list(outs) if length > 1 else [outs]
        return outs, axis, F
    # merge True, or None (no preference): keep the merged tensor
    if in_axis != axis:
        inputs = F.SwapAxis(inputs, dim1=0, dim2=1)
    return inputs, axis, F


class RNNCell(BaseRNNCell):
    """Vanilla RNN cell (reference ~L450)."""

    def __init__(self, num_hidden, activation="tanh", prefix="rnn_",
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._activation = activation
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    def __call__(self, inputs, states):
        self._counter += 1
        F = _infer_ns(inputs)
        name = f"{self._prefix}t{self._counter}_"
        i2h = F.FullyConnected(inputs, self._iW, self._iB,
                               num_hidden=self._num_hidden,
                               name=f"{name}i2h")
        h2h = F.FullyConnected(states[0], self._hW, self._hB,
                               num_hidden=self._num_hidden,
                               name=f"{name}h2h")
        output = self._get_activation(i2h + h2h, self._activation,
                                      name=f"{name}out")
        return output, [output]


class LSTMCell(BaseRNNCell):
    """LSTM cell, cuDNN gate order [i, f, g, o] (reference ~L500)."""

    def __init__(self, num_hidden, prefix="lstm_", params=None,
                 forget_bias=1.0):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        from ..initializer import LSTMBias

        self._iW = self.params.get("i2h_weight")
        # forget-gate bias starts at forget_bias (Module.init_params honors
        # the Variable's init attr; reference LSTMCell does the same)
        self._iB = self.params.get(
            "i2h_bias", init=LSTMBias(forget_bias=forget_bias))
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")
        self._forget_bias = forget_bias

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"},
                {"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_i", "_f", "_c", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        F = _infer_ns(inputs)
        name = f"{self._prefix}t{self._counter}_"
        i2h = F.FullyConnected(inputs, self._iW, self._iB,
                               num_hidden=self._num_hidden * 4,
                               name=f"{name}i2h")
        h2h = F.FullyConnected(states[0], self._hW, self._hB,
                               num_hidden=self._num_hidden * 4,
                               name=f"{name}h2h")
        gates = i2h + h2h
        sliced = F.SliceChannel(gates, num_outputs=4, axis=-1,
                                name=f"{name}slice")
        in_gate = F.Activation(sliced[0], act_type="sigmoid")
        forget_gate = F.Activation(sliced[1], act_type="sigmoid")
        in_transform = F.Activation(sliced[2], act_type="tanh")
        out_gate = F.Activation(sliced[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(BaseRNNCell):
    """GRU cell, gate order [r, z, n] (reference ~L600)."""

    def __init__(self, num_hidden, prefix="gru_", params=None):
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._iW = self.params.get("i2h_weight")
        self._iB = self.params.get("i2h_bias")
        self._hW = self.params.get("h2h_weight")
        self._hB = self.params.get("h2h_bias")

    @property
    def state_info(self):
        return [{"shape": (0, self._num_hidden), "__layout__": "NC"}]

    @property
    def _gate_names(self):
        return ("_r", "_z", "_o")

    def __call__(self, inputs, states):
        self._counter += 1
        F = _infer_ns(inputs)
        name = f"{self._prefix}t{self._counter}_"
        prev_h = states[0]
        i2h = F.FullyConnected(inputs, self._iW, self._iB,
                               num_hidden=self._num_hidden * 3,
                               name=f"{name}i2h")
        h2h = F.FullyConnected(prev_h, self._hW, self._hB,
                               num_hidden=self._num_hidden * 3,
                               name=f"{name}h2h")
        i2h_r, i2h_z, i2h_n = F.SliceChannel(i2h, num_outputs=3, axis=-1)
        h2h_r, h2h_z, h2h_n = F.SliceChannel(h2h, num_outputs=3, axis=-1)
        reset = F.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F.Activation(i2h_n + reset * h2h_n, act_type="tanh")
        next_h = (1.0 - update) * next_h_tmp + update * prev_h
        return next_h, [next_h]


class FusedRNNCell(BaseRNNCell):
    """Fused multi-layer RNN backed by the scan-based ``RNN`` op
    (reference FusedRNNCell ~L700: the cuDNN path)."""

    def __init__(self, num_hidden, num_layers=1, mode="lstm",
                 bidirectional=False, dropout=0.0, get_next_state=False,
                 forget_bias=1.0, prefix=None, params=None):
        if prefix is None:
            prefix = f"{mode}_"
        super().__init__(prefix=prefix, params=params)
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._dropout = dropout
        self._get_next_state = get_next_state
        self._parameters = self.params.get("parameters")

    @property
    def state_info(self):
        b = self._num_layers * (2 if self._bidirectional else 1)
        n = 2 if self._mode == "lstm" else 1
        return [{"shape": (b, 0, self._num_hidden), "__layout__": "LNC"}
                for _ in range(n)]

    @property
    def _gate_names(self):
        return {"rnn_relu": ("",), "rnn_tanh": ("",),
                "lstm": ("_i", "_f", "_c", "_o"),
                "gru": ("_r", "_z", "_o")}[self._mode]

    @property
    def _num_gates(self):
        return len(self._gate_names)

    def _zeros_states(self, first_input, batch_axis=0):
        """batch_axis: 0 when given a per-step (B, C) slice (stacked
        inside SequentialRNNCell), 1 when given the merged TNC tensor."""
        F = _infer_ns(first_input)
        dirs = 2 if self._bidirectional else 1
        states = []
        for _ in range(2 if self._mode == "lstm" else 1):
            states.append(F._begin_state_zeros_layers(
                first_input, num_hidden=self._num_hidden,
                num_layers=self._num_layers * dirs,
                batch_axis=batch_axis))
        return states

    def __call__(self, inputs, states):
        raise MXNetError("FusedRNNCell cannot be stepped; use unroll")

    def _slice_plan(self, input_size):
        """(name, offset, shape) for every per-gate array inside the flat
        vector, derived from the shared rnn_packed_layout (the single
        source of truth also used by the RNN op and shape inference)."""
        from ..ops.rnn_ops import rnn_packed_layout

        H = self._num_hidden
        dnames = ("l", "r")
        entries, total = rnn_packed_layout(
            self._mode, input_size, H, self._num_layers,
            self._bidirectional)
        plan = []
        for layer, d, group, kind, off, shape in entries:
            cols = shape[1] if kind == "weight" else None
            per_gate = H * cols if kind == "weight" else H
            for g, gate in enumerate(self._gate_names):
                gshape = (H, cols) if kind == "weight" else (H,)
                plan.append((f"{self._prefix}{dnames[d]}{layer}_{group}"
                             f"{gate}_{kind}", off + g * per_gate, gshape))
        return plan, total

    def _input_size_from(self, total):
        """Solve the layer-0 input size from the flat vector length: the
        total is affine in the input size."""
        from ..ops.rnn_ops import rnn_packed_layout

        _, t0 = rnn_packed_layout(self._mode, 0, self._num_hidden,
                                  self._num_layers, self._bidirectional)
        _, t1 = rnn_packed_layout(self._mode, 1, self._num_hidden,
                                  self._num_layers, self._bidirectional)
        slope = t1 - t0
        assert (total - t0) % slope == 0, \
            f"flat parameter size {total} inconsistent with cell config"
        return (total - t0) // slope

    def unpack_weights(self, args):
        args = dict(args)
        name = f"{self._prefix}parameters"
        if name not in args:
            return args
        arr = args.pop(name)
        plan, _ = self._slice_plan(self._input_size_from(arr.shape[0]))
        for pname, off, shape in plan:
            n = int(_np_prod(shape))
            args[pname] = arr[off:off + n].reshape(shape).copy()
        return args

    def pack_weights(self, args):
        from .. import ndarray as nd

        args = dict(args)
        probe = f"{self._prefix}l0_i2h{self._gate_names[0]}_weight"
        if probe not in args:
            return args
        input_size = args[probe].shape[1]
        plan, total = self._slice_plan(input_size)
        flat = [args.pop(pname).reshape((-1,)) for pname, _, _ in plan]
        args[f"{self._prefix}parameters"] = nd.Concat(*flat, dim=0)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        if self._dropout > 0 and self._num_layers > 1:
            import warnings

            warnings.warn(
                "FusedRNNCell: inter-layer dropout is not applied on the "
                "symbolic fused path (the stateless RNN op has no RNG key "
                "input); unfuse() for training-time dropout", stacklevel=2)
        inputs, _, F = _normalize_sequence(length, inputs, "TNC", True,
                                           in_layout=layout)
        if begin_state is None:
            begin_state = self._zeros_states(inputs, batch_axis=1)
        states = list(begin_state)
        outs = F.RNN(inputs, self._parameters, *states,
                     state_size=self._num_hidden,
                     num_layers=self._num_layers, mode=self._mode,
                     bidirectional=self._bidirectional, p=self._dropout,
                     state_outputs=True,
                     name=f"{self._prefix}rnn")
        outputs, hN = outs[0], outs[1]
        states = [hN, outs[2]] if self._mode == "lstm" else [hN]
        if layout == "NTC":
            outputs = F.SwapAxis(outputs, dim1=0, dim2=1)
        outputs, _, _ = _normalize_sequence(length, outputs, layout,
                                            merge_outputs)
        if self._get_next_state:
            return outputs, states
        return outputs, []

    def unfuse(self):
        """Equivalent stack of unfused cells (reference ~L880)."""
        stack = SequentialRNNCell()
        get_cell = {
            "rnn_relu": lambda pre: RNNCell(self._num_hidden,
                                            activation="relu", prefix=pre),
            "rnn_tanh": lambda pre: RNNCell(self._num_hidden,
                                            activation="tanh", prefix=pre),
            "lstm": lambda pre: LSTMCell(self._num_hidden, prefix=pre),
            "gru": lambda pre: GRUCell(self._num_hidden, prefix=pre),
        }[self._mode]
        for i in range(self._num_layers):
            if self._bidirectional:
                stack.add(BidirectionalCell(
                    get_cell(f"{self._prefix}l{i}_"),
                    get_cell(f"{self._prefix}r{i}_"),
                    output_prefix=f"{self._prefix}bi_l{i}_"))
            else:
                stack.add(get_cell(f"{self._prefix}l{i}_"))
            if self._dropout > 0 and i != self._num_layers - 1:
                stack.add(DropoutCell(self._dropout,
                                      prefix=f"{self._prefix}_dropout{i}_"))
        return stack


class SequentialRNNCell(BaseRNNCell):
    """Stack of cells applied in order (reference ~L950)."""

    def __init__(self, params=None):
        super().__init__(prefix="", params=params)
        self._cells = []
        self._override_cell_params = params is not None

    def add(self, cell):
        self._cells.append(cell)
        if self._override_cell_params:
            cell.params._params.update(self.params._params)
        self.params._params.update(cell.params._params)

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def _zeros_states(self, first_input, batch_axis=0):
        return sum((c._zeros_states(first_input, batch_axis)
                    for c in self._cells), [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._cells:
            n = len(cell.state_info)
            cell_states = states[p:p + n]
            p += n
            inputs, cell_states = cell(inputs, cell_states)
            next_states.extend(cell_states)
        return inputs, next_states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        num_cells = len(self._cells)
        if begin_state is None:
            first, _, _ = _normalize_sequence(length, inputs, layout, False)
            begin_state = self._zeros_states(first[0])
        p = 0
        next_states = []
        for i, cell in enumerate(self._cells):
            n = len(cell.state_info)
            states = begin_state[p:p + n]
            p += n
            inputs, states = cell.unroll(
                length, inputs=inputs, begin_state=states, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs)
            next_states.extend(states)
        return inputs, next_states


class BidirectionalCell(BaseRNNCell):
    """Forward + backward cell over the sequence (reference ~L1050)."""

    def __init__(self, l_cell, r_cell, params=None, output_prefix="bi_"):
        super().__init__(prefix="", params=params)
        self._output_prefix = output_prefix
        self._cells = [l_cell, r_cell]

    def __call__(self, inputs, states):
        raise MXNetError("BidirectionalCell cannot be stepped; use unroll")

    @property
    def state_info(self):
        return sum((c.state_info for c in self._cells), [])

    def begin_state(self, **kwargs):
        return sum((c.begin_state(**kwargs) for c in self._cells), [])

    def _zeros_states(self, first_input, batch_axis=0):
        return sum((c._zeros_states(first_input, batch_axis)
                    for c in self._cells), [])

    def unpack_weights(self, args):
        for cell in self._cells:
            args = cell.unpack_weights(args)
        return args

    def pack_weights(self, args):
        for cell in self._cells:
            args = cell.pack_weights(args)
        return args

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None):
        self.reset()
        inputs, axis, F = _normalize_sequence(length, inputs, layout, False)
        if begin_state is None:
            begin_state = self._zeros_states(inputs[0])
        states = begin_state
        l_cell, r_cell = self._cells
        n_l = len(l_cell.state_info)
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False)
        outputs = [F.Concat(l_o, r_o, dim=1,
                            name=f"{self._output_prefix}t{i}")
                   for i, (l_o, r_o) in enumerate(
                       zip(l_outputs, reversed(r_outputs)))]
        outputs, _, _ = _normalize_sequence(length, outputs, layout,
                                            merge_outputs)
        return outputs, l_states + r_states


class ModifierCell(BaseRNNCell):
    """Base for cells wrapping another cell (reference ~L1150)."""

    def __init__(self, base_cell):
        super().__init__()
        base_cell._modified = True
        self.base_cell = base_cell

    @property
    def params(self):
        self._own_params = False
        return self.base_cell.params

    @property
    def state_info(self):
        return self.base_cell.state_info

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def _zeros_states(self, first_input, batch_axis=0):
        self.base_cell._modified = False
        states = self.base_cell._zeros_states(first_input, batch_axis)
        self.base_cell._modified = True
        return states

    def unpack_weights(self, args):
        return self.base_cell.unpack_weights(args)

    def pack_weights(self, args):
        return self.base_cell.pack_weights(args)


class DropoutCell(BaseRNNCell):
    """Dropout on the outputs (reference ~L1120)."""

    def __init__(self, dropout, prefix="dropout_", params=None):
        super().__init__(prefix=prefix, params=params)
        self.dropout = dropout

    @property
    def state_info(self):
        return []

    def __call__(self, inputs, states):
        if self.dropout > 0:
            F = _infer_ns(inputs)
            inputs = F.Dropout(inputs, p=self.dropout)
        return inputs, states


class ZoneoutCell(ModifierCell):
    """Zoneout regularization on states (reference ~L1200)."""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, FusedRNNCell), \
            "FusedRNNCell doesn't support zoneout; unfuse() first"
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self.prev_output = None

    def reset(self):
        super().reset()
        self.prev_output = None

    def __call__(self, inputs, states):
        cell, p_outputs, p_states = (self.base_cell, self.zoneout_outputs,
                                     self.zoneout_states)
        next_output, next_states = cell(inputs, states)
        F = _infer_ns(inputs)

        def mask(p, like):
            return F.Dropout(F.ones_like(like), p=p)

        prev_output = self.prev_output if self.prev_output is not None \
            else F.zeros_like(next_output)
        output = (F.where(mask(p_outputs, next_output), next_output,
                          prev_output)
                  if p_outputs != 0.0 else next_output)
        states = ([F.where(mask(p_states, new_s), new_s, old_s)
                   for new_s, old_s in zip(next_states, states)]
                  if p_states != 0.0 else next_states)
        self.prev_output = output
        return output, states


class ResidualCell(ModifierCell):
    """Adds the input to the output (reference ~L1260)."""

    def __call__(self, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states
