"""BERT MLM pretraining over a device mesh (reference: the GluonNLP
bert pretraining scripts the reference docs point at; BASELINE target 2).

Single chip:   python examples/bert_pretrain.py --steps 20
Virtual mesh:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
               python examples/bert_pretrain.py --dp 4 --tp 2 --model small
3D (dp/pp/tp): ... bert_pretrain.py --dp 2 --pp 2 --tp 2 --model small
               (pipeline-parallel stacked encoder, models/bert_pp.py)
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.models import bert_base, bert_small
from mxnet_tpu.models.bert import bert_sharding_rules
from mxnet_tpu.parallel import DataParallelStep, make_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="small", choices=["small", "base"])
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--tp", type=int, default=1)
    ap.add_argument("--pp", type=int, default=1,
                    help="pipeline stages (uses the stacked pp encoder)")
    ap.add_argument("--pp-microbatches", type=int, default=2)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"])
    args = ap.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")

    import jax

    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    mx.random.seed(0)
    n_dev = args.dp * args.tp * args.pp
    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        raise SystemExit(f"need {n_dev} devices, have {len(devices)}")
    mesh = make_mesh(tp=args.tp, pp=args.pp, devices=devices)

    if args.pp > 1:
        # pipeline path: the stacked-parameter encoder (models/bert_pp.py)
        from mxnet_tpu.models import bert_pp_small
        from mxnet_tpu.models.bert_pp import (BERTForMLMPipelined,
                                              bert_pp_sharding_rules)

        net = (BERTForMLMPipelined() if args.model == "base"
               else bert_pp_small())
        rules = bert_pp_sharding_rules()
    else:
        net = bert_base() if args.model == "base" else bert_small()
        rules = bert_sharding_rules()
    if args.model != "base":
        args.seq_len = min(args.seq_len, 64)  # small-config max_length
    net.initialize(mx.init.Normal(0.02))
    if args.dtype == "bfloat16":
        net.cast("bfloat16")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    def mlm_loss(logits, labels):
        return loss_fn(logits.reshape(-1, logits.shape[-1]),
                       labels.reshape(-1))

    step = DataParallelStep(net, mlm_loss, mesh=mesh, optimizer="adam",
                            optimizer_params={"learning_rate": 1e-4},
                            rules=rules,
                            pp_microbatches=args.pp_microbatches)
    V = 30522 if args.model == "base" else 512
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, V, (args.batch_size, args.seq_len)).astype(
        np.int32)
    labels = tokens.astype(np.float32)
    tb = nd.array(tokens, dtype="int32")
    lb = nd.array(labels)

    t0 = time.perf_counter()
    for i in range(args.steps):
        # lazy AsyncLoss: only the logging interval pays a host readback
        loss = step.step(tb, lb)
        if i % 5 == 0:
            v = float(loss)
            dt = time.perf_counter() - t0
            toks = (i + 1) * args.batch_size * args.seq_len
            print(f"step {i}: loss={v:.4f}  {toks / dt:.0f} tok/s")
    step.drain()
    v = float(loss)
    print(f"final mlm loss {v:.4f} on mesh "
          f"dp{args.dp}xpp{args.pp}xtp{args.tp}")
    assert np.isfinite(v)


if __name__ == "__main__":
    main()
