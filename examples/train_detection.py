"""Detection training on synthetic boxes: SSD or Faster-RCNN (BASELINE
config 5; reference: example/ssd/train.py + example/rcnn/train_end2end.py).

    python examples/train_detection.py --model ssd --steps 20
    python examples/train_detection.py --model faster_rcnn --steps 12
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import (FasterRCNNTrainLoss, SSDTrainLoss,
                              faster_rcnn_small, ssd_300)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ssd",
                    choices=["ssd", "faster_rcnn"])
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=96)
    ap.add_argument("--num-classes", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"])
    args = ap.parse_args()
    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    mx.random.seed(0)
    B, S = args.batch_size, args.image_size
    x = nd.array(np.random.RandomState(0).rand(B, 3, S, S)
                 .astype(np.float32))

    if args.model == "ssd":
        net = ssd_300(num_classes=args.num_classes)
        net.initialize(mx.init.Xavier())
        loss_block = SSDTrainLoss()
        # SSD labels are normalized corner boxes [cls, x1, y1, x2, y2]
        labels = nd.array(np.tile(
            np.array([[[0, 0.25, 0.25, 0.75, 0.75]]], np.float32),
            (B, 1, 1)))

        def forward():
            anchors, cls_preds, box_preds = net(x)
            return loss_block(anchors, cls_preds, box_preds, labels)
    else:
        net = faster_rcnn_small(num_classes=args.num_classes)
        net.initialize(mx.init.Xavier())
        loss_block = FasterRCNNTrainLoss(net)
        # RCNN gt boxes are PIXEL corner boxes [cls, x1, y1, x2, y2]
        gt = nd.array(np.tile(np.array(
            [[[0, S // 4, S // 4, 3 * S // 4, 3 * S // 4]]], np.float32),
            (B, 1, 1)))
        im_info = nd.array(np.tile(
            np.array([[S, S, 1.0]], np.float32), (B, 1)))

        def forward():
            return loss_block(x, gt, im_info)

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    t0 = time.perf_counter()
    first = last = None
    for i in range(args.steps):
        with autograd.record():
            loss = forward()
        loss.backward()
        trainer.step(B)
        last = float(loss.asnumpy().mean())
        if first is None:
            first = last
        if i % 5 == 0:
            print(f"step {i}: loss={last:.4f}  "
                  f"{(i + 1) * B / (time.perf_counter() - t0):.1f} img/s")
    print(f"{args.model}: loss {first:.4f} -> {last:.4f} "
          f"({args.steps} steps)")
    assert np.isfinite(last)


if __name__ == "__main__":
    main()
