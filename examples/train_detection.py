"""Detection training: SSD or Faster-RCNN (BASELINE config 5; reference:
example/ssd/train.py + example/rcnn/train_end2end.py).

    python examples/train_detection.py --model ssd --steps 20
    python examples/train_detection.py --model faster_rcnn --steps 12
    # config-5 acceptance shape — detection RecordIO -> ImageDetIter
    # (bbox-aware augmentation) -> SSD train step:
    python examples/train_detection.py --model ssd --rec det.rec
    python examples/train_detection.py --model ssd --make-rec 64  # synth
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu.models import (FasterRCNNTrainLoss, SSDTrainLoss,
                              faster_rcnn_small, ssd_300)


def _synth_det_rec(n, size, num_classes):
    """Write a synthetic detection RecordIO (random images, 1-2 packed
    det boxes each) and return its path."""
    import tempfile

    from mxnet_tpu import recordio
    from mxnet_tpu.image.detection import pack_det_label

    d = tempfile.mkdtemp(prefix="det_rec_")
    rec, idx = f"{d}/det.rec", f"{d}/det.idx"
    rng = np.random.RandomState(0)
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        arr = rng.randint(0, 255, (size, size, 3), np.uint8)
        objs = [[i % num_classes, 0.2, 0.25, 0.7, 0.75]]
        if i % 2:  # alternate 1/2 boxes so the -1 label padding is real
            objs.append([(i + 1) % num_classes, 0.1, 0.1, 0.45, 0.5])
        header = recordio.IRHeader(
            0, pack_det_label(np.array(objs, np.float32)), i, 0)
        w.write_idx(i, recordio.pack_img(header, arr, quality=90))
    w.close()
    print(f"synthesized {n}-image det RecordIO at {rec}")
    return rec


def _next_batch(it):
    try:
        batch = next(it)
    except StopIteration:
        it.reset()
        try:
            batch = next(it)
        except StopIteration:
            raise SystemExit("--rec file holds no records")
    return batch.data[0], batch.label[0]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="ssd",
                    choices=["ssd", "faster_rcnn"])
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=96)
    ap.add_argument("--num-classes", type=int, default=1)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"])
    ap.add_argument("--rec", default=None,
                    help="detection RecordIO (packed det labels) -> "
                         "ImageDetIter input path; SSD only")
    ap.add_argument("--make-rec", type=int, default=0, metavar="N",
                    help="synthesize an N-image detection RecordIO in a "
                         "temp dir and train from it (SSD only)")
    args = ap.parse_args()
    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    mx.random.seed(0)
    B, S = args.batch_size, args.image_size
    x = nd.array(np.random.RandomState(0).rand(B, 3, S, S)
                 .astype(np.float32))

    if args.make_rec and not args.rec:
        args.rec = _synth_det_rec(args.make_rec, S, args.num_classes)
    det_iter = None
    if args.rec:
        if args.model != "ssd":
            raise SystemExit("--rec drives the SSD input path")
        from mxnet_tpu.image.detection import (CreateDetAugmenter,
                                               ImageDetIter)

        # real config-5 preprocessing: bbox-aware mirror + random crop +
        # mean/std normalization (the reference SSD recipe)
        augs = CreateDetAugmenter((3, S, S), rand_mirror=True,
                                  rand_crop=0.5, mean=True, std=True)
        det_iter = ImageDetIter(batch_size=B, data_shape=(3, S, S),
                                path_imgrec=args.rec, shuffle=True,
                                aug_list=augs)

    if args.model == "ssd":
        net = ssd_300(num_classes=args.num_classes)
        net.initialize(mx.init.Xavier())
        loss_block = SSDTrainLoss()
        # SSD labels are normalized corner boxes [cls, x1, y1, x2, y2]
        labels = nd.array(np.tile(
            np.array([[[0, 0.25, 0.25, 0.75, 0.75]]], np.float32),
            (B, 1, 1)))

        if det_iter is not None:
            def forward():
                data, lab = _next_batch(det_iter)
                anchors, cls_preds, box_preds = net(data)
                return loss_block(anchors, cls_preds, box_preds, lab)
        else:
            def forward():
                anchors, cls_preds, box_preds = net(x)
                return loss_block(anchors, cls_preds, box_preds, labels)
    else:
        net = faster_rcnn_small(num_classes=args.num_classes)
        net.initialize(mx.init.Xavier())
        loss_block = FasterRCNNTrainLoss(net)
        # RCNN gt boxes are PIXEL corner boxes [cls, x1, y1, x2, y2]
        gt = nd.array(np.tile(np.array(
            [[[0, S // 4, S // 4, 3 * S // 4, 3 * S // 4]]], np.float32),
            (B, 1, 1)))
        im_info = nd.array(np.tile(
            np.array([[S, S, 1.0]], np.float32), (B, 1)))

        def forward():
            return loss_block(x, gt, im_info)

    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    t0 = time.perf_counter()
    first = last = None
    for i in range(args.steps):
        with autograd.record():
            loss = forward()
        loss.backward()
        trainer.step(B)
        # force the loss to host ONLY at display cadence: a per-step
        # asnumpy() blocks the dispatch pipeline on every iteration
        if i % 5 == 0 or i == args.steps - 1:
            last = float(loss.asnumpy().mean())
            if first is None:
                first = last
        if i % 5 == 0:
            print(f"step {i}: loss={last:.4f}  "
                  f"{(i + 1) * B / (time.perf_counter() - t0):.1f} img/s")
    trainer.drain()
    print(f"{args.model}: loss {first:.4f} -> {last:.4f} "
          f"({args.steps} steps)")
    assert np.isfinite(last)


if __name__ == "__main__":
    main()
