"""ImageNet-style classification training (reference:
example/image-classification/train_imagenet.py): ResNet/VGG/MobileNet from
the model zoo over ImageRecordIter (.rec) input, with the fused
data-parallel step as the TPU throughput path.

Run:
  python examples/train_imagenet.py --rec train.rec --model resnet50_v1b
  python examples/train_imagenet.py --synthetic   # no data needed
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.gluon.model_zoo import vision
from mxnet_tpu.parallel import DataParallelStep, local_mesh


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", default=None, help=".rec file (ImageRecordIter)")
    ap.add_argument("--synthetic", action="store_true")
    ap.add_argument("--model", default="resnet50_v1b")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--num-classes", type=int, default=1000)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"],
                    help="cpu pins the CPU backend via jax.config (the "
                         "JAX_PLATFORMS env var is not reliable under a "
                         "TPU-relay shim); auto uses the default platform")
    args = ap.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    shape = tuple(int(s) for s in args.image_shape.split(","))
    mx.random.seed(0)
    ctx = mx.current_context()
    net = vision.get_model(args.model)
    net.initialize(mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    step = DataParallelStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mesh=local_mesh(devices=[ctx.jax_device]), optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": 0.9,
                          "wd": 1e-4})

    if args.rec:
        it = mx.io.ImageRecordIter(
            path_imgrec=args.rec, batch_size=args.batch_size,
            data_shape=shape, shuffle=True, rand_crop=True,
            rand_mirror=True)
        # device-side prefetch: decode/augment AND the H2D transfer of the
        # next batch run in a background thread while the current fused
        # step computes (the step skips its own transfer)
        it = mx.io.DevicePrefetchIter(it, step)

        def batches():
            while True:
                for b in it:
                    yield b.data[0], b.label[0]
                it.reset()
    else:
        rng = np.random.RandomState(0)
        x = rng.rand(args.batch_size, *shape).astype(np.float32)
        y = rng.randint(0, args.num_classes,
                        args.batch_size).astype(np.float32)
        if args.dtype == "bfloat16":
            import ml_dtypes

            x = x.astype(ml_dtypes.bfloat16)
        xb = nd.array(x, ctx=ctx, dtype=x.dtype)
        yb = nd.array(y, ctx=ctx)

        def batches():
            while True:
                yield xb, yb

    gen = batches()
    t0 = time.perf_counter()
    for i, (data, label) in zip(range(args.steps), gen):
        # step() returns a LAZY AsyncLoss: dispatch never blocks, and the
        # loss is only read back at the logging interval below
        loss = step.step(data, label)
        if i % 10 == 0:
            v = float(loss)
            dt = time.perf_counter() - t0
            seen = (i + 1) * args.batch_size
            print(f"step {i}: loss={v:.4f}  {seen / dt:.1f} img/s")
    step.drain()  # land (and error-check) every in-flight step
    v = float(loss)
    print(f"final loss {v:.4f}")
    assert np.isfinite(v)


if __name__ == "__main__":
    main()
