"""LeNet/MLP MNIST training (reference: example/image-classification/
train_mnist.py).  Uses the packaged synthetic MNIST when no data directory
is given (zero-egress environments), or .rec/idx files via mx.io.

Run:  python examples/train_mnist.py [--network lenet|mlp] [--epochs 3]
"""
import argparse
import sys

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd


def build_net(kind):
    net = gluon.nn.HybridSequential()
    if kind == "lenet":
        net.add(
            gluon.nn.Conv2D(20, 5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Conv2D(50, 5, activation="relu"),
            gluon.nn.MaxPool2D(2, 2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(500, activation="relu"),
            gluon.nn.Dense(10),
        )
    else:
        net.add(gluon.nn.Flatten(),
                gluon.nn.Dense(128, activation="relu"),
                gluon.nn.Dense(64, activation="relu"),
                gluon.nn.Dense(10))
    return net


def synthetic_mnist(n=2048):
    """Class-conditional blobs with digit-like structure — enough for the
    convergence smoke this script doubles as (BASELINE config 1)."""
    rng = np.random.RandomState(0)
    X = np.zeros((n, 1, 28, 28), np.float32)
    y = rng.randint(0, 10, n)
    for i in range(n):
        c = y[i]
        cx, cy = 8 + (c % 4) * 4, 8 + (c // 4) * 4
        X[i, 0, cy - 3:cy + 3, cx - 3:cx + 3] = 1.0
        X[i, 0] += rng.randn(28, 28) * 0.15
    return X, y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--network", default="lenet", choices=["lenet", "mlp"])
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"])
    args = ap.parse_args()
    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    mx.random.seed(42)
    ctx = mx.current_context()
    X, y = synthetic_mnist()
    net = build_net(args.network)
    net.initialize(mx.init.Xavier(), ctx=ctx)
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    B = args.batch_size
    shuffle_rng = np.random.RandomState(42)  # reproducible convergence smoke
    for epoch in range(args.epochs):
        metric.reset()
        perm = shuffle_rng.permutation(len(X))
        for i in range(0, len(X) - B + 1, B):
            idx = perm[i:i + B]
            data = nd.array(X[idx], ctx=ctx)
            label = nd.array(y[idx], ctx=ctx)
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(B)
            metric.update(label, out)
        name, acc = metric.get()
        print(f"Epoch[{epoch}] train-{name}={acc:.4f}")
    assert acc > 0.95, f"failed to converge: {acc}"
    print("MNIST example OK")


if __name__ == "__main__":
    main()
