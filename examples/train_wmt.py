"""Transformer machine-translation training (BASELINE config 4 skeleton;
reference: GluonNLP scripts/machine_translation train_transformer.py).

Runs the encoder-decoder Transformer with label-smoothed CE through the
fused multi-input DataParallelStep — forward, backward, optimizer and the
tied-embedding softmax compile to ONE XLA program per step.  With no WMT
corpus in the sandbox (zero egress) the default data is a synthetic
copy/reverse corpus; point --src/--tgt at token-id files (one
space-separated sentence per line) for real data.

  python examples/train_wmt.py --model base --steps 30
  python examples/train_wmt.py --model big --dp 8   # pod recipe shape
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.models.transformer import (Transformer, label_smoothed_ce,
                                          transformer_base, transformer_big)
from mxnet_tpu.parallel import DataParallelStep, make_mesh

PAD, BOS, EOS = 0, 1, 2


def synthetic_batch(rng, batch, src_len, vocab):
    src = rng.randint(3, vocab, (batch, src_len)).astype(np.int32)
    tgt_in = np.zeros((batch, src_len + 2), np.int32)
    tgt_out = np.zeros((batch, src_len + 2), np.int32)
    rev = src[:, ::-1]
    tgt_in[:, 0] = BOS
    tgt_in[:, 1:src_len + 1] = rev
    tgt_out[:, :src_len] = rev
    tgt_out[:, src_len] = EOS
    return src, tgt_in, tgt_out


def load_parallel_corpus(src_path, tgt_path, max_len, batch):
    """Token-id files (one space-separated sentence per line) -> one
    padded (src, tgt_in, tgt_out) batch of the first `batch` pairs."""
    def read(path):
        rows = []
        with open(path) as f:
            for line in f:
                toks = [int(t) for t in line.split()][:max_len]
                if toks:
                    rows.append(toks)
        return rows

    s_rows, t_rows = read(src_path), read(tgt_path)
    if len(s_rows) != len(t_rows):
        raise SystemExit(f"corpus length mismatch: {len(s_rows)} src vs "
                         f"{len(t_rows)} tgt sentences")
    n = min(batch, len(s_rows))
    Ls = max(len(r) for r in s_rows[:n])
    Lt = max(len(r) for r in t_rows[:n]) + 2
    src = np.full((n, Ls), PAD, np.int32)
    tgt_in = np.full((n, Lt), PAD, np.int32)
    tgt_out = np.full((n, Lt), PAD, np.int32)
    for i in range(n):
        src[i, :len(s_rows[i])] = s_rows[i]
        tgt_in[i, 0] = BOS
        tgt_in[i, 1:len(t_rows[i]) + 1] = t_rows[i]
        tgt_out[i, :len(t_rows[i])] = t_rows[i]
        tgt_out[i, len(t_rows[i])] = EOS
    return src, tgt_in, tgt_out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="base", choices=["base", "big", "tiny"])
    ap.add_argument("--src", default=None, help="source token-id file")
    ap.add_argument("--tgt", default=None, help="target token-id file")
    ap.add_argument("--vocab-size", type=int, default=32000)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--src-len", type=int, default=16)
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--smoothing", type=float, default=0.1)
    ap.add_argument("--dp", type=int, default=1)
    ap.add_argument("--sp", type=int, default=1)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--device", default="auto", choices=["auto", "cpu"])
    args = ap.parse_args()
    if args.steps < 1:
        raise SystemExit("--steps must be >= 1")
    if args.device == "cpu":
        mx.context.pin_platform("cpu")

    import jax

    mx.random.seed(0)
    n_dev = args.dp * args.sp
    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        raise SystemExit(f"need {n_dev} devices, have {len(devices)}")
    mesh = make_mesh(sp=args.sp, devices=devices)

    if args.model == "tiny":
        net = Transformer(args.vocab_size, units=64, hidden_size=128,
                          num_heads=4, num_layers=2, dropout=0.1)
    elif args.model == "base":
        net = transformer_base(args.vocab_size)
    else:
        net = transformer_big(args.vocab_size)
    net.initialize(mx.init.Xavier())
    if args.dtype == "bfloat16":
        net.cast("bfloat16")

    step = DataParallelStep(
        net,
        lambda logits, labels: label_smoothed_ce(logits, labels,
                                                 smoothing=args.smoothing),
        mesh=mesh, optimizer="adam",
        optimizer_params={"learning_rate": args.lr})

    rng = np.random.RandomState(0)
    if args.src and args.tgt:
        src, tgt_in, tgt_out = load_parallel_corpus(
            args.src, args.tgt, args.src_len, args.batch_size)
    else:
        src, tgt_in, tgt_out = synthetic_batch(rng, args.batch_size,
                                               args.src_len, args.vocab_size)
    sb = nd.array(src, dtype="int32")
    tb = nd.array(tgt_in, dtype="int32")
    lb = nd.array(tgt_out.astype(np.float32))

    tokens_per_step = int((tgt_out != PAD).sum())
    t0 = time.perf_counter()
    for i in range(args.steps):
        # lazy AsyncLoss: forced at step 0 (compile split) and at the end
        loss = step.step((sb, tb), lb)
        if i == 0:
            val = float(loss)
            print(f"step 0: loss={val:.4f} (compile "
                  f"{time.perf_counter() - t0:.1f}s)", flush=True)
            t0 = time.perf_counter()
    step.drain()
    val = float(loss)
    dt = time.perf_counter() - t0
    rate = tokens_per_step * max(args.steps - 1, 1) / dt
    print(f"final loss {val:.4f}  {rate:.0f} tok/s")


if __name__ == "__main__":
    main()
