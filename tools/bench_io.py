#!/usr/bin/env python
"""Input-pipeline throughput benchmark (reference analog:
benchmark/python + tools/bandwidth — documents the img/s the native
RecordIO iterator sustains, per SURVEY §7.3 item 4).

Generates a synthetic .rec (random JPEGs at --size), then measures
ImageRecordIter throughput with the ResNet-50 augmentation recipe.
Prints one JSON line.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-images", type=int, default=512)
    ap.add_argument("--size", type=int, default=256)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 4)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    # the measurement is the HOST decode/augment pipeline — pin jax to CPU
    # so NDArray wrapping never waits on an accelerator backend
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from mxnet_tpu import recordio
    from mxnet_tpu.io import ImageRecordIter
    from mxnet_tpu.io import native as native_mod

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        rec = os.path.join(d, "bench.rec")
        writer = recordio.MXIndexedRecordIO(os.path.join(d, "bench.idx"),
                                            rec, "w")
        for i in range(args.num_images):
            arr = rng.randint(0, 255, (args.size, args.size, 3), np.uint8)
            header = recordio.IRHeader(0, float(i % 1000), i, 0)
            writer.write_idx(i, recordio.pack_img(header, arr, quality=90))
        writer.close()

        it = ImageRecordIter(
            path_imgrec=rec, data_shape=(3, args.crop, args.crop),
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True, resize=args.size,
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.395, std_g=57.12, std_b=57.375,
            preprocess_threads=args.threads)
        # warmup epoch (thread pool spin-up, page cache)
        for _ in it:
            pass
        n = 0
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            it.reset()
            for batch in it:
                n += batch.data[0].shape[0]
        dt = time.perf_counter() - t0
    print(json.dumps({
        "metric": "image_record_iter_images_per_sec",
        "value": round(n / dt, 1), "unit": "images/sec",
        "native": native_mod.available(), "threads": args.threads,
        "crop": args.crop}))


if __name__ == "__main__":
    main()
