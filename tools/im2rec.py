#!/usr/bin/env python3
"""im2rec: pack an image dataset into RecordIO (.rec + .idx).

Reference parity: tools/im2rec.py + tools/im2rec.cc (OpenCV encode ->
RecordIO packer, multithreaded ~L1-400).  Usage mirrors the reference:

  # make a list file (label = class-subdirectory index)
  python tools/im2rec.py --list data/train data/imgs --recursive

  # pack it
  python tools/im2rec.py data/train data/imgs --resize 256 --quality 95 \
      --num-thread 8

List format (tab-separated): index \t label... \t relative_path
"""
from __future__ import annotations

import argparse
import os
import sys
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

_EXTS = {".jpg", ".jpeg", ".png", ".bmp"}


def make_list(prefix, root, recursive=False, train_ratio=1.0, chunks=1):
    entries = []
    if recursive:
        classes = sorted(
            d for d in os.listdir(root)
            if os.path.isdir(os.path.join(root, d)))
        for label, cls in enumerate(classes):
            for dirpath, _, files in os.walk(os.path.join(root, cls)):
                for f in sorted(files):
                    if os.path.splitext(f)[1].lower() in _EXTS:
                        rel = os.path.relpath(os.path.join(dirpath, f), root)
                        entries.append((float(label), rel))
        print(f"{len(classes)} classes: {classes}")
    else:
        for f in sorted(os.listdir(root)):
            if os.path.splitext(f)[1].lower() in _EXTS:
                entries.append((0.0, f))

    import random

    random.Random(0).shuffle(entries)
    n_train = int(len(entries) * train_ratio)
    splits = [("", entries[:n_train])]
    if train_ratio < 1.0:
        splits = [("_train", entries[:n_train]), ("_val", entries[n_train:])]
    for suffix, ents in splits:
        path = f"{prefix}{suffix}.lst"
        with open(path, "w") as f:
            for i, (label, rel) in enumerate(ents):
                f.write(f"{i}\t{label}\t{rel}\n")
        print(f"wrote {len(ents)} entries to {path}")


def read_list(path):
    with open(path) as f:
        for line in f:
            parts = line.strip().split("\t")
            if len(parts) < 3:
                continue
            idx = int(parts[0])
            labels = [float(x) for x in parts[1:-1]]
            yield idx, labels, parts[-1]


def pack(prefix, root, resize=0, quality=95, num_thread=4, color=1,
         encoding=".jpg"):
    from mxnet_tpu import recordio
    from mxnet_tpu.image import imread, imresize

    lst = list(read_list(prefix + ".lst"))
    record = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")

    def encode_one(item):
        idx, labels, rel = item
        import numpy as np

        img = imread(os.path.join(root, rel), to_ndarray=False)
        if resize:
            h, w = img.shape[:2]
            if min(h, w) != resize:
                s = resize / min(h, w)
                img = imresize(img, int(round(w * s)), int(round(h * s)))
        header = recordio.IRHeader(
            flag=len(labels) if len(labels) > 1 else 0,
            label=(labels if len(labels) > 1 else labels[0]),
            id=idx, id2=0)
        return idx, recordio.pack_img(header, img, quality=quality,
                                      img_fmt=encoding)

    with ThreadPoolExecutor(max_workers=num_thread) as pool:
        for i, (idx, payload) in enumerate(pool.map(encode_one, lst)):
            record.write_idx(idx, payload)
            if (i + 1) % 1000 == 0:
                print(f"packed {i + 1}/{len(lst)}")
    record.close()
    print(f"wrote {len(lst)} records to {prefix}.rec")


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("prefix", help="output prefix (prefix.lst/.rec/.idx)")
    ap.add_argument("root", help="image root directory")
    ap.add_argument("--list", action="store_true",
                    help="generate the .lst file instead of packing")
    ap.add_argument("--recursive", action="store_true",
                    help="label images by class subdirectory")
    ap.add_argument("--train-ratio", type=float, default=1.0)
    ap.add_argument("--resize", type=int, default=0,
                    help="resize shorter side before encoding")
    ap.add_argument("--quality", type=int, default=95)
    ap.add_argument("--num-thread", type=int, default=4)
    ap.add_argument("--encoding", default=".jpg")
    args = ap.parse_args(argv)

    if args.list:
        make_list(args.prefix, args.root, recursive=args.recursive,
                  train_ratio=args.train_ratio)
    else:
        pack(args.prefix, args.root, resize=args.resize,
             quality=args.quality, num_thread=args.num_thread,
             encoding=args.encoding)


if __name__ == "__main__":
    main()
