#!/usr/bin/env python
"""Fleet-wide serving-request analysis over merged telemetry JSONL
streams (docs/OBSERVABILITY.md §Request tracing).

The serving stack writes one ``rank-<R>.jsonl`` stream per process —
the Router's ``serve_route``/``serve_dispatch`` spans in its stream,
each replica's ``serve_handle``/``serve_queue``/``serve_prefill``/
``serve_decode`` spans plus the per-request ``serve_request`` event in
its own — all correlated by the ``trace_id`` the Router minted and
propagated in the ``X-MX-Trace`` header.  This CLI merges the streams
(clock-anchor alignment, the same wall<-mono mapping
``telemetry.export_chrome_trace`` uses) and reconstructs ONE span tree
per request, then answers the question the per-rank views cannot:
*why was the p99 slow?*

  * **tail-latency attribution table** — p50 / p50-p90 / p90-p99 / p99+
    buckets, each broken into the six legs of a request's life:
    router queue (residence outside any dispatch attempt), dispatch
    (network + serialization: attempt wall minus replica handle wall),
    replica queue, prefill (ingest included), decode, stream (handle
    residual);
  * **dominant cause per slow request** — priority-ordered:
    ``failover`` (a dispatch attempt died; the router's
    ``serve_cause`` event), ``preempt`` (recompute preemption),
    ``swap`` (decoded across a weight hot-swap window), ``cache_miss``
    (prefix-cache miss), ``straggler`` (its replica's decode ms/token
    exceeds ``--straggler-x`` times the fleet median), else the largest
    leg;
  * **SLO violations** — the engine's ``serve_slo_violation`` events
    (``MX_SERVE_SLO_TTFT_MS`` / ``MX_SERVE_SLO_TPOT_MS`` at serve
    time) plus an optional analysis-time ``--slo-total-ms`` gate;
  * **unfinished request trees** — traces whose ``serve_route`` /
    ``serve_handle`` begin never saw its end: the fleet edition of the
    flight recorder's "died inside X" clue (what tools/launch.py's
    gang-death hook echoes).

Exit code: 0 clean, 2 usage/IO error, 3 when SLO violations were found
— CI and the launch.py supervisor key off it.  ``--json`` emits the
full report object for machines.

Importable WITHOUT jax/mxnet_tpu (stdlib only), like its siblings
``trace_report.py`` / ``mem_report.py`` — the supervisor runs it right
after a gang death.  The JSONL schema knowledge is shared with
``mxnet_tpu/telemetry.py`` and ``mxnet_tpu/serving/router.py`` — keep
them in sync.  Request-level serving analysis lives HERE; step-level
training analysis (and its straggler rules, which serving's
driver+HTTP thread shape would confuse) stays in ``trace_report.py``,
which defers to this tool when it detects serving-mode streams.

Thresholds come from flags, falling back to env knobs registered in
``mxnet_tpu/env_vars.py``: ``MX_RQTRACE_STRAGGLER_X`` (replica decode
ms/token vs fleet median, default 2.0).
"""
from __future__ import annotations

import argparse
import glob
import json
import math
import os
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_streams", "build_report", "format_text", "main"]

DEFAULT_STRAGGLER_X = 2.0
LEGS = ("router_queue_ms", "dispatch_ms", "replica_queue_ms",
        "prefill_ms", "decode_ms", "stream_ms")
MAX_SLOW_ROWS = 50
MAX_UNFINISHED_ROWS = 20


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_streams(paths: List[str]) -> Tuple[Dict[str, List[dict]],
                                            List[str]]:
    """{stream_name: [events...]} for every ``rank-<R>.jsonl`` under the
    given directories (or explicit .jsonl files), plus human-readable
    warnings.  Stream names stay unique when several directories hold
    the same rank number (a router dir next to a replica dir)."""
    streams: Dict[str, List[dict]] = {}
    warnings: List[str] = []
    files: List[Tuple[str, str]] = []
    for path in paths:
        if os.path.isdir(path):
            found = sorted(glob.glob(os.path.join(path, "rank-*.jsonl")))
            if not found:
                warnings.append(f"no rank-*.jsonl streams under {path!r}")
            files.extend((path, f) for f in found)
        elif os.path.isfile(path):
            files.append((os.path.dirname(path) or ".", path))
        else:
            raise OSError(f"no such telemetry dir or stream: {path!r}")
    for base, fpath in files:
        name = os.path.basename(fpath)
        if name in streams:  # same rank number from a second directory
            name = f"{os.path.basename(os.path.abspath(base))}/{name}"
        events: List[dict] = []
        torn = 0
        with open(fpath, "r", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    torn += 1  # a crash mid-write leaves one torn tail
                    continue
                if isinstance(ev, dict):
                    events.append(ev)
        if torn:
            warnings.append(f"{name}: {torn} torn line(s) skipped")
        streams[name] = events
    return streams, warnings


def _anchor_offset(events: List[dict]) -> Optional[float]:
    """wall - mono offset from the stream's clock_anchor events (median
    over all anchors; None when the stream predates anchors)."""
    offs = sorted(ev["wall"] - ev["mono"] for ev in events
                  if ev.get("kind") == "clock_anchor"
                  and "wall" in ev and "mono" in ev)
    if not offs:
        return None
    return offs[len(offs) // 2]


def _extract_spans(events: List[dict], stream: str,
                   warnings: List[str]) -> Tuple[List[dict], List[dict]]:
    """(closed_spans, open_spans) for one stream, start times on the
    gang wall timeline.  Closed spans come from complete ``span``
    events and matched begin/end pairs; an unmatched ``span_begin`` is
    the "died inside X" clue and lands in open_spans."""
    off = _anchor_offset(events)
    closed: List[dict] = []
    opens: Dict[int, dict] = {}
    last_wall = 0.0
    for ev in events:
        t = ev.get("t")
        if isinstance(t, (int, float)):
            last_wall = max(last_wall, float(t))
        kind = ev.get("kind")
        if kind not in ("span", "span_begin", "span_end"):
            continue
        mono = ev.get("mono")
        if kind == "span":
            dur = float(ev.get("dur_ms", 0.0))
            if off is not None and isinstance(mono, (int, float)):
                start = float(mono) + off
            else:  # old-format stream: approximate from the wall stamp
                start = float(ev.get("t", 0.0)) - dur / 1e3
            closed.append(dict(ev, start_wall=start, stream=stream))
        elif kind == "span_begin":
            start = (float(mono) + off
                     if off is not None and isinstance(mono, (int, float))
                     else float(ev.get("t", 0.0)))
            opens[ev.get("span")] = dict(ev, start_wall=start,
                                         stream=stream)
        elif kind == "span_end":
            begin = opens.pop(ev.get("span"), None)
            if begin is None:
                continue
            merged = dict(begin)
            merged["dur_ms"] = float(ev.get("dur_ms", 0.0))
            if "error" in ev:
                merged["error"] = ev["error"]
            merged["kind"] = "span"
            closed.append(merged)
    open_spans = []
    for sp in opens.values():
        sp["open_ms"] = max(0.0, (last_wall - sp["start_wall"]) * 1e3)
        open_spans.append(sp)
    return closed, open_spans


# ---------------------------------------------------------------------------
# per-request reconstruction
# ---------------------------------------------------------------------------
SERVE_EVENT_KINDS = ("serve_request", "serve_slo_violation", "serve_cause",
                     "serve_preempt", "serve_failover",
                     "serve_pool_pressure", "serve_prefix")


def _collect_traces(streams: Dict[str, List[dict]],
                    warnings: List[str]) -> Dict[str, dict]:
    """trace_id -> raw material: spans + serving events, cross-stream."""
    traces: Dict[str, dict] = {}

    def bucket(tid) -> dict:
        return traces.setdefault(str(tid), {
            "spans": [], "open_spans": [], "events": []})

    for stream, events in streams.items():
        closed, open_spans = _extract_spans(events, stream, warnings)
        for sp in closed:
            if sp.get("trace_id") and str(sp.get("name", "")
                                          ).startswith("serve_"):
                bucket(sp["trace_id"])["spans"].append(sp)
        for sp in open_spans:
            if sp.get("trace_id"):
                bucket(sp["trace_id"])["open_spans"].append(sp)
        for ev in events:
            if ev.get("kind") not in SERVE_EVENT_KINDS:
                continue
            tid = ev.get("trace_id")
            if tid is None and ev.get("kind") == "serve_request":
                # untraced engine-only run (no router): still analyzable
                # from the event's own legs, keyed by request id
                tid = f"req:{ev.get('request_id')}"
            if tid is not None:
                bucket(tid)["events"].append(dict(ev, stream=stream))
    return traces


def _build_request(tid: str, raw: dict) -> dict:
    """One reconstructed request: its span tree roots, leg breakdown
    and engine-attributed cause (straggler attribution needs the whole
    fleet and happens later in build_report)."""
    spans = raw["spans"]
    by_name: Dict[str, List[dict]] = {}
    for sp in spans:
        by_name.setdefault(str(sp.get("name")), []).append(sp)
    route = min(by_name.get("serve_route", []),
                key=lambda s: s["start_wall"], default=None)
    handle = min(by_name.get("serve_handle", []),
                 key=lambda s: s["start_wall"], default=None)
    dispatches = sorted(by_name.get("serve_dispatch", []),
                        key=lambda s: s["start_wall"])
    sreq = next((e for e in raw["events"]
                 if e.get("kind") == "serve_request"), None)
    slo = [e for e in raw["events"]
           if e.get("kind") == "serve_slo_violation"]
    failover = (any(e.get("kind") in ("serve_cause", "serve_failover")
                    and (e.get("cause") == "failover"
                         or e.get("kind") == "serve_failover")
                    for e in raw["events"])
                or any(d.get("error") for d in dispatches))

    legs = dict.fromkeys(LEGS, 0.0)
    route_ms = float(route["dur_ms"]) if route else 0.0
    handle_ms = float(handle["dur_ms"]) if handle else 0.0
    disp_ms = sum(float(d["dur_ms"]) for d in dispatches)
    if sreq is not None:
        legs["replica_queue_ms"] = float(sreq.get("queue_wait_ms", 0.0))
        legs["prefill_ms"] = float(sreq.get("prefill_ms", 0.0))
        legs["decode_ms"] = float(sreq.get("decode_ms", 0.0))
    else:
        q = min(by_name.get("serve_queue", []),
                key=lambda s: s["start_wall"], default=None)
        legs["replica_queue_ms"] = float(q["dur_ms"]) if q else 0.0
        legs["prefill_ms"] = sum(float(s["dur_ms"])
                                 for s in by_name.get("serve_prefill", []))
        legs["decode_ms"] = sum(float(s["dur_ms"])
                                for s in by_name.get("serve_decode", []))
    legs["prefill_ms"] += sum(float(s["dur_ms"])
                              for s in by_name.get("serve_ingest", []))
    served = (legs["replica_queue_ms"] + legs["prefill_ms"]
              + legs["decode_ms"])
    if handle_ms:
        legs["stream_ms"] = max(0.0, handle_ms - served)
    inner = handle_ms if handle_ms else served + legs["stream_ms"]
    if disp_ms:
        legs["dispatch_ms"] = max(0.0, disp_ms - inner)
    if route_ms:
        legs["router_queue_ms"] = max(0.0, route_ms - disp_ms)
    latency = (route_ms or handle_ms
               or (float(sreq.get("latency_ms", 0.0)) if sreq else 0.0))

    cause = str(sreq.get("cause", "none")) if sreq else "none"
    if failover:
        cause = "failover"  # outranks the engine's verdict: the request
        #                     paid a whole failed attempt first
    replica = None
    if sreq is not None:
        replica = sreq.get("rank")
    elif handle is not None:
        replica = handle.get("replica")
    elif dispatches:
        replica = dispatches[-1].get("replica")
    opens = sorted(raw["open_spans"],
                   key=lambda s: s.get("depth", 0), reverse=True)
    return {
        "trace_id": tid,
        "request_id": (sreq.get("request_id") if sreq else
                       (route or handle or {}).get("request_id")),
        "latency_ms": round(latency, 3),
        "ttft_ms": round(float(sreq.get("ttft_ms", 0.0)), 3)
        if sreq else None,
        "tokens": int(sreq.get("tokens", 0)) if sreq else 0,
        "replica": replica,
        "legs": {k: round(v, 3) for k, v in legs.items()},
        "attempts": len(dispatches),
        "failed_attempts": sum(1 for d in dispatches if d.get("error")),
        "cause": cause,
        "slo_violated": sorted({str(e.get("stage")) for e in slo}),
        "late_sampled": any(sp.get("late_sampled") for sp in spans),
        "spans": len(spans),
        "open_span": ({"name": opens[0].get("name"),
                       "stream": opens[0].get("stream"),
                       "open_ms": round(opens[0]["open_ms"], 1)}
                      if opens else None),
        "finished": bool(sreq) or (route is not None and not opens),
    }


def _dominant_leg(req: dict) -> str:
    legs = req["legs"]
    return max(LEGS, key=lambda k: legs[k])


def _percentile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1,
              max(0, int(math.ceil(q / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[idx]


def _bucketize(reqs: List[dict]) -> List[dict]:
    """The tail-latency attribution table: p50 / p50-p90 / p90-p99 /
    p99+ cohorts with mean per-leg breakdown and cause histogram."""
    lats = sorted(r["latency_ms"] for r in reqs)
    p50, p90, p99 = (_percentile(lats, 50), _percentile(lats, 90),
                     _percentile(lats, 99))
    edges = [("p50", lambda v: v <= p50),
             ("p50-p90", lambda v: p50 < v <= p90),
             ("p90-p99", lambda v: p90 < v <= p99),
             ("p99+", lambda v: v > p99)]
    rows = []
    for label, member in edges:
        cohort = [r for r in reqs if member(r["latency_ms"])]
        if not cohort:
            rows.append({"bucket": label, "count": 0})
            continue
        n = len(cohort)
        causes: Dict[str, int] = {}
        for r in cohort:
            causes[r["cause"]] = causes.get(r["cause"], 0) + 1
        rows.append({
            "bucket": label, "count": n,
            "latency_ms": round(sum(r["latency_ms"]
                                    for r in cohort) / n, 3),
            "legs": {k: round(sum(r["legs"][k] for r in cohort) / n, 3)
                     for k in LEGS},
            "causes": dict(sorted(causes.items(),
                                  key=lambda kv: -kv[1])),
        })
    return rows


def _flag_stragglers(reqs: List[dict], straggler_x: float) -> List[dict]:
    """Fleet-wide straggler attribution: a replica whose mean decode
    ms/token exceeds ``straggler_x`` times the fleet median re-labels
    its cause-less requests ``straggler``.  Needs >= 2 replicas — one
    replica has no fleet to be slower than."""
    per_rep: Dict[object, List[float]] = {}
    for r in reqs:
        if r["tokens"] > 0 and r["replica"] is not None:
            per_rep.setdefault(r["replica"], []).append(
                r["legs"]["decode_ms"] / r["tokens"])
    if len(per_rep) < 2:
        return []
    means = {rep: sum(v) / len(v) for rep, v in per_rep.items()}
    # LOWER median on even fleets: with 2 replicas the upper median IS
    # the suspect, and comparing it against itself would hide it
    med = sorted(means.values())[(len(means) - 1) // 2]
    flagged = [rep for rep, m in means.items()
               if med > 0 and m > straggler_x * med]
    for r in reqs:
        if r["replica"] in flagged and r["cause"] == "none":
            r["cause"] = "straggler"
    return [{"replica": rep, "decode_ms_per_token": round(means[rep], 3),
             "fleet_median": round(med, 3)} for rep in sorted(
                 flagged, key=lambda rep: -means[rep])]


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
def build_report(streams: Dict[str, List[dict]],
                 straggler_x: Optional[float] = None,
                 slo_total_ms: float = 0.0,
                 warnings: Optional[List[str]] = None) -> dict:
    warnings = list(warnings or [])
    if straggler_x is None:
        straggler_x = _env_float("MX_RQTRACE_STRAGGLER_X",
                                 DEFAULT_STRAGGLER_X)
    traces = _collect_traces(streams, warnings)
    reqs = [_build_request(tid, raw) for tid, raw in traces.items()]
    finished = [r for r in reqs if r["finished"]]
    unfinished = sorted((r for r in reqs if not r["finished"]),
                        key=lambda r: -(r["open_span"] or {}
                                        ).get("open_ms", 0.0))
    stragglers = _flag_stragglers(finished, straggler_x)

    violations: List[dict] = []
    for r in finished:
        for stage in r["slo_violated"]:
            violations.append({"trace_id": r["trace_id"],
                               "stage": stage,
                               "latency_ms": r["latency_ms"],
                               "cause": r["cause"]})
        if slo_total_ms > 0 and r["latency_ms"] > slo_total_ms:
            violations.append({"trace_id": r["trace_id"],
                               "stage": "total",
                               "latency_ms": r["latency_ms"],
                               "threshold_ms": slo_total_ms,
                               "cause": r["cause"]})
    lats = sorted(r["latency_ms"] for r in finished)
    slow_floor = _percentile(lats, 90)
    slow = sorted((r for r in finished
                   if r["latency_ms"] > slow_floor or r["slo_violated"]),
                  key=lambda r: -r["latency_ms"])
    causes: Dict[str, int] = {}
    for r in finished:
        causes[r["cause"]] = causes.get(r["cause"], 0) + 1
    return {
        "streams": sorted(streams),
        "requests": len(finished),
        "unfinished": len(unfinished),
        "latency_ms": {"p50": _percentile(lats, 50),
                       "p90": _percentile(lats, 90),
                       "p99": _percentile(lats, 99),
                       "max": lats[-1] if lats else 0.0},
        "attribution": _bucketize(finished) if finished else [],
        "causes": dict(sorted(causes.items(), key=lambda kv: -kv[1])),
        "straggler_replicas": stragglers,
        "straggler_x": straggler_x,
        "slow_requests": [
            {"trace_id": r["trace_id"], "request_id": r["request_id"],
             "latency_ms": r["latency_ms"], "replica": r["replica"],
             "dominant_leg": _dominant_leg(r), "cause": r["cause"],
             "attempts": r["attempts"],
             "slo_violated": r["slo_violated"]}
            for r in slow[:MAX_SLOW_ROWS]],
        "slo_violations": violations,
        "unfinished_requests": [
            {"trace_id": r["trace_id"], "request_id": r["request_id"],
             "replica": r["replica"], "open_span": r["open_span"],
             "attempts": r["attempts"]}
            for r in unfinished[:MAX_UNFINISHED_ROWS]],
        "per_request": {r["trace_id"]: r for r in finished},
        "warnings": warnings,
    }


def format_text(report: dict) -> str:
    out: List[str] = []
    put = out.append
    put(f"serve_report: {len(report['streams'])} stream(s), "
        f"{report['requests']} completed request(s), "
        f"{report['unfinished']} unfinished")
    lat = report["latency_ms"]
    put(f"latency ms: p50={lat['p50']:.1f} p90={lat['p90']:.1f} "
        f"p99={lat['p99']:.1f} max={lat['max']:.1f}")
    if report["attribution"]:
        put("")
        put("== tail-latency attribution (mean ms per leg) ==")
        hdr = (f"{'bucket':>8} {'n':>5} {'latency':>9} "
               + " ".join(f"{leg[:-3]:>12}" for leg in LEGS))
        put(hdr)
        for row in report["attribution"]:
            if not row["count"]:
                continue
            put(f"{row['bucket']:>8} {row['count']:>5} "
                f"{row['latency_ms']:>9.1f} "
                + " ".join(f"{row['legs'][leg]:>12.1f}" for leg in LEGS))
    if report["causes"]:
        put("")
        put("== attributed causes ==")
        for cause, n in report["causes"].items():
            put(f"  {cause:<12} {n}")
    for srep in report["straggler_replicas"]:
        put(f"  straggler replica {srep['replica']}: "
            f"{srep['decode_ms_per_token']:.2f} ms/token vs fleet "
            f"median {srep['fleet_median']:.2f} "
            f"(x{report['straggler_x']:.1f} rule)")
    if report["slow_requests"]:
        put("")
        put("== slow requests (> p90 or SLO-violating) ==")
        put(f"{'trace':>18} {'latency':>9} {'replica':>8} "
            f"{'dominant leg':>16} {'cause':>12}")
        for r in report["slow_requests"]:
            put(f"{str(r['trace_id']):>18} {r['latency_ms']:>9.1f} "
                f"{str(r['replica']):>8} {_short(r['dominant_leg']):>16} "
                f"{r['cause']:>12}")
    if report["slo_violations"]:
        put("")
        put(f"== SLO violations ({len(report['slo_violations'])}) ==")
        for v in report["slo_violations"][:MAX_SLOW_ROWS]:
            put(f"  trace {v['trace_id']}: stage={v['stage']} "
                f"latency={v['latency_ms']:.1f}ms cause={v['cause']}")
    if report["unfinished_requests"]:
        put("")
        put("== unfinished requests (died inside ...) ==")
        for r in report["unfinished_requests"]:
            sp = r["open_span"] or {}
            put(f"  trace {r['trace_id']}: open {sp.get('name')} "
                f"({sp.get('open_ms', 0.0):.0f} ms before stream end, "
                f"{sp.get('stream')})")
    for w in report["warnings"]:
        put(f"warning: {w}")
    return "\n".join(out) + "\n"


def _short(leg: str) -> str:
    return leg[:-3] if leg.endswith("_ms") else leg


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_report",
        description="per-request tail-latency attribution over merged "
                    "serving telemetry streams")
    ap.add_argument("paths", nargs="+",
                    help="telemetry dir(s) (rank-*.jsonl) or stream files")
    ap.add_argument("--json", action="store_true",
                    help="emit the report object as JSON")
    ap.add_argument("--straggler-x", type=float, default=None,
                    help="replica decode ms/token vs fleet median "
                         "(default MX_RQTRACE_STRAGGLER_X or "
                         f"{DEFAULT_STRAGGLER_X})")
    ap.add_argument("--slo-total-ms", type=float, default=0.0,
                    help="analysis-time end-to-end latency SLO "
                         "(0 = serve-time events only)")
    args = ap.parse_args(argv)
    try:
        streams, warnings = load_streams(args.paths)
    except OSError as e:
        print(f"serve_report: {e}", file=sys.stderr)
        return 2
    if not streams:
        print("serve_report: no telemetry streams found", file=sys.stderr)
        return 2
    report = build_report(streams, straggler_x=args.straggler_x,
                          slo_total_ms=args.slo_total_ms,
                          warnings=warnings)
    if args.json:
        print(json.dumps(report, indent=2, sort_keys=True, default=str))
    else:
        sys.stdout.write(format_text(report))
    return 3 if report["slo_violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
