#!/usr/bin/env python
"""Gang-wide trace analysis over per-rank telemetry JSONL streams
(docs/OBSERVABILITY.md §Tracing & analysis).

``mxnet_tpu/telemetry.py`` leaves one ``rank-<R>.jsonl`` event stream per
rank under ``MX_TELEMETRY_DIR``; this CLI merges them into the questions a
human (or the launch.py supervisor, or CI) actually asks after a run:

  * **per-step breakdown** — compile vs steady-state step counts and
    wall, and where a steady step's time goes (``dispatch`` /
    ``input_stage`` / ``block_wait`` / ``loss_wait`` span phases, H2D
    bytes and how much of them a prefetcher overlapped);
  * **per-rank skew table with straggler flagging** — two rules, because
    sync-SGD hides stragglers two different ways:
      - *idle-gap skew* (checked first): wall-clock run span minus time
        accounted by that rank's top-level spans (and step walls).  In
        lock-step training the straggler's lost time is *unrecorded host
        time* (slow disk, GC, CPU contention, a sleeping process) while
        its peers' equal share of waiting shows up inside recorded
        ``loss_wait``/``block_wait``/collective/dispatch regions — and
        the victims' step walls BALLOON from that waiting, so the naive
        "slowest wall = straggler" reading names the wrong rank.  The
        rank whose unaccounted time towers over the others is the one
        everyone else was waiting for;
      - *step-wall skew*: mean steady step wall over a sliding window of
        each rank's newest steps; a rank slower than the fastest by more
        than the threshold is flagged.  Applied only when idle gaps are
        symmetric (the non-lockstep shape: independent cadences, one
        rank's compute/dispatch genuinely slower);
  * **collective bandwidth table** — per op and per rank: count, bytes,
    dispatch wall, effective MB/s (first-use compile-tagged events are
    excluded from the bandwidth math);
  * **retrace attribution** — which executor kept recompiling, with the
    newest offending signature;
  * **heartbeat-gap timeline** — stretches where a rank's event stream
    went silent longer than the threshold: the "was it stuck or slow,
    and *when*" answer for post-mortems.

Exit code: 0 clean, 2 usage/IO error, 3 when anomalies were flagged
(stragglers, retrace storms, event gaps) — CI and the supervisor key off
it.  ``--json`` emits the full report object for machines.

Importable WITHOUT jax/mxnet_tpu (stdlib only): the launch.py supervisor
runs it right after a gang death, where importing jax could hang on a
poisoned accelerator runtime.  The JSONL schema knowledge is shared with
``mxnet_tpu/telemetry.py`` — keep the two in sync.

Thresholds come from flags, falling back to env knobs registered in
``mxnet_tpu/env_vars.py``: ``MX_TRACE_WINDOW`` (sliding window, default
20 steps), ``MX_TRACE_STRAGGLER_PCT`` (skew threshold, default 25%),
``MX_TRACE_HEARTBEAT_GAP_SEC`` (silence threshold, default 30 s).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional, Tuple

__all__ = ["load_gang", "build_report", "format_text", "main"]

DEFAULT_WINDOW = 20
DEFAULT_STRAGGLER_PCT = 25.0
DEFAULT_GAP_SEC = 30.0


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_gang(directory: str) -> Tuple[Dict[int, List[dict]], List[str]]:
    """{rank: [events...]} for every rank-<R>.jsonl under ``directory``,
    plus human-readable warnings (torn lines, missing clock anchors)."""
    ranks: Dict[int, List[dict]] = {}
    warnings: List[str] = []
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        raise SystemExit(f"trace_report: cannot read {directory}: {e}")
    for name in names:
        if not (name.startswith("rank-") and name.endswith(".jsonl")):
            continue
        try:
            rank = int(name[len("rank-"):-len(".jsonl")])
        except ValueError:
            continue
        events: List[dict] = []
        torn = 0
        with open(os.path.join(directory, name), errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    torn += 1
                    continue
                if isinstance(ev, dict) and "kind" in ev:
                    events.append(ev)
        if torn:
            warnings.append(f"rank {rank}: {torn} torn JSONL line(s) "
                            "skipped (SIGKILL mid-write?)")
        if events and not any(e["kind"] == "clock_anchor" for e in events):
            # the satellite contract: old-format files must degrade loudly,
            # not silently misalign the merged timeline
            warnings.append(
                f"rank {rank}: no clock_anchor events (old-format stream?) "
                "— cross-rank span alignment falls back to per-event wall "
                "stamps and may be skewed by flush latency")
        ranks[rank] = events
    return ranks, warnings


def _pair_spans(events: List[dict]) -> List[dict]:
    """Completed spans: {name, dur_ms, depth, tid, t} (begin wall stamp).
    Handles both forms the recorder emits: complete ``span`` events
    (hot-path) and ``span_begin``/``span_end`` pairs (blocking regions)."""
    open_spans: Dict[int, dict] = {}
    out: List[dict] = []
    for ev in events:
        kind = ev.get("kind")
        if kind == "span":
            out.append({"name": ev.get("name", "?"),
                        "dur_ms": float(ev.get("dur_ms", 0.0)),
                        "depth": int(ev.get("depth", 0)),
                        "tid": ev.get("tid"),
                        "t": float(ev.get("t", 0.0))})
        elif kind == "span_begin" and "span" in ev:
            open_spans[ev["span"]] = ev
        elif kind == "span_end" and ev.get("span") in open_spans:
            begin = open_spans.pop(ev["span"])
            out.append({"name": ev.get("name", "?"),
                        "dur_ms": float(ev.get("dur_ms", 0.0)),
                        "depth": int(begin.get("depth", 0)),
                        "tid": begin.get("tid"),
                        "t": float(begin.get("t", 0.0))})
    return out


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------
def _resize_stamps(events: List[dict]) -> List[float]:
    """Wall stamps of ``resize`` events (elastic gang resize — a new
    incarnation at a different world size, recorded by
    ``parallel/dist.py`` at the post-resize rendezvous)."""
    return sorted(float(e["t"]) for e in events
                  if e.get("kind") == "resize" and "t" in e)


def _rank_stats(events: List[dict], window: int) -> dict:
    # an elastic resize restarts the process, re-rendezvouses and
    # RECOMPILES every executable: the teardown silence and the fresh
    # compile wall belong to the resize, not to this rank's behavior.
    # Skew/idle accounting therefore runs on the NEWEST segment only
    # (events after the last resize) — without this, every survivor of a
    # resize reads as an idle-gap straggler against a rank that died
    # before it.
    resizes = _resize_stamps(events)
    n_resizes = len(resizes)
    if resizes:
        cut = resizes[-1]
        events = [e for e in events
                  if e.get("kind") == "resize"
                  or float(e.get("t", cut)) >= cut]
    steps = [e for e in events if e.get("kind") == "step"]
    steady = [e for e in steps if not e.get("traced")]
    compile_ = [e for e in steps if e.get("traced")]
    spans = _pair_spans(events)
    top_level = [s for s in spans if s["depth"] == 0]
    # idle-gap accounting runs on the BUSIEST thread only: checkpoint
    # writer / prefetcher threads overlap the training thread, and summing
    # across threads would count the same wall twice
    by_tid: Dict[object, float] = {}
    for s in top_level:
        by_tid[s["tid"]] = by_tid.get(s["tid"], 0.0) + s["dur_ms"]
    main_tid = max(by_tid, key=by_tid.get) if by_tid else None
    span_account_ms = by_tid.get(main_tid, 0.0)
    step_wall_ms = sum(float(e.get("wall_ms", 0.0)) for e in steps)
    # span coverage and step walls OVERLAP (a DataParallelStep stream's
    # train_step spans contain the step walls; a Trainer stream's step
    # walls contain its push_bucketed/fused_apply spans), so summing them
    # would double-count busy time, clamp idle_gap to 0 everywhere, and
    # blind the straggler rule.  max() of the two is a lower bound on
    # accounted busy time that never double-counts — and also covers the
    # edge where the busiest span thread is a checkpoint writer rather
    # than the training loop.
    accounted_ms = max(span_account_ms, step_wall_ms)
    # idle-gap accounting runs over the TRAINING window (first step/span
    # event -> last event): rendezvous/compile slack before training is
    # shared by every rank and would only dilute the skew percentage
    work_kinds = ("step", "span", "span_begin", "span_end")
    work_stamps = [float(e["t"]) for e in events
                   if e.get("kind") in work_kinds and "t" in e]
    stamps = [float(e.get("t", 0.0)) for e in events
              if e.get("kind") != "clock_anchor" and "t" in e]
    if work_stamps and stamps:
        run_span_ms = (max(stamps) - min(work_stamps)) * 1e3
    elif len(stamps) > 1:
        run_span_ms = (max(stamps) - min(stamps)) * 1e3
    else:
        run_span_ms = 0.0
    win = steady[-window:] if window > 0 else steady
    win_walls = [float(e.get("wall_ms", 0.0)) for e in win]
    span_ms: Dict[str, Dict[str, float]] = {}
    for s in spans:
        agg = span_ms.setdefault(s["name"],
                                 {"count": 0, "total_ms": 0.0, "max_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += s["dur_ms"]
        agg["max_ms"] = max(agg["max_ms"], s["dur_ms"])
    return {
        "resizes": n_resizes,
        "steps": len(steps),
        "steady_steps": len(steady),
        "compile_steps": len(compile_),
        "compile_ms": round(sum(float(e.get("wall_ms", 0.0))
                                for e in compile_), 3),
        "steady_wall_ms": round(sum(float(e.get("wall_ms", 0.0))
                                    for e in steady), 3),
        "mean_steady_ms": round(
            sum(float(e.get("wall_ms", 0.0)) for e in steady)
            / len(steady), 3) if steady else None,
        "window_steps": len(win),
        "window_mean_ms": (round(sum(win_walls) / len(win_walls), 3)
                           if win_walls else None),
        "block_wait_ms": round(sum(float(e.get("block_wait_ms", 0.0))
                                   for e in steps), 3),
        "transfer_bytes": sum(int(e.get("transfer_bytes", 0))
                              for e in steps),
        "h2d_overlapped_bytes": sum(int(e.get("h2d_overlapped", 0))
                                    for e in steps),
        "run_span_ms": round(run_span_ms, 3),
        "accounted_ms": round(accounted_ms, 3),
        "idle_gap_ms": round(max(0.0, run_span_ms - accounted_ms), 3),
        # serving-mode streams (router/replica processes) break both
        # straggler rules' assumptions: the driver thread blocks in
        # request polls while HTTP handler threads do the work, so the
        # busiest-thread idle-gap math reads wait time as unaccounted
        # skew, and there are no steady step walls at all.  Flagged
        # here so build_report can exclude them and defer request-level
        # analysis to tools/serve_report.py.
        "serving_mode": any(
            str(e.get("kind", "")).startswith("serve_")
            or (e.get("kind") in ("span", "span_begin")
                and str(e.get("name", "")).startswith("serve_"))
            for e in events),
        "spans": {k: {"count": v["count"],
                      "total_ms": round(v["total_ms"], 3),
                      "max_ms": round(v["max_ms"], 3)}
                  for k, v in sorted(span_ms.items())},
    }


def _collective_table(ranks: Dict[int, List[dict]]) -> List[dict]:
    rows: List[dict] = []
    for rank, events in sorted(ranks.items()):
        per_op: Dict[str, dict] = {}
        for e in events:
            if e.get("kind") != "collective":
                continue
            op = str(e.get("op", "?"))
            row = per_op.setdefault(op, {"count": 0, "bytes": 0,
                                         "wall_ms": 0.0, "compile": 0})
            row["count"] += 1
            if e.get("traced"):
                row["compile"] += 1  # first-use compile: not bandwidth
            else:
                row["bytes"] += int(e.get("nbytes", 0))
                row["wall_ms"] += float(e.get("wall_ms", 0.0))
        for op, row in sorted(per_op.items()):
            mbps = (row["bytes"] / 1e6 / (row["wall_ms"] / 1e3)
                    if row["wall_ms"] > 0 else 0.0)
            rows.append({"rank": rank, "op": op, "count": row["count"],
                         "compile_calls": row["compile"],
                         "bytes": row["bytes"],
                         "wall_ms": round(row["wall_ms"], 3),
                         "mb_per_sec": round(mbps, 2)})
    return rows


def _retrace_table(ranks: Dict[int, List[dict]]) -> List[dict]:
    rows = []
    for rank, events in sorted(ranks.items()):
        for e in events:
            if e.get("kind") == "retrace":
                rows.append({"rank": rank,
                             "executor": e.get("executor", "?"),
                             "traces": int(e.get("traces", 0)),
                             "signature": str(e.get("signature", ""))[:200]})
    return rows


def _event_gaps(ranks: Dict[int, List[dict]], gap_sec: float) -> List[dict]:
    """Stretches of stream silence longer than gap_sec, per rank.  A gap
    containing a ``resize`` stamp is the gang teardown + re-rendezvous of
    an elastic resize — planned dead time, not a hung rank."""
    rows = []
    for rank, events in sorted(ranks.items()):
        resizes = _resize_stamps(events)
        stamps = sorted(float(e["t"]) for e in events
                        if "t" in e and e.get("kind") != "clock_anchor")
        for prev, cur in zip(stamps, stamps[1:]):
            if cur - prev > gap_sec:
                if any(prev < s <= cur for s in resizes):
                    continue
                rows.append({"rank": rank, "at": round(prev, 3),
                             "gap_sec": round(cur - prev, 3)})
    return rows


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an ascending list (stdlib-only, the
    same estimator telemetry.py uses for its rolling rollups)."""
    if not sorted_vals:
        return 0.0
    import math

    idx = min(len(sorted_vals) - 1,
              max(0, int(math.ceil(q / 100.0 * len(sorted_vals))) - 1))
    return sorted_vals[idx]


# cap on per-request rows carried in the report object: a million-request
# serving log must not turn --json into a gigabyte; the aggregate
# percentiles cover the full population either way (log()-style note in
# the section itself records the truncation)
MAX_REQUEST_ROWS = 200


def _serving_section(ranks: Dict[int, List[dict]]) -> Optional[dict]:
    """Per-request serving breakdown from ``serve_request`` /
    ``serve_preempt`` / ``serve_slo_violation`` events plus the
    ``serve_stream`` spans' occupancy gauges (docs/OBSERVABILITY.md
    §Serving traces).  None when the gang never served."""
    requests: List[dict] = []
    preempts: Dict[str, int] = {}
    slo: Dict[str, int] = {"ttft": 0, "tpot": 0}
    occupancy: List[dict] = []
    for rank, events in sorted(ranks.items()):
        for e in events:
            kind = e.get("kind")
            if kind == "serve_request":
                requests.append({
                    "rank": rank,
                    "id": str(e.get("request_id", "?")),
                    "queue_ms": round(float(e.get("queue_wait_ms", 0.0)), 3),
                    "prefill_ms": round(float(e.get("prefill_ms", 0.0)), 3),
                    "decode_ms": round(float(e.get("decode_ms", 0.0)), 3),
                    "latency_ms": round(float(e.get("latency_ms", 0.0)), 3),
                    "ttft_ms": round(float(e.get("ttft_ms", 0.0)), 3),
                    "tokens": int(e.get("tokens", 0)),
                    "reason": e.get("reason"),
                })
            elif kind == "serve_preempt":
                rid = str(e.get("request_id", "?"))
                preempts[rid] = preempts.get(rid, 0) + 1
            elif kind == "serve_slo_violation":
                stage = str(e.get("stage", "?"))
                slo[stage] = slo.get(stage, 0) + 1
            elif kind == "span" and e.get("name") == "serve_stream":
                occupancy.append({
                    "t": round(float(e.get("t", 0.0)), 3),
                    "rank": rank,
                    "active_slots": int(e.get("active_slots", 0)),
                    "queue_depth": int(e.get("queue_depth", 0)),
                })
    if not requests and not occupancy and not preempts:
        return None
    ttfts = sorted(r["ttft_ms"] for r in requests if r["ttft_ms"] > 0)
    lats = sorted(r["latency_ms"] for r in requests)
    occupancy.sort(key=lambda row: row["t"])
    slots = [row["active_slots"] for row in occupancy]
    out = {
        "requests": len(requests),
        "tokens": sum(r["tokens"] for r in requests),
        "ttft_p50_ms": round(_percentile(ttfts, 50), 3),
        "ttft_p99_ms": round(_percentile(ttfts, 99), 3),
        "latency_p50_ms": round(_percentile(lats, 50), 3),
        "latency_p99_ms": round(_percentile(lats, 99), 3),
        "preemptions": sum(preempts.values()),
        "preempted_requests": preempts,
        "slo_violations": slo,
        "per_request": requests[:MAX_REQUEST_ROWS],
        "per_request_truncated": max(0, len(requests) - MAX_REQUEST_ROWS),
        "slot_occupancy": {
            "samples": len(occupancy),
            "mean_active_slots": (round(sum(slots) / len(slots), 3)
                                  if slots else 0.0),
            "max_active_slots": max(slots) if slots else 0,
            "max_queue_depth": max((row["queue_depth"]
                                    for row in occupancy), default=0),
            # burst-cadence timeline (newest MAX_REQUEST_ROWS points):
            # active slots + queue depth per stream boundary
            "timeline": occupancy[-MAX_REQUEST_ROWS:],
        },
    }
    return out


def _find_stragglers(per_rank: Dict[int, dict], pct: float) -> List[dict]:
    flagged: List[dict] = []
    if len(per_rank) < 2:
        return flagged
    # rule 1: idle-gap skew — checked FIRST because sync training INVERTS
    # the naive wall reading: the victim ranks' step walls balloon (they
    # wait for the straggler inside their dispatch/collectives) while the
    # straggler's own wall stays small.  A rank whose unaccounted host
    # time towers over the others' is the one everyone waited for, and
    # once that's established the wall skew is explained (victim waiting)
    # and must not be double-reported against the victims.
    idles = {r: s["idle_gap_ms"] for r, s in per_rank.items()
             if s["run_span_ms"] > 0}
    if len(idles) >= 2:
        base = min(idles.values())
        # skew % is measured against the STEADY portion of the run:
        # compile wall is recorded, shared by every rank, and often
        # rivals the whole steady phase on cold caches — leaving it in
        # the denominator dilutes a real straggler below threshold
        span = max(s["run_span_ms"] - s["compile_ms"]
                   for s in per_rank.values())
        for r, idle in sorted(idles.items()):
            excess = idle - base
            if span > 0 and excess / span * 100.0 > pct and excess > 100.0:
                flagged.append({
                    "rank": r, "rule": "idle-gap",
                    "detail": f"{idle:.0f}ms unaccounted host time vs "
                              f"{base:.0f}ms on the best rank "
                              f"({excess / span * 100:.0f}% of the "
                              "steady run span) — peers were waiting on "
                              "this rank inside recorded waits"})
    if flagged:
        return flagged
    # rule 2: step-wall skew over the sliding window — the non-lockstep
    # shape (independent cadences, no collective coupling): a rank whose
    # own recorded step wall is genuinely slower is the straggler.
    means = {r: s["window_mean_ms"] for r, s in per_rank.items()
             if s["window_mean_ms"] is not None and s["window_steps"] >= 3}
    if len(means) >= 2:
        fastest = min(means.values())
        slowest = max(means.values())
        if fastest > 0 and (slowest - fastest) / fastest * 100.0 > pct:
            for r, m in sorted(means.items()):
                if (m - fastest) / fastest * 100.0 > pct:
                    flagged.append({
                        "rank": r, "rule": "step-wall",
                        "detail": f"window mean {m:.2f}ms vs fastest "
                                  f"{fastest:.2f}ms "
                                  f"(+{(m - fastest) / fastest * 100:.0f}%)"})
    return flagged


def build_report(directory: str, window: Optional[int] = None,
                 straggler_pct: Optional[float] = None,
                 gap_sec: Optional[float] = None) -> dict:
    """The full gang report object (what ``--json`` prints)."""
    # None means "not given" — an explicit 0 must survive to _rank_stats,
    # whose window<=0 branch means "all steady steps"
    if window is None:
        window = int(_env_float("MX_TRACE_WINDOW", DEFAULT_WINDOW))
    pct = (straggler_pct if straggler_pct is not None
           else _env_float("MX_TRACE_STRAGGLER_PCT", DEFAULT_STRAGGLER_PCT))
    gap_sec = (gap_sec if gap_sec is not None
               else _env_float("MX_TRACE_HEARTBEAT_GAP_SEC", DEFAULT_GAP_SEC))
    ranks, warnings = load_gang(directory)
    warnings = list(warnings)
    per_rank = {r: _rank_stats(events, window)
                for r, events in ranks.items()}
    # gang-wide phase breakdown: where a steady step's time goes
    phase_names = ("input_stage", "dispatch", "block_wait", "loss_wait")
    phases = {}
    steady_total = sum(s["steady_steps"] for s in per_rank.values())
    for name in phase_names:
        tot = sum(s["spans"].get(name, {}).get("total_ms", 0.0)
                  for s in per_rank.values())
        cnt = sum(s["spans"].get(name, {}).get("count", 0)
                  for s in per_rank.values())
        if cnt:
            phases[name] = {"count": cnt, "total_ms": round(tot, 3),
                            "mean_ms": round(tot / cnt, 3)}
    # serving streams confuse both straggler rules (driver thread
    # blocks while HTTP threads serve; no step cadence): exclude them
    # from the skew math and point at serve_report, which reconstructs
    # per-request trees instead of per-step walls
    serving_ranks = sorted(r for r, s in per_rank.items()
                           if s.get("serving_mode"))
    stragglers = _find_stragglers(
        {r: s for r, s in per_rank.items()
         if not s.get("serving_mode")}, pct)
    if serving_ranks:
        warnings.append(
            f"rank(s) {serving_ranks} are serving-mode streams — "
            "excluded from straggler rules; run tools/serve_report.py "
            "for request-level analysis")
    retraces = _retrace_table(ranks)
    gaps = _event_gaps(ranks, gap_sec)
    resizes = []
    for r, events in sorted(ranks.items()):
        for e in events:
            if e.get("kind") == "resize":
                resizes.append({"rank": r,
                                "old_world": e.get("old_world"),
                                "new_world": e.get("new_world"),
                                "at": round(float(e.get("t", 0.0)), 3)})
    anomalies = []
    for s in stragglers:
        anomalies.append(f"straggler: rank {s['rank']} ({s['rule']}): "
                         f"{s['detail']}")
    for row in retraces:
        anomalies.append(f"retrace storm: rank {row['rank']} "
                         f"{row['executor']} traced {row['traces']} "
                         "distinct signatures")
    for row in gaps:
        anomalies.append(f"event gap: rank {row['rank']} silent for "
                         f"{row['gap_sec']:.1f}s (> {gap_sec:.0f}s) at "
                         f"t={row['at']}")
    return {
        "dir": os.path.abspath(directory),
        "num_ranks": len(ranks),
        "window": window,
        "straggler_pct": pct,
        "gap_sec": gap_sec,
        "per_rank": {str(r): s for r, s in sorted(per_rank.items())},
        "step_phases": phases,
        "steady_steps_total": steady_total,
        "compile_steps_total": sum(s["compile_steps"]
                                   for s in per_rank.values()),
        "compile_ms_total": round(sum(s["compile_ms"]
                                      for s in per_rank.values()), 3),
        "collectives": _collective_table(ranks),
        "serving": _serving_section(ranks),
        "serving_ranks": serving_ranks,
        "retraces": retraces,
        "resizes": resizes,
        "event_gaps": gaps,
        "stragglers": stragglers,
        "warnings": warnings,
        "anomalies": anomalies,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------
def _fmt_bytes(n: int) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{n}B"
        n /= 1024.0
    return f"{n}B"


def format_text(rep: dict) -> str:
    out: List[str] = []
    w = out.append
    w(f"gang trace report — {rep['dir']} "
      f"({rep['num_ranks']} rank(s), window={rep['window']})")
    for warn in rep["warnings"]:
        w(f"  WARNING: {warn}")
    w("")
    w("per-step breakdown")
    w(f"  compile: {rep['compile_steps_total']} step(s), "
      f"{rep['compile_ms_total']:.0f}ms   steady: "
      f"{rep['steady_steps_total']} step(s)")
    for name, ph in rep["step_phases"].items():
        w(f"  {name:<12} mean {ph['mean_ms']:8.3f}ms   "
          f"total {ph['total_ms']:10.1f}ms   n={ph['count']}")
    w("")
    for row in rep.get("resizes", []):
        w(f"  elastic resize: rank {row['rank']} rejoined at world size "
          f"{row['new_world']} (was {row['old_world']}) — skew/idle stats "
          "below cover the post-resize segment only")
    if rep.get("resizes"):
        w("")
    w("per-rank skew")
    w(f"  {'rank':>4} {'steps':>6} {'win mean ms':>12} {'block ms':>10} "
      f"{'idle gap ms':>12} {'h2d':>10} straggler")
    flagged = {s["rank"]: s for s in rep["stragglers"]}
    for r, s in rep["per_rank"].items():
        mark = ""
        if int(r) in flagged:
            mark = f"<-- {flagged[int(r)]['rule']}"
        wm = (f"{s['window_mean_ms']:.3f}"
              if s["window_mean_ms"] is not None else "-")
        w(f"  {r:>4} {s['steady_steps']:>6} {wm:>12} "
          f"{s['block_wait_ms']:>10.1f} {s['idle_gap_ms']:>12.1f} "
          f"{_fmt_bytes(s['transfer_bytes']):>10} {mark}")
    for s in rep["stragglers"]:
        w(f"  rank {s['rank']} [{s['rule']}]: {s['detail']}")
    w("")
    if rep["collectives"]:
        w("collective bandwidth")
        w(f"  {'rank':>4} {'op':<20} {'n':>5} {'bytes':>10} "
          f"{'wall ms':>10} {'MB/s':>9}")
        for row in rep["collectives"]:
            w(f"  {row['rank']:>4} {row['op']:<20} {row['count']:>5} "
              f"{_fmt_bytes(row['bytes']):>10} {row['wall_ms']:>10.1f} "
              f"{row['mb_per_sec']:>9.1f}")
        w("")
    srv = rep.get("serving")
    if srv:
        w("serving")
        w(f"  {srv['requests']} request(s), {srv['tokens']} token(s); "
          f"TTFT p50 {srv['ttft_p50_ms']:.1f}ms p99 "
          f"{srv['ttft_p99_ms']:.1f}ms; latency p50 "
          f"{srv['latency_p50_ms']:.1f}ms p99 "
          f"{srv['latency_p99_ms']:.1f}ms")
        occ = srv["slot_occupancy"]
        w(f"  slot occupancy: mean {occ['mean_active_slots']:.2f} / max "
          f"{occ['max_active_slots']} active over {occ['samples']} stream "
          f"boundaries; max queue depth {occ['max_queue_depth']}")
        if srv["preemptions"]:
            w(f"  {srv['preemptions']} preemption(s): " + ", ".join(
                f"{rid} x{n}" for rid, n in
                sorted(srv["preempted_requests"].items())))
        viol = {k: v for k, v in srv["slo_violations"].items() if v}
        if viol:
            w("  SLO violations: " + ", ".join(
                f"{k}={v}" for k, v in sorted(viol.items())))
        w(f"  {'id':<12} {'queue ms':>9} {'prefill ms':>11} "
          f"{'decode ms':>10} {'ttft ms':>8} {'tok':>4} reason")
        for r in srv["per_request"][:20]:
            w(f"  {r['id']:<12} {r['queue_ms']:>9.1f} "
              f"{r['prefill_ms']:>11.1f} {r['decode_ms']:>10.1f} "
              f"{r['ttft_ms']:>8.1f} {r['tokens']:>4} {r['reason']}")
        if len(srv["per_request"]) > 20 or srv["per_request_truncated"]:
            hidden = (len(srv["per_request"]) - 20
                      + srv["per_request_truncated"])
            w(f"  ... {hidden} more request(s) (--json carries "
              f"{MAX_REQUEST_ROWS})")
        w("")
    if rep["retraces"]:
        w("retrace attribution")
        for row in rep["retraces"]:
            w(f"  rank {row['rank']} {row['executor']}: "
              f"{row['traces']} distinct signatures; newest: "
              f"{row['signature']}")
        w("")
    if rep["event_gaps"]:
        w("heartbeat/event gaps")
        for row in rep["event_gaps"]:
            w(f"  rank {row['rank']}: silent {row['gap_sec']:.1f}s "
              f"starting t={row['at']}")
        w("")
    if rep["anomalies"]:
        w(f"ANOMALIES ({len(rep['anomalies'])}):")
        for a in rep["anomalies"]:
            w(f"  - {a}")
    else:
        w("no anomalies detected")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge per-rank telemetry JSONL into a gang-wide "
                    "report (straggler hunting, step breakdown, "
                    "collective bandwidth).")
    ap.add_argument("directory", help="MX_TELEMETRY_DIR of the run")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report object")
    ap.add_argument("--window", type=int, default=None, metavar="N",
                    help="sliding window of newest steady steps for the "
                         "skew table; 0 = all steady steps (default: "
                         f"MX_TRACE_WINDOW or {DEFAULT_WINDOW})")
    ap.add_argument("--straggler-pct", type=float, default=None, metavar="P",
                    help="flag a rank slower/idler than the best by more "
                         "than P%% (default: MX_TRACE_STRAGGLER_PCT or "
                         f"{DEFAULT_STRAGGLER_PCT})")
    ap.add_argument("--heartbeat-gap", type=float, default=None, metavar="S",
                    help="flag event-stream silences longer than S seconds "
                         "(default: MX_TRACE_HEARTBEAT_GAP_SEC or "
                         f"{DEFAULT_GAP_SEC})")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.directory):
        print(f"trace_report: {args.directory} is not a directory",
              file=sys.stderr)
        return 2
    rep = build_report(args.directory, window=args.window,
                       straggler_pct=args.straggler_pct,
                       gap_sec=args.heartbeat_gap)
    if rep["num_ranks"] == 0:
        print(f"trace_report: no rank-*.jsonl streams under "
              f"{args.directory}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(rep))
    else:
        print(format_text(rep))
    return 3 if rep["anomalies"] else 0


if __name__ == "__main__":
    sys.exit(main())
