#!/usr/bin/env python
"""Gang-wide memory & compile-cost report over per-rank telemetry JSONL
streams (docs/OBSERVABILITY.md §Memory).

``mxnet_tpu/memwatch.py`` records ``mem`` samples (per-device watermarks +
categorized live-array census), ``mem_leak`` warnings, per-executable
``compile`` cost events, and ``oom_report`` post-mortems into the same
``rank-<R>.jsonl`` streams PR 2/5 established; this CLI merges them into
the after-the-run questions:

  * **per-rank watermark / category table** — peak bytes per rank, the
    last census split by category (params / optimizer / inflight /
    checkpoint / other), and each category's own high-water mark;
  * **leak-trend verdict** — the trailing-window monotonic-growth check
    re-run offline over each rank's samples (same rule as the in-process
    detector: strictly increasing totals across the window above a noise
    floor), plus any ``mem_leak`` events the run recorded live.  Verdict
    per rank: ``leak`` / ``clean`` / ``no-data``;
  * **executable cost table** — one row per ``compile`` event: executor,
    stable fingerprint (the AOT-cache key), compile wall, FLOPs,
    argument/output/temp bytes where the run captured them, and the
    AOT-cache disposition — entries the run DESERIALIZED from the
    persistent executable cache (``MX_EXECUTABLE_CACHE_DIR``) are marked
    ``hit`` with their deserialize wall, so a post-mortem distinguishes
    "loaded in 0.2s" from "compiled in 40s";
  * **OOM post-mortems** — any ``oom_report`` echoed verbatim (largest
    category, watermark, in-flight depth, top executables).

Exit code: 0 clean, 2 usage/IO error (no rank streams), 3 when anomalies
were flagged (a leak verdict or an OOM) — CI and the launch.py
supervisor can key off it, mirroring ``trace_report.py``.  ``--json``
emits the full report object.

Importable WITHOUT jax/mxnet_tpu (stdlib only), like trace_report.py:
the JSONL schema knowledge is shared with ``mxnet_tpu/memwatch.py`` —
keep the two in sync.  The leak window falls back to the same
``MX_MEMWATCH_LEAK_WINDOW`` knob the in-process detector reads.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Dict, List, Optional

__all__ = ["load_gang", "build_report", "format_text", "main"]

DEFAULT_LEAK_WINDOW = 12
# same noise floor as memwatch._LEAK_MIN_GROWTH: strictly-increasing
# growth below this across the whole window is allocator jitter
LEAK_MIN_GROWTH = 1 << 16


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except (TypeError, ValueError):
        return default


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_gang(directory: str) -> Dict[int, List[dict]]:
    """{rank: [events...]} for every rank-<R>.jsonl under ``directory``
    (torn lines skipped, like trace_report)."""
    ranks: Dict[int, List[dict]] = {}
    try:
        names = sorted(os.listdir(directory))
    except OSError as e:
        raise SystemExit(f"mem_report: cannot read {directory}: {e}")
    for name in names:
        if not (name.startswith("rank-") and name.endswith(".jsonl")):
            continue
        try:
            rank = int(name[len("rank-"):-len(".jsonl")])
        except ValueError:
            continue
        events: List[dict] = []
        with open(os.path.join(directory, name), errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue  # torn final line of a SIGKILLed rank
                if isinstance(ev, dict) and "kind" in ev:
                    events.append(ev)
        ranks[rank] = events
    return ranks


def _cat_bytes(ev: dict) -> Dict[str, int]:
    out = {}
    for cat, row in (ev.get("categories") or {}).items():
        out[cat] = int(row.get("nbytes", 0)) if isinstance(row, dict) \
            else int(row)
    return out


# ---------------------------------------------------------------------------
# analysis
# ---------------------------------------------------------------------------
def _leak_verdict(mems: List[dict], window: int) -> dict:
    """Offline re-run of the in-process trend rule over the TRAILING
    window of samples: strictly monotonic growth of the live total above
    the noise floor = leak; fewer samples than the window = no-data."""
    if len(mems) < window:
        return {"verdict": "no-data", "samples": len(mems),
                "window": window}
    tail = mems[-window:]
    totals = [int(e.get("live_bytes", 0)) for e in tail]
    growing = all(b > a for a, b in zip(totals, totals[1:]))
    growth = totals[-1] - totals[0]
    if growing and growth > LEAK_MIN_GROWTH:
        first, last = _cat_bytes(tail[0]), _cat_bytes(tail[-1])
        deltas = {c: last.get(c, 0) - first.get(c, 0)
                  for c in set(first) | set(last)}
        top = max(deltas, key=deltas.get) if deltas else "other"
        return {"verdict": "leak", "samples": len(mems), "window": window,
                "growth_bytes": growth, "category": top,
                "category_growth_bytes": deltas.get(top, 0)}
    return {"verdict": "clean", "samples": len(mems), "window": window,
            "growth_bytes": growth}


def _rank_mem(events: List[dict], window: int) -> dict:
    mems = [e for e in events if e.get("kind") == "mem"]
    leaks = [e for e in events if e.get("kind") == "mem_leak"]
    watermark = max((int(e.get("watermark_bytes", 0)) for e in mems),
                    default=0)
    peak_cats: Dict[str, int] = {}
    for e in mems:
        for cat, nb in _cat_bytes(e).items():
            peak_cats[cat] = max(peak_cats.get(cat, 0), nb)
    last = mems[-1] if mems else {}
    # an elastic resize restarts the process: a verdict window spanning
    # the boundary mixes two allocator lifetimes, and the fresh
    # incarnation's normal ramp-up (params placed, caches warming) reads
    # as monotonic "leak" growth.  The trend rule runs on the newest
    # segment only; watermark/peaks above stay whole-stream.
    resize_stamps = [float(e["t"]) for e in events
                     if e.get("kind") == "resize" and "t" in e]
    trend_mems = mems
    if resize_stamps:
        cut = max(resize_stamps)
        trend_mems = [e for e in mems if float(e.get("t", cut)) >= cut]
    verdict = _leak_verdict(trend_mems, window)
    if leaks and verdict["verdict"] != "leak":
        # the live detector fired mid-run even if the trailing window
        # has since flattened (e.g. the leak crashed the run) — a
        # recorded leak is a leak
        verdict = dict(verdict, verdict="leak",
                       category=leaks[-1].get("category"),
                       growth_bytes=leaks[-1].get("growth_bytes", 0))
    return {
        "samples": len(mems),
        "watermark_bytes": watermark,
        "live_bytes_last": int(last.get("live_bytes", 0)),
        "categories_last": _cat_bytes(last),
        "peak_category_bytes": peak_cats,
        "host_bytes_last": last.get("host_bytes", {}),
        "bytes_in_use_last": last.get("bytes_in_use"),
        "bytes_limit": last.get("bytes_limit"),
        "leak": verdict,
        "recorded_leak_events": len(leaks),
    }


def _executables(ranks: Dict[int, List[dict]]) -> List[dict]:
    rows = []
    seen = set()
    for rank, events in sorted(ranks.items()):
        for e in events:
            if e.get("kind") != "compile":
                continue
            key = (rank, e.get("executor"), e.get("fingerprint"))
            if key in seen:
                continue
            seen.add(key)
            rows.append({
                "rank": rank,
                "executor": e.get("executor", "?"),
                "fingerprint": e.get("fingerprint", "?"),
                "site": e.get("site", ""),
                "wall_ms": float(e.get("wall_ms", 0.0)),
                "flops": e.get("flops"),
                "bytes_accessed": e.get("bytes_accessed"),
                "arg_bytes": e.get("arg_bytes"),
                "out_bytes": e.get("out_bytes"),
                "temp_bytes": e.get("temp_bytes"),
                "cache_hit": bool(e.get("cache_hit", False)),
                "deserialize_ms": e.get("deserialize_ms"),
            })
    rows.sort(key=lambda r: (-(r["temp_bytes"] or 0),
                             -(r["bytes_accessed"] or 0), -r["wall_ms"]))
    return rows


def build_report(directory: str, window: Optional[int] = None) -> dict:
    if window is None:
        window = _env_int("MX_MEMWATCH_LEAK_WINDOW", DEFAULT_LEAK_WINDOW)
    # clamp user input too: --window 0 must not slice mems[-0:] = the
    # whole stream while claiming a zero-sample window
    window = max(2, window)
    ranks = load_gang(directory)
    per_rank = {r: _rank_mem(events, window)
                for r, events in ranks.items()}
    ooms = []
    for rank, events in sorted(ranks.items()):
        for e in events:
            if e.get("kind") == "oom_report":
                ooms.append(dict(e, rank=rank))
    anomalies = []
    for r, s in sorted(per_rank.items()):
        if s["leak"]["verdict"] == "leak":
            anomalies.append(
                f"leak: rank {r} live bytes grew monotonically "
                f"(+{s['leak'].get('growth_bytes', 0)}B over the last "
                f"{s['leak']['window']} samples); top-growing category: "
                f"{s['leak'].get('category')}")
    for e in ooms:
        anomalies.append(
            f"oom: rank {e['rank']} RESOURCE_EXHAUSTED at step "
            f"{e.get('step')}; largest live-array category: "
            f"{e.get('largest_category')}")
    return {
        "dir": os.path.abspath(directory),
        "num_ranks": len(ranks),
        "window": window,
        "per_rank": {str(r): s for r, s in sorted(per_rank.items())},
        "executables": _executables(ranks),
        "ooms": ooms,
        "anomalies": anomalies,
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------
def _fmt_bytes(n) -> str:
    if n is None:
        return "-"
    n = float(n)
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n}B"


def format_text(rep: dict) -> str:
    out: List[str] = []
    w = out.append
    w(f"gang memory report — {rep['dir']} ({rep['num_ranks']} rank(s), "
      f"leak window={rep['window']})")
    w("")
    w("per-rank watermarks & categories")
    w(f"  {'rank':>4} {'samples':>8} {'watermark':>11} {'live now':>10} "
      f"{'leak':>8}  categories (last sample)")
    for r, s in rep["per_rank"].items():
        cats = "  ".join(f"{c}={_fmt_bytes(b)}"
                         for c, b in sorted(s["categories_last"].items()))
        w(f"  {r:>4} {s['samples']:>8} "
          f"{_fmt_bytes(s['watermark_bytes']):>11} "
          f"{_fmt_bytes(s['live_bytes_last']):>10} "
          f"{s['leak']['verdict']:>8}  {cats}")
        if s["leak"]["verdict"] == "leak":
            w(f"       leak: +{_fmt_bytes(s['leak'].get('growth_bytes'))} "
              f"over {s['leak']['window']} samples; top-growing "
              f"category: {s['leak'].get('category')}")
        if s["host_bytes_last"]:
            hb = "  ".join(f"{c}={_fmt_bytes(b)}"
                           for c, b in sorted(s["host_bytes_last"].items()))
            w(f"       host buffers: {hb}")
    w("")
    if rep["executables"]:
        w("executable cost table (compile events)")
        w(f"  {'rank':>4} {'executor':<34} {'fingerprint':<17} "
          f"{'wall ms':>9} {'flops':>12} {'args':>9} {'out':>9} "
          f"{'temp':>9} {'aot':>12}")
        for row in rep["executables"]:
            flops = (f"{row['flops']:.3g}" if row["flops"] is not None
                     else "-")
            # "hit(0.2s)" = deserialized from the persistent AOT cache,
            # never compiled in this process; "-" = compiled fresh
            if row["cache_hit"]:
                des = row.get("deserialize_ms")
                aot = (f"hit({des / 1e3:.1f}s)" if des is not None
                       else "hit")
            else:
                aot = "-"
            w(f"  {row['rank']:>4} {row['executor']:<34.34} "
              f"{row['fingerprint']:<17} {row['wall_ms']:>9.1f} "
              f"{flops:>12} {_fmt_bytes(row['arg_bytes']):>9} "
              f"{_fmt_bytes(row['out_bytes']):>9} "
              f"{_fmt_bytes(row['temp_bytes']):>9} {aot:>12}")
        w("")
    for e in rep["ooms"]:
        w(f"OOM post-mortem: rank {e['rank']} step {e.get('step')}: "
          f"largest category {e.get('largest_category')} "
          f"({_fmt_bytes((e.get('categories') or {}).get(e.get('largest_category'), 0))}); "
          f"watermark {_fmt_bytes(e.get('watermark_bytes'))}; "
          f"inflight depth {e.get('inflight_depth')}")
    if rep["ooms"]:
        w("")
    if rep["anomalies"]:
        w(f"ANOMALIES ({len(rep['anomalies'])}):")
        for a in rep["anomalies"]:
            w(f"  - {a}")
    else:
        w("no anomalies detected")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Merge per-rank telemetry JSONL into a gang-wide "
                    "memory report (watermarks, category census, leak "
                    "verdicts, executable cost table, OOM post-mortems).")
    ap.add_argument("directory", help="MX_TELEMETRY_DIR of the run")
    ap.add_argument("--json", action="store_true",
                    help="emit the machine-readable report object")
    ap.add_argument("--window", type=int, default=None, metavar="N",
                    help="trailing-sample window for the leak verdict "
                         "(default: MX_MEMWATCH_LEAK_WINDOW or "
                         f"{DEFAULT_LEAK_WINDOW})")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.directory):
        print(f"mem_report: {args.directory} is not a directory",
              file=sys.stderr)
        return 2
    rep = build_report(args.directory, window=args.window)
    if rep["num_ranks"] == 0:
        print(f"mem_report: no rank-*.jsonl streams under "
              f"{args.directory}", file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(rep))
    else:
        print(format_text(rep))
    return 3 if rep["anomalies"] else 0


if __name__ == "__main__":
    sys.exit(main())
