#!/usr/bin/env python
"""One-shot on-chip measurement suite (r4; r5: shares tools/_runner.TASKS).

NOTE (r5): when the relay is only intermittently alive, prefer
`tools/relay_watch.py` — it probes in a loop, runs the same canonical
task list, and re-probes between steps.  This suite remains the one-shot
batch for a relay that is actually up.

Runs every TPU-dependent measurement the r3 verdict asked for — the
canonical task table lives in tools/_runner.py (headline bench, TPU
profile+HLO, BERT tokens/sec with no-fusion fallback, batch/layout
ablations, dispatch timing, e2e input pipeline, transformer tokens/sec,
434-case consistency oracle) — each step in a subprocess with a hard
timeout so one hang cannot kill the batch.  A step only counts as ok if
its measurement really ran on the TPU backend (a CPU fallback is
recorded rc-0 but ok-false and persists no artifact).  Artifacts land in
docs/artifacts/ and a combined log in docs/artifacts/on_chip_suite.log.

    python tools/on_chip_suite.py [--quick]
"""
import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(_REPO, "docs", "artifacts")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _runner import SKIP_IF, TASKS, VALIDATORS, run_task  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="shorter timeouts, skip the full consistency sweep")
    args = ap.parse_args()
    os.makedirs(ART, exist_ok=True)
    log = []
    succeeded = set()

    for name, argv, extra_env, timeout in TASKS:
        if name in SKIP_IF and SKIP_IF[name] in succeeded:
            continue  # e.g. no-fusion BERT fallback after a clean BERT run
        if args.quick:
            if name == "consistency":
                continue
            timeout = min(timeout, 600)
        print(f"=== {name}: {' '.join(argv)} {extra_env or ''}", flush=True)
        ok, rec = run_task(name, argv, extra_env, timeout,
                           validator=VALIDATORS.get(name))
        rec["ok"] = ok
        print(json.dumps(rec), flush=True)
        log.append(rec)
        if ok:
            succeeded.add(name)

    with open(os.path.join(ART, "on_chip_suite.log"), "w") as f:
        json.dump(log, f, indent=1)
    print("suite complete:", len(succeeded), "/", len(log), "steps ok")
    return 0 if len(succeeded) == len(log) else 1


if __name__ == "__main__":
    sys.exit(main())
