#!/usr/bin/env python
"""One-shot on-chip measurement suite (r4).

Runs every TPU-dependent measurement the r3 verdict asked for, the moment
the relay answers, each step in a subprocess with a hard timeout so one
hang cannot kill the batch.  Artifacts land in docs/artifacts/ and a
combined log in docs/artifacts/on_chip_suite.log.

    python tools/on_chip_suite.py [--quick]

Steps:
  1. bench.py                       ResNet-50 bs256 NHWC (headline)
  2. bench.py BENCH_LAYOUT=NCHW     layout ablation
  3. bench.py BENCH_BATCH=128       batch ablation (r3 measured bs128)
  4. bench.py BENCH_MODEL=bert      BERT-base tokens/sec (BASELINE #2)
  5. tools/bench_step.py --device tpu   eager Trainer vs fused ratio
  6. tools/check_consistency.py     434-case cpu-vs-tpu oracle
  7. tools/dump_hlo.py --platform tpu --profile-steps 5   HLO + profile
"""
import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(_REPO, "docs", "artifacts")


def run(name, cmd, env_extra=None, timeout=1800, log=None):
    env = dict(os.environ)
    env.update(env_extra or {})
    t0 = time.time()
    print(f"=== {name}: {' '.join(cmd)} {env_extra or ''}", flush=True)
    try:
        p = subprocess.run(cmd, cwd=_REPO, env=env, capture_output=True,
                           text=True, timeout=timeout)
        out, rc = (p.stdout or ""), p.returncode
        err = (p.stderr or "")[-2000:]
    except subprocess.TimeoutExpired as te:
        # keep whatever the child printed before the timeout: bench.py
        # emits its primary JSON line as soon as it exists
        out = te.stdout.decode() if isinstance(te.stdout, bytes) else (
            te.stdout or "")
        rc, err = -1, f"TIMEOUT after {timeout}s"
    dt = round(time.time() - t0, 1)
    rec = {"step": name, "rc": rc, "s": dt,
           "stdout_tail": out.strip().splitlines()[-3:] if out else [],
           "stderr_tail": err.strip().splitlines()[-3:] if err else []}
    print(json.dumps(rec), flush=True)
    if log is not None:
        log.append(rec)
    # persist any bench JSON line as its own artifact
    for line in reversed(out.strip().splitlines()):
        try:
            j = json.loads(line)
            if isinstance(j, dict) and "metric" in j:
                path = os.path.join(ART, f"{name}.json")
                with open(path, "w") as f:
                    json.dump(j, f, indent=1)
                break
        except ValueError:
            continue
    return rc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="shorter timeouts, skip the full consistency sweep")
    args = ap.parse_args()
    os.makedirs(ART, exist_ok=True)
    py = sys.executable
    log = []
    t = 600 if args.quick else 1800

    # BENCH_SECONDARY=0: the dedicated bench_bert step below covers the
    # secondary metric; re-running BERT inside every ResNet step would
    # burn chip time and could push a step past its timeout, discarding
    # the already-measured headline
    no_sec = {"BENCH_SECONDARY": "0"}
    run("bench_resnet_bs256_nhwc", [py, "bench.py"], dict(no_sec),
        timeout=t, log=log)
    run("bench_resnet_bs256_nchw", [py, "bench.py"],
        dict(no_sec, BENCH_LAYOUT="NCHW"), timeout=t, log=log)
    run("bench_resnet_bs128_nhwc", [py, "bench.py"],
        dict(no_sec, BENCH_BATCH="128"), timeout=t, log=log)
    rc = run("bench_bert", [py, "bench.py"], {"BENCH_MODEL": "bert"},
             timeout=t, log=log)
    if rc != 0:
        # Pallas lowering through the relay is the likeliest failure; the
        # dense-attention path is numerically equivalent (MXNET_USE_FUSION
        # is the reference's fusion kill-switch)
        run("bench_bert_nofusion", [py, "bench.py"],
            {"BENCH_MODEL": "bert", "MXNET_USE_FUSION": "0"},
            timeout=t, log=log)
    run("bench_transformer_base", [py, "bench.py"],
        {"BENCH_MODEL": "transformer"}, timeout=t, log=log)
    run("bench_step_eager_vs_fused",
        [py, "tools/bench_step.py", "--device", "tpu", "--batch", "64",
         "--res", "64", "--steps", "5"], timeout=t, log=log)
    if not args.quick:
        run("check_consistency", [py, "tools/check_consistency.py"],
            timeout=3000, log=log)
    run("dump_hlo_tpu",
        [py, "tools/dump_hlo.py", "--platform", "tpu", "--batch", "256",
         "--profile-steps", "5"], timeout=t, log=log)

    with open(os.path.join(ART, "on_chip_suite.log"), "w") as f:
        json.dump(log, f, indent=1)
    print("suite complete:",
          sum(1 for r in log if r["rc"] == 0), "/", len(log), "steps ok")


if __name__ == "__main__":
    main()
