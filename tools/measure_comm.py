"""Collective-communication bandwidth harness (reference:
tools/bandwidth/measure.py — the kvstore push/pull bandwidth tool).

Measures compiled allreduce (psum) and all_gather throughput over the
active device mesh: the ICI path on real TPU chips, or the virtual CPU
mesh for plumbing checks:

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
      python tools/measure_comm.py --size-mb 16
"""
import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--size-mb", type=float, default=16.0,
                    help="payload per device, MB")
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--dp", type=int, default=0,
                    help="devices to use (0 = all)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    n = args.dp or len(devices)
    devices = devices[:n]
    mesh = Mesh(np.asarray(devices), ("dp",))
    elems = int(args.size_mb * 1e6 / 4)
    x = jnp.arange(n * elems, dtype=jnp.float32).reshape(n, elems)
    x = jax.device_put(x, NamedSharding(mesh, P("dp", None)))

    from mxnet_tpu.parallel.sharding import shard_map_compat

    @jax.jit
    def allreduce(v):
        return shard_map_compat(
            lambda s: jax.lax.psum(s, "dp"), mesh=mesh,
            in_specs=P("dp", None), out_specs=P(None, None))(v)

    @jax.jit
    def allgather(v):
        return shard_map_compat(
            lambda s: jax.lax.all_gather(s, "dp"), mesh=mesh,
            in_specs=P("dp", None), out_specs=P(None, "dp", None))(v)

    for name, fn in (("allreduce", allreduce), ("all_gather", allgather)):
        out = fn(x)
        jax.block_until_ready(out)  # compile
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = fn(x)
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / args.iters
        # ring cost model: 2(n-1)/n of the payload crosses each link
        payload = elems * 4
        algo_bw = payload / dt / 1e9
        bus_bw = algo_bw * 2 * (n - 1) / n
        print(f"{name:<11} n={n}  {args.size_mb:.0f}MB/dev  "
              f"{dt * 1e3:7.2f} ms   algo {algo_bw:6.2f} GB/s   "
              f"bus {bus_bw:6.2f} GB/s")


if __name__ == "__main__":
    main()
