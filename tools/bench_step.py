#!/usr/bin/env python
"""Eager Trainer step vs fused DataParallelStep throughput.

VERDICT r2 weak #6 asked for an honest account of the eager path's cost:
the Gluon Trainer path dispatches per-op (reference: per-batch chain of
engine pushes) while DataParallelStep compiles forward+backward+optimizer
into ONE XLA program.  This tool measures both on the same net/batch and
prints one JSON line with the ratio.

Run on CPU (default, for CI-ish environments) or TPU (JAX_PLATFORMS
untouched when --device tpu).
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--res", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--device", default="cpu", choices=["cpu", "tpu"])
    args = ap.parse_args()

    import jax

    if args.device == "cpu":
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    def make_net():
        mx.random.seed(0)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(16, 3, padding=1), gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"), gluon.nn.MaxPool2D(2),
                gluon.nn.Conv2D(32, 3, padding=1), gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"), gluon.nn.GlobalAvgPool2D(),
                gluon.nn.Dense(10))
        net.initialize(mx.init.Xavier())
        return net

    x = np.random.RandomState(0).rand(
        args.batch, 3, args.res, args.res).astype(np.float32)
    y = np.random.RandomState(1).randint(0, 10, args.batch).astype(np.float32)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # --- eager Trainer path (hybridized forward, per-op backward/update) --
    net = make_net()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9})

    def eager_step():
        with autograd.record():
            loss = loss_fn(net(nd.array(x)), nd.array(y))
        loss.backward()
        trainer.step(args.batch)
        return loss

    # host-materialize the loss rather than block_until_ready: the latter
    # does NOT block through the axon relay (see bench.py _timed_steps);
    # steps chain through the updated params, so the final read times all
    float(np.asarray(eager_step()._data).sum())  # warmup/compile
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = eager_step()
    float(np.asarray(loss._data).sum())
    eager_dt = (time.perf_counter() - t0) / args.steps

    # --- fused step -------------------------------------------------------
    net2 = make_net()
    step = DataParallelStep(
        net2, loss_fn, mesh=local_mesh(devices=[mx.current_context().jax_device]),
        optimizer="sgd", optimizer_params={"learning_rate": 0.05,
                                           "momentum": 0.9})
    float(np.asarray(step.step(nd.array(x), nd.array(y))).sum())  # compile
    t0 = time.perf_counter()
    for _ in range(args.steps):
        loss = step.step(nd.array(x), nd.array(y))
    float(np.asarray(loss).sum())
    fused_dt = (time.perf_counter() - t0) / args.steps

    # report the MEASURED backend, not the requested flag — without the
    # axon env a --device tpu run silently lands on CPU and must not be
    # recorded as an on-chip number (tools/relay_watch.py keys off this)
    measured = jax.devices()[0].platform
    print(json.dumps({
        "metric": "fused_vs_eager_step_speedup",
        "eager_ms": round(eager_dt * 1e3, 2),
        "fused_ms": round(fused_dt * 1e3, 2),
        "value": round(eager_dt / fused_dt, 2),
        "unit": "x",
        "device": "cpu" if measured == "cpu" else "tpu",
        "requested": args.device, "batch": args.batch}))


if __name__ == "__main__":
    main()
