"""CPU-vs-TPU consistency oracle over the full op sweep.

One command (r3 verdict #5): replays every tests/test_op_sweep.py case on
the real chip and on the host CPU and compares forwards and tape gradients
— the TPU-native analog of the reference's check_consistency harness
(tests/python/gpu/test_operator_gpu.py ~L1300), which re-runs the whole op
surface across device/dtype combos.

    python tools/check_consistency.py [--limit N] [--filter SUBSTR]
                                      [--out CONSISTENCY.json]

Architecture (relay-hang-proof, like bench.py): the TPU half runs in a
SUBPROCESS under the axon platform with a hard timeout; the parent pins
itself to CPU, evaluates the same cases, compares, and always writes a
parseable JSON report.  Exit 0 with {"skipped": true} when no chip
answers — rerun the moment the relay returns.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

# probe/env handling is bench.py's (retry-with-backoff, PYTHONPATH
# preserved) — one implementation, not a drifting copy
import bench as _bench

# forward+grad per case is tiny; the budget is relay round-trips + compiles
CHILD_TIMEOUT = float(os.environ.get("CONSISTENCY_TIMEOUT", 2400))

# dtype-aware tolerances: TPU matmul/conv accumulate bf16xbf16->f32 for
# bf16 inputs but run f32 math through the MXU's f32 path for f32 inputs;
# expect near-f32 agreement with CPU, loose enough for transcendentals.
RTOL, ATOL = 2e-3, 2e-4


def _axon_env():
    env = _bench._axon_env()
    env["PYTHONPATH"] = _REPO + ":" + env.get("PYTHONPATH", "")
    return env


def _probe():
    # session cache: if bench/pytest already paid for a probe this boot,
    # reuse the verdict instead of burning ~5 min on a dead relay again
    return _bench._probe_tpu([], use_cache=True)


def tpu_child(case_ids, result_path):
    """Runs under the axon platform: evaluate cases on mx.tpu()."""
    import numpy as np  # noqa: F401

    from consistency_common import eval_case, load_cases

    import mxnet_tpu as mx

    sweep = load_cases()
    by_id = {c.id: c for c in sweep.CASES}
    ctx = mx.tpu()
    results, errors = {}, {}

    def flush():
        # incremental: a parent-side timeout must not discard finished work
        tmp = result_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"results": results, "errors": errors}, f)
        os.replace(tmp, result_path)

    for idx, cid in enumerate(case_ids):
        case = by_id[cid]
        try:
            fwd, grads = eval_case(case, ctx)
            results[cid] = {
                "fwd": [a.tolist() for a in fwd],
                "grads": (None if grads is None else
                          [None if g is None else g.tolist() for g in grads]),
            }
        except Exception as e:  # record and keep sweeping
            errors[cid] = f"{type(e).__name__}: {e}"
        if (idx + 1) % 25 == 0:
            flush()
            print(f"tpu child: {idx + 1}/{len(case_ids)}", flush=True)
    flush()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--limit", type=int, default=0, help="first N cases only")
    ap.add_argument("--filter", default="", help="substring filter on case id")
    ap.add_argument("--out", default=os.path.join(_REPO, "CONSISTENCY.json"))
    args = ap.parse_args()

    t0 = time.perf_counter()
    if not _probe():
        report = {"skipped": True, "reason": "no TPU backend answered probe",
                  "elapsed_s": round(time.perf_counter() - t0, 1)}
        with open(args.out, "w") as f:
            json.dump(report, f, indent=1)
        print(json.dumps(report))
        return 0

    # enumerate cases (registry import only; no backend touch yet)
    from consistency_common import compare, eval_case, load_cases

    sweep = load_cases()
    cases = [c for c in sweep.CASES if args.filter in c.id]
    if args.limit:
        cases = cases[:args.limit]
    ids = [c.id for c in cases]

    # TPU half in a subprocess with a hard timeout
    with tempfile.NamedTemporaryFile(suffix=".json", delete=False) as tf:
        result_path = tf.name
    child_code = (
        "import sys; sys.path.insert(0, {tools!r}); sys.path.insert(0, {repo!r})\n"
        "from check_consistency import tpu_child\n"
        "import json\n"
        "tpu_child(json.load(open({ids_path!r})), {result_path!r})\n"
    )
    with tempfile.NamedTemporaryFile("w", suffix=".json", delete=False) as f:
        json.dump(ids, f)
        ids_path = f.name
    code = child_code.format(tools=os.path.dirname(os.path.abspath(__file__)),
                             repo=_REPO, ids_path=ids_path,
                             result_path=result_path)
    timed_out, child = False, None
    try:
        try:
            child = subprocess.run([sys.executable, "-c", code],
                                   env=_axon_env(), timeout=CHILD_TIMEOUT,
                                   text=True, capture_output=True)
        except subprocess.TimeoutExpired:
            timed_out = True  # partial results may still exist (incremental)
        try:
            with open(result_path) as f:
                tpu = json.load(f)
        except (OSError, ValueError):
            tail = ("" if child is None
                    else (child.stderr or child.stdout or "")[-1500:])
            report = {"skipped": True,
                      "reason": (f"tpu child exceeded {CHILD_TIMEOUT}s with "
                                 "no partial results" if timed_out
                                 else "tpu child produced no results"),
                      "child_tail": tail,
                      "elapsed_s": round(time.perf_counter() - t0, 1)}
            with open(args.out, "w") as f:
                json.dump(report, f, indent=1)
            print(json.dumps(report))
            return 0
    finally:
        for p in (result_path, ids_path):
            try:
                os.unlink(p)
            except OSError:
                pass

    # CPU half in-process
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    import mxnet_tpu as mx

    ctx = mx.cpu()
    mismatches, tpu_errors, compared = [], tpu["errors"], 0
    cpu_errors = {}
    for case in cases:
        rec = tpu["results"].get(case.id)
        if rec is None:
            continue
        try:  # per-case guard, like the TPU child — one failure must not
            # abort the run after the chip already spent its budget
            fwd_cpu, grads_cpu = eval_case(case, ctx)
        except Exception as e:
            cpu_errors[case.id] = f"{type(e).__name__}: {e}"
            continue
        fwd_tpu = [np.asarray(a) for a in rec["fwd"]]
        msg = compare(case, fwd_tpu, fwd_cpu, RTOL, ATOL, "fwd")
        if msg is None and grads_cpu is not None and rec["grads"] is not None:
            grads_tpu = [None if g is None else np.asarray(g)
                         for g in rec["grads"]]
            msg = compare(case, grads_tpu, grads_cpu, 5 * RTOL, 5 * ATOL,
                          "grad")
        if msg:
            mismatches.append(msg)
        compared += 1

    report = {
        "skipped": False,
        "partial": timed_out,
        "cases_total": len(cases),
        "cases_compared": compared,
        "mismatches": mismatches,
        "tpu_errors": tpu_errors,
        "cpu_errors": cpu_errors,
        "rtol": RTOL, "atol": ATOL,
        "elapsed_s": round(time.perf_counter() - t0, 1),
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps({k: (len(v) if isinstance(v, (list, dict)) else v)
                      for k, v in report.items()}))
    # a sweep where nothing compared (or any case crashed on-chip) is NOT
    # a pass — the exit code is the CI contract
    ok = (compared > 0 and not mismatches and not tpu_errors
          and not cpu_errors)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
