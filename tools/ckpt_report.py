#!/usr/bin/env python
"""Offline checkpoint-directory audit (docs/FAULT_TOLERANCE.md
§Shard-granular checkpoints).

``mxnet_tpu/checkpoint.py`` writes ``step-N/`` dirs in two formats: the
gathered format (``params.nd`` + digests in ``meta.json``) and the
shard-granular format 2 (``params-shard-R.nd`` / ``optstate-shard-R.nd``
per rank, per-rank ``shard-R.json`` digest markers, and a shard manifest
in ``meta.json`` next to ``layout``).  This CLI answers the after-the-run
questions without loading a single tensor:

  * **per-step verdict** — meta parse, SHA-256 digest verification of
    every recorded payload (meta-level digests for format 1,
    per-rank marker digests for format 2), and whether restore would
    accept the step;
  * **per-rank shard table** (format 2) — each rank's shard-file sizes
    and shard counts, the zero-collective scaling signal on disk: a
    rank's bytes track the shards it owns, not the global param count;
  * **missing / orphan shard detection** — manifest shards whose rank
    never committed a marker or whose ``name#j`` key is absent from the
    rank's file, and shard files / keys on disk the manifest never
    mentions (a stale rank from a previous world size);
  * **layout vs manifest consistency** — every layout spec key must
    appear in the manifest (and vice versa), and no manifest shard may
    cite a rank >= the recorded world size.

Exit code: 0 clean, 2 usage/IO error (missing directory, no step dirs),
3 when any step is invalid or inconsistent — CI and the launch.py
supervisor can key off it, mirroring ``trace_report.py`` /
``mem_report.py``.  ``--json`` emits the full report object; ``--step N``
audits one step only.

Importable WITHOUT jax/numpy/mxnet_tpu (stdlib only): the native ``.nd``
header (magic ``MXTPND01`` | u64 header_len | JSON header | raw
payloads) and the checkpoint dir protocol are parsed directly — keep in
sync with ``mxnet_tpu/ndarray/utils.py`` and ``mxnet_tpu/checkpoint.py``.
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import struct
import sys
from typing import Dict, List, Optional

__all__ = ["audit_step", "build_report", "format_text", "main"]

_ND_MAGIC = b"MXTPND01"
_SHARD_PREFIX = {"params": "params-shard", "opt_state": "optstate-shard"}


def _sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def read_nd_header(path: str) -> dict:
    """Parse a native .nd file's JSON header (names/dtypes/shapes/nbytes)
    without decoding any payload; raises ValueError on a foreign or
    truncated header."""
    with open(path, "rb") as f:
        magic = f.read(len(_ND_MAGIC))
        if magic != _ND_MAGIC:
            raise ValueError(f"{path}: not a native .nd file")
        raw = f.read(8)
        if len(raw) != 8:
            raise ValueError(f"{path}: truncated header length")
        (hlen,) = struct.unpack("<Q", raw)
        blob = f.read(hlen)
        if len(blob) != hlen:
            raise ValueError(f"{path}: truncated header")
        return json.loads(blob.decode())


def _nd_keys(path: str) -> Dict[str, dict]:
    """{name: entry} of a native .nd file, header-only."""
    return {e["name"]: e for e in read_nd_header(path).get("entries", [])}


def _manifest_shards(manifest: dict):
    """Yield (section, name, shard_dict) over a format-2 manifest."""
    for section in ("params", "opt_state"):
        for name, ent in (manifest.get(section) or {}).items():
            for sh in ent.get("shards", []):
                yield section, name, sh


def audit_step(d: str) -> dict:
    """Audit one ``step-N`` dir; returns {step, format, valid, issues,
    ranks: {rank: {files: {fname: bytes}, shards}}, total_bytes}."""
    issues: List[str] = []
    out = {"dir": d, "format": 0, "valid": False, "issues": issues,
           "ranks": {}, "total_bytes": 0}
    try:
        with open(os.path.join(d, "meta.json")) as f:
            meta = json.load(f)
    except (OSError, ValueError) as e:
        issues.append(f"meta.json unreadable: {e}")
        return out
    if not isinstance(meta, dict) or "step" not in meta:
        issues.append("meta.json carries no step")
        return out
    out["step"] = meta["step"]
    fmt = int(meta.get("format", 1))
    out["format"] = fmt
    try:
        out["total_bytes"] = sum(
            os.path.getsize(os.path.join(d, f)) for f in os.listdir(d))
    except OSError:
        pass
    # meta-level digests (format 1: all payloads; format 2: trainer.states)
    for fname, want in (meta.get("digests") or {}).items():
        path = os.path.join(d, fname)
        if not os.path.exists(path):
            issues.append(f"digest-listed file missing: {fname}")
            continue
        if _sha256_file(path) != want:
            issues.append(f"digest mismatch: {fname}")
    if fmt < 2:
        if meta.get("digests") is None and not os.path.exists(
                os.path.join(d, "params.nd")):
            issues.append("pre-digest checkpoint missing params.nd")
        out["valid"] = not issues
        return out
    manifest = meta.get("manifest") or {}
    layout = meta.get("layout") or {}
    world = meta.get("world_size") or layout.get("world_size")
    # ------------------------------------------------------------------
    # layout vs manifest consistency
    # ------------------------------------------------------------------
    specs = set((layout.get("specs") or {}))
    mparams = set(manifest.get("params") or {})
    for name in sorted(specs - mparams):
        issues.append(f"layout spec {name!r} missing from manifest")
    for name in sorted(mparams - specs):
        if specs:  # a layout without specs can't be cross-checked
            issues.append(f"manifest param {name!r} absent from layout "
                          "specs")
    ranks_needed: Dict[int, Dict[str, set]] = {}
    for section, name, sh in _manifest_shards(manifest):
        r = int(sh["rank"])
        if world is not None and r >= int(world):
            issues.append(
                f"manifest shard {name}#{sh.get('j')} cites rank {r} "
                f">= world_size {world}")
        ranks_needed.setdefault(r, {"params": set(), "opt_state": set()})
        ranks_needed[r][section].add(f"{name}#{sh.get('j', 0)}")
    # ------------------------------------------------------------------
    # per-rank shard files: markers, digests, key coverage
    # ------------------------------------------------------------------
    for r in sorted(ranks_needed):
        row = {"files": {}, "shards": 0}
        out["ranks"][r] = row
        mpath = os.path.join(d, f"shard-{r}.json")
        try:
            with open(mpath) as f:
                marker = json.load(f)
        except (OSError, ValueError) as e:
            issues.append(f"rank {r}: shard-{r}.json unreadable ({e})")
            continue
        digests = marker.get("digests") or {}
        for fname, want in digests.items():
            path = os.path.join(d, fname)
            if not os.path.exists(path):
                issues.append(f"rank {r}: committed file missing: {fname}")
                continue
            row["files"][fname] = os.path.getsize(path)
            if _sha256_file(path) != want:
                issues.append(f"rank {r}: digest mismatch: {fname}")
        for section, keys in ranks_needed[r].items():
            if not keys:
                continue
            fname = f"{_SHARD_PREFIX[section]}-{r}.nd"
            path = os.path.join(d, fname)
            if fname not in digests:
                issues.append(f"rank {r}: {fname} owed by manifest but "
                              "not committed")
                continue
            if not os.path.exists(path):
                continue  # already flagged above
            try:
                entries = _nd_keys(path)
            except (ValueError, OSError) as e:
                issues.append(f"rank {r}: {fname} header unreadable ({e})")
                continue
            row["shards"] += len(entries)
            missing = sorted(keys - set(entries))
            for k in missing[:4]:
                issues.append(f"rank {r}: {fname} missing shard key {k}")
            if len(missing) > 4:
                issues.append(f"rank {r}: {fname} missing "
                              f"{len(missing) - 4} more shard keys")
            for k in sorted(set(entries) - keys):
                issues.append(f"rank {r}: {fname} orphan shard key {k} "
                              "(not in manifest)")
    # ------------------------------------------------------------------
    # orphan shard files: on disk but owed by no manifest shard
    # ------------------------------------------------------------------
    try:
        names = os.listdir(d)
    except OSError:
        names = []
    for fname in sorted(names):
        for section, prefix in _SHARD_PREFIX.items():
            if not (fname.startswith(f"{prefix}-")
                    and fname.endswith(".nd")):
                continue
            try:
                r = int(fname[len(prefix) + 1:-3])
            except ValueError:
                continue
            if r not in ranks_needed or not ranks_needed[r][section]:
                issues.append(f"orphan shard file: {fname} (manifest "
                              f"owes rank {r} nothing in {section})")
    out["valid"] = not issues
    return out


def build_report(directory: str, step: Optional[int] = None) -> dict:
    steps = []
    try:
        names = os.listdir(directory)
    except OSError as e:
        raise OSError(f"cannot read {directory}: {e}") from e
    for dname in sorted(names):
        if not dname.startswith("step-"):
            continue
        try:
            s = int(dname.split("-", 1)[1])
        except ValueError:
            continue
        if step is not None and s != step:
            continue
        steps.append(audit_step(os.path.join(directory, dname)))
    steps.sort(key=lambda r: r.get("step", -1))
    latest = None
    try:
        with open(os.path.join(directory, "latest")) as f:
            latest = int(f.read().strip())
    except (OSError, ValueError):
        pass
    anomalies = [i for r in steps for i in r["issues"]]
    return {"directory": directory, "latest": latest, "steps": steps,
            "anomalies": anomalies}


def format_text(rep: dict) -> str:
    lines = [f"checkpoint dir: {rep['directory']}",
             f"latest pointer: {rep['latest']}"]
    for r in rep["steps"]:
        fmt = {0: "?", 1: "gathered", 2: "sharded"}.get(r["format"],
                                                        str(r["format"]))
        verdict = "ok" if r["valid"] else "INVALID"
        lines.append(f"  step {r.get('step', '?')}: {fmt} "
                     f"{r['total_bytes']} B -> {verdict}")
        for rank in sorted(r["ranks"]):
            row = r["ranks"][rank]
            files = ", ".join(f"{f}={n}B"
                              for f, n in sorted(row["files"].items()))
            lines.append(f"    rank {rank}: {row['shards']} shards "
                         f"({files})")
        for issue in r["issues"]:
            lines.append(f"    ! {issue}")
    if rep["anomalies"]:
        lines.append(f"{len(rep['anomalies'])} issue(s) found")
    else:
        lines.append("all checkpoints verify")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Offline shard-manifest audit of a checkpoint "
                    "directory (exit 0 clean / 2 usage-IO / 3 anomalies)")
    ap.add_argument("directory", help="AsyncCheckpointer directory")
    ap.add_argument("--step", type=int, default=None, metavar="N",
                    help="audit only step N")
    ap.add_argument("--json", action="store_true",
                    help="emit the full report object as JSON")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.directory):
        print(f"ckpt_report: no such directory: {args.directory}",
              file=sys.stderr)
        return 2
    try:
        rep = build_report(args.directory, step=args.step)
    except OSError as e:
        print(f"ckpt_report: {e}", file=sys.stderr)
        return 2
    if not rep["steps"]:
        print(f"ckpt_report: no step-* dirs in {args.directory}"
              + (f" matching step {args.step}" if args.step is not None
                 else ""), file=sys.stderr)
        return 2
    if args.json:
        print(json.dumps(rep, indent=2, sort_keys=True))
    else:
        print(format_text(rep))
    return 3 if rep["anomalies"] else 0


if __name__ == "__main__":
    sys.exit(main())
