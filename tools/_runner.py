"""Shared subprocess runner for the on-chip measurement tools.

One implementation of the run-with-timeout + artifact-persist contract,
used by both tools/on_chip_suite.py (one-shot batch) and
tools/relay_watch.py (probe loop) so the two cannot drift:

- every task runs under bench._axon_env() (PYTHONPATH=/root/.axon_site +
  JAX_PLATFORMS=axon when the relay site exists) — tools that don't
  rebuild the env themselves would otherwise silently fall back to CPU;
- a metric JSON line on stdout is persisted to docs/artifacts/<name>.json
  ONLY when its measured platform/device is "tpu" — a CPU fallback must
  never clobber a committed on-chip artifact;
- a consistency-style report line ({"skipped": ...}) only counts as
  success when the sweep really compared cases.
"""
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(_REPO, "docs", "artifacts")
_PY = sys.executable

sys.path.insert(0, _REPO)
import bench as _bench  # noqa: E402

# Canonical on-chip task table — THE one list both tools consume, in
# value order for a short relay window (headline first, then the
# MFU-decisive profile, then the never-measured metrics, then ablations
# and the long consistency sweep).  Artifact names = task names, so a
# measurement captured by either tool is visible to both.
TASKS = [
    # (name, argv, extra_env, timeout_s)
    ("bench_resnet_bs256_nhwc",
     [_PY, "bench.py"], {"BENCH_SECONDARY": "0"}, 1500),
    ("tpu_profile_hlo",
     [_PY, "tools/dump_hlo.py", "--platform", "tpu", "--batch", "256",
      "--profile-steps", "5"], {}, 1500),
    ("bench_bert",
     [_PY, "bench.py"], {"BENCH_MODEL": "bert", "BENCH_SECONDARY": "0"},
     1200),
    ("bench_bert_nofusion",
     [_PY, "bench.py"],
     {"BENCH_MODEL": "bert", "BENCH_SECONDARY": "0",
      "MXNET_USE_FUSION": "0"}, 1200),
    ("bench_resnet_bs128_nhwc",
     [_PY, "bench.py"], {"BENCH_BATCH": "128", "BENCH_SECONDARY": "0"},
     1200),
    # dispatch-overhead ablation: all steps inside one lax.scan program —
    # the delta vs the headline per-step-dispatch number IS the relay
    # dispatch cost (docs/PERF.md r5 reading)
    ("bench_resnet_bs256_scan",
     [_PY, "bench.py"], {"BENCH_SCAN": "1", "BENCH_SECONDARY": "0"},
     1200),
    # batch-scaling headroom probe: bs512 + remat (fails harmlessly if it
    # doesn't fit HBM; succeeds -> bs256 was underutilizing the chip)
    ("bench_resnet_bs512_remat",
     [_PY, "bench.py"],
     {"BENCH_BATCH": "512", "BENCH_REMAT": "1", "BENCH_SECONDARY": "0"},
     1200),
    ("bench_resnet_bs256_nchw",
     [_PY, "bench.py"], {"BENCH_LAYOUT": "NCHW", "BENCH_SECONDARY": "0"},
     1200),
    ("bench_step_tpu",
     [_PY, "tools/bench_step.py", "--device", "tpu"], {}, 900),
    ("bench_e2e_tpu",
     [_PY, "tools/bench_e2e.py", "--tpu", "--size", "256", "--crop", "224",
      "--batch-size", "256", "--model", "resnet50_v1b", "--dtype",
      "bfloat16", "--num-images", "2048", "--num-classes", "1000"], {},
     1500),
    ("bench_transformer",
     [_PY, "bench.py"],
     {"BENCH_MODEL": "transformer", "BENCH_SECONDARY": "0"}, 1200),
    ("consistency",
     [_PY, "tools/check_consistency.py"], {}, 1800),
]

# task -> other task whose success makes it unnecessary (the nofusion
# BERT run is only a fallback for a Pallas failure on the relay)
SKIP_IF = {"bench_bert_nofusion": "bench_bert"}


def _profile_ok():
    """dump_hlo exits 0 even when lowering failed — success requires the
    actual optimized (or at least stablehlo) module in the artifact."""
    path = os.path.join(ART, "resnet50_step_nhwc_bs256.tpu.hlo.txt")
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return False
    return "### optimized" in text or "### stablehlo" in text


VALIDATORS = {"tpu_profile_hlo": _profile_ok}


def artifact_done(name):
    """True if docs/artifacts/<name>.json already holds an on-chip metric
    (so neither tool re-burns a relay window re-measuring it)."""
    try:
        with open(os.path.join(ART, f"{name}.json")) as f:
            j = json.load(f)
    except (OSError, ValueError):
        return False
    return j.get("platform", j.get("device")) == "tpu"


def run_task(name, argv, extra_env=None, timeout=1800, validator=None):
    """Run `argv` in a subprocess; return (ok, record).

    ok = exit 0, AND the metric line (if any) was measured on TPU, AND the
    report line (if any) wasn't a skipped/empty sweep, AND `validator()`
    (if given) confirms the produced artifact is real.
    """
    env = _bench._axon_env()
    env.update(extra_env or {})
    t0 = time.perf_counter()
    try:
        p = subprocess.run(argv, cwd=_REPO, env=env, capture_output=True,
                           text=True, timeout=timeout)
        out, rc = p.stdout or "", p.returncode
        err = (p.stderr or "")[-1500:]
    except subprocess.TimeoutExpired as te:
        # keep whatever the child printed: bench.py emits its primary JSON
        # line as soon as it exists
        out = te.stdout if isinstance(te.stdout, str) else (
            te.stdout.decode() if te.stdout else "")
        rc, err = -1, f"TIMEOUT after {timeout}s"
    dt = round(time.perf_counter() - t0, 1)
    rec = {"task": name, "rc": rc, "s": dt,
           "stdout_tail": out.strip().splitlines()[-4:] if out else [],
           "stderr_tail": err.strip().splitlines()[-2:] if err else []}

    ok = rc == 0
    os.makedirs(ART, exist_ok=True)
    for line in reversed(out.strip().splitlines() if out else []):
        try:
            j = json.loads(line)
        except (ValueError, TypeError):
            continue
        if not isinstance(j, dict):
            continue
        if "metric" in j:
            # bench.py/bench_e2e.py tag "platform"; bench_step.py "device"
            if j.get("platform", j.get("device")) != "tpu":
                ok = False  # CPU fallback: don't persist, retry later
            else:
                with open(os.path.join(ART, f"{name}.json"), "w") as f:
                    json.dump(j, f, indent=1)
            break
        if "skipped" in j:
            # check_consistency exits 0 on a skipped sweep — only a
            # really-compared sweep counts as done
            if j.get("skipped") or not j.get("cases_compared"):
                ok = False
            break
    if ok and validator is not None:
        ok = validator()
    return ok, rec
