#!/usr/bin/env python
"""Config-2 end-to-end rehearsal (r4 verdict #7): ONE measured loop of
ImageRecordIter (libmxio C++ decode/augment) -> device feed -> fused
DataParallelStep, reporting train img/s AND the input-stall fraction —
the coupling the reference's ImageRecordIter + executor pipeline provides
(SURVEY §3.6), which per-component benches (bench_io.py, bench.py) can't
see.

    python tools/bench_e2e.py                    # CPU sanity shapes
    python tools/bench_e2e.py --tpu --crop 224 --batch-size 256 \
        --model resnet50_v1b --dtype bfloat16    # the real config-2 loop

The step dispatches asynchronously (PjRt), so the host's time splits into
"waiting on the input pipeline" (stall) vs "dispatch + waiting on the
device".  input_stall_pct ~ 0 means the C++ pipeline keeps the chip fed.
Prints one JSON line.
"""
import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-images", type=int, default=256)
    ap.add_argument("--size", type=int, default=96)
    ap.add_argument("--crop", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--threads", type=int, default=os.cpu_count() or 4)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--num-classes", type=int, default=100)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--tpu", action="store_true",
                    help="run the step on the TPU backend (default: CPU)")
    args = ap.parse_args()

    import jax

    if not args.tpu:
        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd, recordio
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.io import ImageRecordIter
    from mxnet_tpu.io import native as native_mod
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    if not args.tpu:
        mx.context.pin_platform("cpu")
    ctx = mx.tpu() if args.tpu else mx.cpu()
    mx.context.Context._default_ctx.value = ctx
    mx.random.seed(0)

    rng = np.random.RandomState(0)
    with tempfile.TemporaryDirectory() as d:
        rec = os.path.join(d, "bench.rec")
        writer = recordio.MXIndexedRecordIO(os.path.join(d, "bench.idx"),
                                            rec, "w")
        for i in range(args.num_images):
            arr = rng.randint(0, 255, (args.size, args.size, 3), np.uint8)
            header = recordio.IRHeader(0, float(i % args.num_classes), i, 0)
            writer.write_idx(i, recordio.pack_img(header, arr, quality=90))
        writer.close()

        it = ImageRecordIter(
            path_imgrec=rec, data_shape=(3, args.crop, args.crop),
            batch_size=args.batch_size, shuffle=True,
            rand_crop=True, rand_mirror=True, resize=args.size,
            mean_r=123.68, mean_g=116.28, mean_b=103.53,
            std_r=58.395, std_g=57.12, std_b=57.375,
            preprocess_threads=args.threads)

        net = getattr(vision, args.model)(classes=args.num_classes)
        net.initialize(mx.init.Xavier())
        net.cast(args.dtype)
        step = DataParallelStep(
            net, gluon.loss.SoftmaxCrossEntropyLoss(),
            mesh=local_mesh(devices=[ctx.jax_device]), optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9})

        def feed(batch):
            x = batch.data[0]
            if args.dtype == "bfloat16":
                x = x.astype("bfloat16")
            return step.step(x, batch.label[0])

        # warmup epoch: thread-pool spin-up + the one compile
        loss = None
        for batch in it:
            loss = feed(batch)
        float(np.asarray(loss))

        n, fetch_s, loss = 0, 0.0, None
        t0 = time.perf_counter()
        for _ in range(args.epochs):
            it.reset()
            while True:
                f0 = time.perf_counter()
                try:
                    batch = next(it)
                except StopIteration:
                    break
                fetch_s += time.perf_counter() - f0
                loss = feed(batch)
                n += args.batch_size
        final = float(np.asarray(loss))  # drain the async chain
        total = time.perf_counter() - t0
    print(json.dumps({
        "metric": "e2e_recorditer_train_images_per_sec",
        "value": round(n / total, 1), "unit": "images/sec",
        "input_stall_pct": round(100.0 * fetch_s / total, 1),
        "final_loss": round(final, 4),
        # measured backend, not the requested flag (relay_watch keys off it)
        "platform": ("cpu" if jax.devices()[0].platform == "cpu" else "tpu"),
        "requested": "tpu" if args.tpu else "cpu",
        "native_io": native_mod.available(),
        "model": args.model, "batch": args.batch_size, "crop": args.crop,
        "threads": args.threads,
    }))


if __name__ == "__main__":
    main()
