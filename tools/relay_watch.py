#!/usr/bin/env python
"""Prioritized relay watcher (r5).

The axon relay comes alive in short, rare windows (one window in rounds
3-5 so far; the r5 window lasted ~one bench run before re-wedging).  The
r4 suite burned that window on ablations in file order; this watcher
instead probes cheaply in a loop and, the moment a probe answers, spends
the window on the HIGHEST-VALUE artifact still missing, in the canonical
value order of tools/_runner.TASKS (headline bench, MFU-decisive
profile+HLO, BERT tokens/sec with a no-fusion fallback, batch/layout
ablations, dispatch timing, e2e input pipeline, transformer tokens/sec,
434-case consistency oracle).

Each task runs via tools/_runner.run_task (shared with on_chip_suite.py:
subprocess + timeout, axon env, TPU-measured-platform artifact persist);
a fresh probe runs between tasks so a re-wedged relay costs one timeout,
not ten.  A task is skipped when a done-sentinel OR an on-chip artifact
with its name already exists (so a suite-captured number is never
re-measured); done tasks leave a sentinel in docs/artifacts/.

    nohup python tools/relay_watch.py > /tmp/relay_watch.log 2>&1 &
"""
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ART = os.path.join(_REPO, "docs", "artifacts")

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from _runner import SKIP_IF, TASKS, VALIDATORS, artifact_done, run_task  # noqa: E402
from _runner import _bench  # noqa: E402  (probe machinery)

RETRY_SLEEP = 15 * 60  # probe timeout itself is bench.PROBE_TIMEOUT (90 s)


def probe():
    """Fresh (uncached) relay probe via bench.py's machinery — it builds
    the axon env (PYTHONPATH=/root/.axon_site + JAX_PLATFORMS) and rejects
    cpu-only answers; one attempt, no backoff burn."""
    t0 = time.perf_counter()
    ok = _bench._probe_tpu([], use_cache=False, attempts=1)
    print(json.dumps({"probe": ok, "s": round(time.perf_counter() - t0, 1),
                      "t": time.strftime("%H:%M:%S")}), flush=True)
    return ok


def sentinel(name):
    return os.path.join(ART, f".watch_done_{name}")


MAX_GENUINE_FAILURES = 2


def fail_marker(name):
    return os.path.join(ART, f".watch_failed_{name}")


def _genuine_failures(name):
    try:
        with open(fail_marker(name)) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def note_genuine_failure(name):
    """Task failed while the relay was ALIVE (post-failure probe passed):
    a real task problem (e.g. bs512 genuinely OOMs), not a closed window.
    After MAX_GENUINE_FAILURES the task is retired so it stops burning
    scarce relay time ahead of lower-priority tasks."""
    n = _genuine_failures(name) + 1
    with open(fail_marker(name), "w") as f:
        f.write(str(n))
    return n


def _done(name):
    return (os.path.exists(sentinel(name)) or artifact_done(name)
            or _genuine_failures(name) >= MAX_GENUINE_FAILURES)


def _skip(name):
    return _done(name) or (name in SKIP_IF and _done(SKIP_IF[name]))


def main():
    os.makedirs(ART, exist_ok=True)
    while True:
        todo = [t for t in TASKS if not _skip(t[0])]
        if not todo:
            print("all tasks done", flush=True)
            return
        if probe():
            for name, argv, extra_env, timeout in todo:
                if _skip(name):  # a task earlier in this window covered it
                    continue
                ok, rec = run_task(name, argv, extra_env, timeout,
                                   validator=VALIDATORS.get(name))
                print(json.dumps(rec), flush=True)
                if ok:
                    with open(sentinel(name), "w") as f:
                        f.write(json.dumps(
                            {"done_at": time.strftime("%F %T"),
                             "s": rec["s"]}))
                elif probe():
                    # relay still alive -> the TASK failed (OOM, bug):
                    # count it; retire after MAX_GENUINE_FAILURES
                    n = note_genuine_failure(name)
                    print(json.dumps({"genuine_failure": name, "count": n}),
                          flush=True)
                else:
                    break  # window closed — back to sleep
        time.sleep(RETRY_SLEEP)


if __name__ == "__main__":
    main()
