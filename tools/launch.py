#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py ~L1-200 +
3rdparty/dmlc-core/tracker/dmlc_tracker — scheduler/server/worker spawn with
DMLC_* env).

TPU-native redesign: there is no parameter-server role — every process is a
worker; rendezvous is jax.distributed's coordination service (worker 0 hosts
it) and aggregation is compiled XLA collectives (mxnet_tpu/parallel/dist.py).
The reference CLI is kept so launch scripts port unchanged:

    python tools/launch.py -n 4 --launcher local python train.py --kv-store dist_sync

Launchers:
  local  N worker processes on this host (the reference's dmlc_tracker
         'local' mode, used by its nightly dist tests) — implemented.
  ssh/mpi/yarn/sge  cluster bring-up: out of scope here; on GKE/Cloud the
         per-host env is provided by the pod spec (MX_COORDINATOR etc.),
         so no tracker is needed (SURVEY §2.4 launcher row).

Both MX_* and DMLC_* env spellings are exported to workers.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


# Exit code a worker uses after a SIGTERM-triggered final checkpoint
# ("clean preemption").  Kept in sync with mxnet_tpu/fault.py EXIT_PREEMPTED
# by value — this launcher must stay importable without jax/mxnet_tpu.
EXIT_PREEMPTED = 83

# flight-recorder events echoed per rank when a gang dies
FLIGHT_TAIL_EVENTS = 8


def _tee(stream, sink, prefix: str) -> None:
    """Copy worker output to our own stream, one line at a time, with a
    `[rank N]` prefix so interleaved gang logs stay attributable."""
    try:
        for line in iter(stream.readline, ""):
            sink.write(prefix + line)
            sink.flush()
    except ValueError:  # stream closed under us during teardown
        pass
    finally:
        try:
            stream.close()
        except OSError:
            pass


# ---------------------------------------------------------------------------
# telemetry (mxnet_tpu/telemetry.py writes these files; the filename
# patterns are duplicated here because this launcher must stay importable
# without jax/mxnet_tpu — keep in sync with telemetry.event_path /
# telemetry.heartbeat_path)
# ---------------------------------------------------------------------------
def _flight_tail(tdir: str, rank: int, k: int = FLIGHT_TAIL_EVENTS):
    """Last k events of a rank's telemetry stream, rendered for humans:
    span begin/end pairs collapse into ONE ``"kind": "span"`` line
    carrying the duration (the raw pair would burn two slots of an
    8-event tail on one fact), clock_anchor bookkeeping lines are
    dropped, and an unmatched span_begin survives as-is — an OPEN span in
    a dead rank's tail is exactly the "died inside X" post-mortem clue.
    A span_end whose begin scrolled off the raw window renders as a
    collapsed span line by itself (the end alone carries name + dur_ms).
    Non-span lines pass through verbatim."""
    path = os.path.join(tdir, f"rank-{rank}.jsonl")
    try:
        with open(path, errors="replace") as f:
            # read enough raw lines that k survives the collapsing
            raw = [line.rstrip("\n") for line in deque(f, maxlen=8 * k)]
    except OSError:
        return []
    rendered = []  # (span id or None, text line)
    begins = {}    # span id -> index into rendered (pending span_begin)
    for line in raw:
        try:
            ev = json.loads(line)
        except ValueError:
            rendered.append((None, line))
            continue
        if not isinstance(ev, dict):
            rendered.append((None, line))
            continue
        kind = ev.get("kind")
        if kind == "clock_anchor":
            continue
        if kind == "span":
            # complete hot-path span: strip the merge-key plumbing so the
            # 8-event tail spends its width on the facts
            merged = {k: v for k, v in ev.items()
                      if k not in ("span", "parent", "depth", "tid",
                                   "mono")}
            rendered.append((None, json.dumps(merged)))
        elif kind == "span_begin" and "span" in ev:
            begins[ev["span"]] = len(rendered)
            rendered.append((ev["span"], line))
        elif kind == "span_end" and ev.get("span") in begins:
            idx = begins.pop(ev["span"])
            begin_ev = json.loads(rendered[idx][1])
            merged = {"t": begin_ev.get("t"), "kind": "span",
                      "rank": ev.get("rank"), "name": ev.get("name"),
                      "dur_ms": ev.get("dur_ms")}
            merged.update({kk: vv for kk, vv in begin_ev.items()
                           if kk not in ("t", "kind", "rank", "name",
                                         "span", "parent", "depth", "tid",
                                         "mono")})
            if "error" in ev:
                merged["error"] = ev["error"]
            rendered[idx] = (None, json.dumps(merged))
        elif kind == "span_end":
            # begin fell off the raw window; the end alone still carries
            # the fact (name + dur_ms) — render it as a collapsed span
            # so e.g. a multi-second checkpoint_save finishing right
            # before death isn't silently absent from the tail
            merged = {k2: v for k2, v in ev.items()
                      if k2 not in ("span", "parent", "depth", "tid",
                                    "mono")}
            merged["kind"] = "span"
            rendered.append((None, json.dumps(merged)))
        else:
            rendered.append((None, line))
    return [text for _sid, text in rendered[-k:]]


def _fmt_mb(n) -> str:
    try:
        return f"{float(n) / 1e6:.1f}MB"
    except (TypeError, ValueError):
        return "?"


def _oom_report(tdir: str, rank: int):
    """The newest ``oom_report`` event in a rank's stream, if any —
    memwatch (mxnet_tpu/memwatch.py) records + flushes one before a
    RESOURCE_EXHAUSTED re-raises, so a rank that died OOM carries its
    own post-mortem (largest live-array category, watermark, in-flight
    depth, top executables)."""
    path = os.path.join(tdir, f"rank-{rank}.jsonl")
    try:
        with open(path, errors="replace") as f:
            raw = deque(f, maxlen=512)
    except OSError:
        return None
    found = None
    for line in raw:
        try:
            ev = json.loads(line)
        except ValueError:
            continue
        if isinstance(ev, dict) and ev.get("kind") == "oom_report":
            found = ev
    return found


def _print_oom_report(ev: dict, rank: int) -> None:
    cats = ev.get("categories") or {}
    largest = ev.get("largest_category")
    parts = [f"launch.py: rank {rank} OOM post-mortem"
             + (f" (step {ev['step']})" if ev.get("step") is not None
                else "") + ":"]
    if largest:
        parts.append(f"largest live-array category {largest} "
                     f"({_fmt_mb(cats.get(largest, 0))} of "
                     f"{_fmt_mb(ev.get('live_bytes', 0))} live);")
    parts.append(f"watermark {_fmt_mb(ev.get('watermark_bytes', 0))};")
    if ev.get("inflight_depth") is not None:
        parts.append(f"inflight depth {ev['inflight_depth']};")
    if ev.get("bytes_limit"):
        parts.append(f"device limit {_fmt_mb(ev['bytes_limit'])};")
    top = ev.get("top_executables") or []
    if top:
        t = top[0]
        weight = (t.get("temp_bytes") or t.get("bytes_accessed")
                  or t.get("arg_bytes") or 0)
        parts.append(f"top executable {t.get('executor')}"
                     f"[{t.get('fingerprint')}] ({_fmt_mb(weight)})")
    print(" ".join(parts).rstrip(";"), file=sys.stderr)


def _print_trace_report(tdir: str) -> None:
    """Run tools/trace_report.py over the telemetry dir and echo its
    gang-wide analysis (straggler flags, step breakdown, collective
    bandwidth) into the supervisor's stderr next to the flight tails.
    Subprocess on purpose: the report is stdlib-only and must not be able
    to wedge the supervisor even if the telemetry dir is garbage."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "trace_report.py")
    if not os.path.isfile(script):
        return
    try:
        res = subprocess.run([sys.executable, script, tdir],
                             capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"launch.py: trace report failed: {e}", file=sys.stderr)
        return
    body = (res.stdout or "").strip()
    if body:
        print("launch.py: gang trace report:", file=sys.stderr)
        for line in body.splitlines():
            print(f"  {line}", file=sys.stderr)
    if res.returncode == 3:
        print("launch.py: trace report flagged anomalies (exit 3) — see "
              "above", file=sys.stderr)


def _serving_streams_present(tdir: str) -> bool:
    """Whether any telemetry stream under ``tdir`` carries serving
    events/spans (the ``serve_`` vocabulary).  Bounded scan — the
    supervisor must not slurp multi-GB streams just to decide whether
    to run serve_report."""
    try:
        names = sorted(os.listdir(tdir))
    except OSError:
        return False
    for name in names:
        if not (name.startswith("rank-") and name.endswith(".jsonl")):
            continue
        try:
            with open(os.path.join(tdir, name), "rb") as f:
                if b'"serve_' in f.read(262_144):
                    return True
        except OSError:
            continue
    return False


def _print_serve_report(tdir: str) -> None:
    """Run tools/serve_report.py over the telemetry dir and echo the
    per-request tail attribution — most importantly the UNFINISHED
    request trees ("died inside X", fleet edition) — next to the flight
    tails.  Subprocess + timeout for the same reason as
    _print_trace_report: stdlib-only, must not wedge the supervisor."""
    script = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "serve_report.py")
    if not os.path.isfile(script):
        return
    try:
        res = subprocess.run([sys.executable, script, tdir],
                             capture_output=True, text=True, timeout=60)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"launch.py: serve report failed: {e}", file=sys.stderr)
        return
    body = (res.stdout or "").strip()
    if body:
        print("launch.py: serving request report:", file=sys.stderr)
        for line in body.splitlines():
            print(f"  {line}", file=sys.stderr)
    if res.returncode == 3:
        print("launch.py: serve report flagged SLO violations (exit 3) "
              "— see above", file=sys.stderr)


def _reexport_trace(tdir) -> None:
    """Re-merge the gang Chrome trace after EVERY rank has been reaped.

    With MX_TRACE_EXPORT on, rank 0's own atexit hook merges the gang
    trace at rank 0's process exit — but peer ranks may still be running
    (rank 0 finishing first is the NORMAL case when another rank is the
    straggler), so that merge can read their streams mid-write and drop
    exactly the straggler tail the trace exists to show.  The supervisor
    owns the only moment the files are known complete, so it re-runs the
    merge and overwrites rank 0's best-effort trace.json.  Subprocess on
    purpose (like _print_trace_report): the exporter lives in
    mxnet_tpu.telemetry, whose import pulls in jax, which must not be
    able to wedge the supervisor."""
    raw = os.environ.get("MX_TRACE_EXPORT", "").strip()
    if not tdir or not raw or raw.lower() in ("0", "false", "off"):
        return
    target = tdir if raw.lower() in ("1", "true", "on") else raw
    env = dict(os.environ)
    # the child must neither re-race the export from its own atexit nor
    # attach a recorder that pollutes the run's streams (empty
    # MX_TELEMETRY_DIR leaves telemetry disabled at import)
    env.pop("MX_TRACE_EXPORT", None)
    env["MX_TELEMETRY_DIR"] = ""
    code = ("import sys\n"
            "from mxnet_tpu import telemetry\n"
            "telemetry.export_chrome_trace(sys.argv[1], out=sys.argv[2])\n")
    try:
        res = subprocess.run(
            [sys.executable, "-c", code, tdir,
             os.path.join(target, "trace.json")],
            capture_output=True, text=True, timeout=120, env=env)
    except (OSError, subprocess.TimeoutExpired) as e:
        print(f"launch.py: gang trace re-export failed: {e}",
              file=sys.stderr)
        return
    if res.returncode != 0:
        print("launch.py: gang trace re-export failed: "
              f"{(res.stderr or '').strip()[-500:]}", file=sys.stderr)


# ---------------------------------------------------------------------------
# gang metrics plane (mxnet_tpu/metrics_server.py serves the per-rank
# endpoints and writes metrics-port-<R>.json portfiles next to the
# heartbeats; the filename pattern is duplicated here because this
# launcher must stay importable without jax/mxnet_tpu — keep in sync
# with metrics_server.portfile_path)
# ---------------------------------------------------------------------------
SCRAPE_TIMEOUT = 2.0


def _rank_endpoint(tdir, rank):
    """http://host:port for a rank's live metrics endpoint (from its
    portfile), or None when the rank never advertised one.  The
    portfile's ``host`` is the connectable address the rank bound
    (MX_METRICS_HOST; wildcard binds advertise loopback) — hardcoding
    127.0.0.1 would break the whole supervisor plane for a
    specific-NIC bind."""
    try:
        with open(os.path.join(tdir, f"metrics-port-{rank}.json")) as f:
            rec = json.load(f)
        port = int(rec["port"])
        host = str(rec.get("host") or "127.0.0.1")
    except (OSError, ValueError, KeyError, TypeError):
        return None
    return f"http://{host}:{port}"


def _http_get(url, timeout=SCRAPE_TIMEOUT):
    """(status, body) for a GET, or (None, error string) when the
    endpoint is unreachable.  5xx bodies are read, not raised — a 503
    /healthz verdict carries the diagnosis."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, resp.read().decode("utf-8", "replace")
    except urllib.error.HTTPError as e:
        try:
            return e.code, e.read().decode("utf-8", "replace")
        except OSError:
            return e.code, ""
    except (OSError, ValueError) as e:
        return None, str(e)


def _scrape_ranks(tdir, num, route, timeout=SCRAPE_TIMEOUT):
    """{rank: (status, body) or (None, reason)} — all ranks scraped
    CONCURRENTLY, so one merged request costs ~one SCRAPE_TIMEOUT even
    when several wedged ranks accept TCP and stall: a sequential walk of
    an 8-rank gang could take 8x the timeout, blowing the Prometheus
    scrape deadline exactly during the incident being observed."""
    out = {}
    threads = []
    for rank in range(num):
        base = _rank_endpoint(tdir, rank)
        if base is None:
            out[rank] = (None, "no metrics portfile")
            continue

        def fetch(rank=rank, base=base):
            out[rank] = _http_get(f"{base}{route}", timeout=timeout)

        t = threading.Thread(target=fetch, daemon=True)
        t.start()
        threads.append(t)
    deadline = time.monotonic() + timeout + 1.0
    for t in threads:
        t.join(timeout=max(0.0, deadline - time.monotonic()))
    for rank in range(num):
        out.setdefault(rank, (None, "scrape thread timed out"))
    return out


def _merge_expositions(per_rank):
    """Merge per-rank OpenMetrics bodies ({rank: body or None}) into ONE
    gang exposition: rank samples pass through (they already carry
    rank="R" labels) but are REGROUPED by metric family — the
    OpenMetrics content type promises each family is one uninterrupted
    block, and strict parsers (Prometheus, promtool) reject interleaved
    families, which naive rank-by-rank concatenation produces the
    moment two ranks are up.  Each rank contributes an ``up`` gauge
    (1 = scraped, 0 = endpoint down/unreachable) and an
    ``mx_scrape_staleness_seconds`` gauge measuring DATA age: the rank's
    own ``mx_heartbeat_age_seconds`` when present — a wedged training
    loop stops heartbeating, so this grows even while the rank's HTTP
    thread keeps answering with fresh render timestamps — else the age
    of its ``mx_export_timestamp_seconds`` stamp (meaningful for a
    never-heartbeat process: how old the exposition itself is)."""
    out = ["# TYPE up gauge"]
    staleness = {}
    now = time.time()
    for rank in sorted(per_rank):
        body = per_rank[rank]
        out.append(f'up{{rank="{rank}"}} {1 if body is not None else 0}')
        if body is None:
            continue
        hb_age = export_age = None
        for line in body.splitlines():
            try:
                if line.startswith("mx_heartbeat_age_seconds"):
                    hb_age = max(0.0, float(line.split()[-1]))
                elif line.startswith("mx_export_timestamp_seconds"):
                    export_age = max(0.0, now - float(line.split()[-1]))
            except (ValueError, IndexError):
                pass
        if hb_age is not None:
            staleness[rank] = hb_age
        elif export_age is not None:
            staleness[rank] = export_age
    if staleness:
        out.append("# TYPE mx_scrape_staleness_seconds gauge")
        for rank, age in sorted(staleness.items()):
            out.append(f'mx_scrape_staleness_seconds{{rank="{rank}"}} '
                       f"{round(age, 3)}")
    # family name -> [type line, sample, sample, ...] in first-seen order
    families = {}
    for rank in sorted(per_rank):
        body = per_rank[rank]
        if body is None:
            continue
        for line in body.splitlines():
            if not line or line.startswith("# EOF"):
                continue  # ONE terminator, appended below
            if line.startswith("# TYPE "):
                parts = line.split()
                name = parts[2] if len(parts) > 2 else line
                families.setdefault(name, [line])
                continue
            if line.startswith("#"):
                continue
            name = line.split("{", 1)[0].split(" ", 1)[0]
            families.setdefault(name, []).append(line)
    for lines in families.values():
        out.extend(lines)
    out.append("# EOF")
    return "\n".join(out) + "\n"


class _GangMetricsServer:
    """The supervisor's merged gang ``/metrics`` (+ ``/healthz``):
    scrape-on-demand over every rank's discovered portfile endpoint, so
    one Prometheus target covers the whole gang and a dead rank flips
    its ``up`` gauge within one scrape interval.  Stdlib-only, daemon
    threads, and inert when the telemetry dir (portfile home) is
    unknown."""

    def __init__(self, tdir, num_workers, port):
        self.tdir = tdir
        self.num = num_workers  # supervisor updates on elastic resize
        outer = self

        class Handler(BaseHTTPRequestHandler):
            server_version = "mxnet-tpu-gang-metrics/1"

            def do_GET(self):  # noqa: N802 (http.server contract)
                route = self.path.split("?", 1)[0].rstrip("/") or "/"
                if route in ("/", "/metrics"):
                    code, ctype, body = outer.merged_metrics()
                elif route == "/healthz":
                    code, ctype, body = outer.merged_healthz()
                else:
                    code, ctype, body = (404, "text/plain; charset=utf-8",
                                         f"no such route {route!r}; try "
                                         "/metrics /healthz\n")
                payload = body.encode("utf-8", "replace")
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, fmt, *args):
                pass  # scrapes must not interleave with [rank N] logs

        # MX_METRICS_HOST (same knob the per-rank endpoint honors): the
        # merged endpoint is the one DESIGNED to be the external scrape
        # target — a cross-host Prometheus needs 0.0.0.0 here, while the
        # per-rank scrapes stay on 127.0.0.1 via the portfiles
        host = os.environ.get("MX_METRICS_HOST", "127.0.0.1")
        self._server = ThreadingHTTPServer((host, port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="gang-metrics", daemon=True)
        self._thread.start()

    def merged_metrics(self):
        scraped = _scrape_ranks(self.tdir, self.num, "/metrics")
        per_rank = {rank: (text if status == 200 else None)
                    for rank, (status, text) in scraped.items()}
        return (200,
                "application/openmetrics-text; version=1.0.0; "
                "charset=utf-8",
                _merge_expositions(per_rank))

    def merged_healthz(self):
        ranks = {}
        all_ok = True
        for rank, (status, text) in sorted(
                _scrape_ranks(self.tdir, self.num, "/healthz").items()):
            if status is None:
                ranks[rank] = {"healthy": False,
                               "reasons": [f"endpoint unreachable: {text}"]}
                all_ok = False
                continue
            try:
                ranks[rank] = json.loads(text)
            except ValueError:
                ranks[rank] = {"healthy": False,
                               "reasons": ["unparseable /healthz body"]}
            if not ranks[rank].get("healthy"):
                all_ok = False
        body = json.dumps({"healthy": all_ok,
                           "ranks": {str(r): v for r, v in ranks.items()}})
        return (200 if all_ok else 503, "application/json", body + "\n")

    def close(self):
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)


class _HeartbeatMonitor:
    """Poll per-rank heartbeat files so a hung/slow rank is diagnosed
    ("rank 2 last heartbeat 45s ago at step 130") BEFORE the gang is torn
    down, and echo each rank's flight-recorder tail after a failure.
    Inert when MX_TELEMETRY_DIR is unset."""

    def __init__(self, num_workers: int, env_extra=None):
        # workers see env_extra OVERLAID on our environ (_spawn_gang), so
        # the monitor must resolve the telemetry config the same way — a
        # programmatic launch_local(env_extra={"MX_TELEMETRY_DIR": ...})
        # must not leave the supervisor blind
        env = dict(os.environ)
        env.update(env_extra or {})
        self.dir = env.get("MX_TELEMETRY_DIR") or None
        try:
            hb = float(env.get("MX_HEARTBEAT_SEC", "5") or 5.0)
        except ValueError:
            hb = 5.0
        # several missed beats = stale; floor keeps sub-second test
        # configs from flagging healthy ranks on a loaded host
        self.stale_after = max(2.0, 5.0 * hb)
        self.num = num_workers
        self._stale = set()
        self._next_poll = 0.0
        self._gang_start = 0.0
        # rank -> parsed /statusz body captured by snapshot_statusz()
        # while the rank was still alive (before any kill), and the
        # /healthz verdict string captured at the same live moment —
        # diagnose() runs after every rank is reaped, when a live probe
        # could only ever say "endpoint unreachable"
        self._statusz = {}
        self._healthz = {}

    def gang_started(self) -> None:
        """Called at each (re)spawn: heartbeats older than this incarnation
        are leftovers of the previous gang, not evidence of a hung rank."""
        self._gang_start = time.time()
        self._stale.clear()
        # pre-teardown snapshots belong to ONE incarnation: a later
        # crash must not print a previous gang's state as its own
        self._statusz.clear()
        self._healthz.clear()
        # drop the previous incarnation's metrics portfiles too: the OS
        # can hand a dead rank's ephemeral port to ANOTHER rank of the
        # new gang, and a scrape through the stale file would attribute
        # that rank's exposition to the wrong (possibly dead) rank.
        # Workers rewrite their portfile at import.  Same hygiene for
        # the on-disk statusz-<R>.json snapshots: a reader of the final
        # post-mortem must not find a previous incarnation's state.
        if self.dir is not None:
            try:
                for name in os.listdir(self.dir):
                    if (name.startswith("metrics-port-")
                            or name.startswith("serve-port-")
                            or name.startswith("statusz-")) and \
                            name.endswith(".json"):
                        try:
                            os.unlink(os.path.join(self.dir, name))
                        except OSError:
                            pass
            except OSError:
                pass

    def _read(self, rank: int):
        try:
            with open(os.path.join(self.dir,
                                   f"heartbeat-{rank}.json")) as f:
                rec = json.load(f)
        except (OSError, ValueError):
            return None
        return rec if isinstance(rec, dict) else None

    def any_started(self) -> bool:
        """Whether any rank of THIS incarnation has heartbeat yet — arms
        the elastic regrow countdown on observed worker progress rather
        than on spawn (imports + rendezvous + restore would otherwise eat
        a fixed-from-spawn deadline).  Always False without telemetry."""
        if self.dir is None:
            return False
        for rank in range(self.num):
            rec = self._read(rank)
            if rec is not None and \
                    float(rec.get("time", 0.0)) >= self._gang_start:
                return True
        return False

    def poll(self) -> None:
        """Called from the supervision loop while the gang is alive;
        reports each staleness episode once (and recovery resets it)."""
        if self.dir is None:
            return
        now = time.monotonic()
        if now < self._next_poll:
            return
        self._next_poll = now + max(1.0, self.stale_after / 4.0)
        for rank in range(self.num):
            rec = self._read(rank)
            if rec is None:
                continue  # not started yet / no telemetry in the worker
            if float(rec.get("time", 0.0)) < self._gang_start:
                continue  # previous incarnation's heartbeat
            age = time.time() - float(rec.get("time", 0.0))
            if age > self.stale_after:
                if rank not in self._stale:
                    self._stale.add(rank)
                    # the one moment the distinction is live: a hung
                    # PROCESS keeps answering /healthz (503, stale
                    # heartbeat); a dead ENDPOINT refuses the connection
                    verdict = self._healthz_verdict(rank)
                    self._healthz[rank] = verdict
                    print(f"launch.py: rank {rank} last heartbeat "
                          f"{age:.1f}s ago at step {rec.get('step')} — "
                          f"suspect hung/slow rank; /healthz: {verdict}",
                          file=sys.stderr)
            else:
                self._stale.discard(rank)

    @staticmethod
    def _render_verdict(status, text) -> str:
        """One-line /healthz verdict from a (status, body) probe result
        — 'hung process' (stale heartbeat, endpoint answering) and
        'dead endpoint' (nothing listening) are different post-mortems
        and the supervisor log must distinguish them."""
        if status is None:
            return f"endpoint unreachable ({text})"
        try:
            snap = json.loads(text)
        except ValueError:
            return f"endpoint answered {status} with unparseable body"
        verdict = "ok" if snap.get("healthy") else \
            "; ".join(snap.get("reasons") or ["unhealthy"])
        return (f"{verdict} (HTTP {status}, step {snap.get('last_step')}, "
                f"inflight {snap.get('inflight_depth')})")

    def _healthz_verdict(self, rank) -> str:
        base = _rank_endpoint(self.dir, rank)
        if base is None:
            return "no live endpoint (MX_METRICS_PORT off or no portfile)"
        return self._render_verdict(*_http_get(f"{base}/healthz",
                                               timeout=1.0))

    def snapshot_statusz(self) -> None:
        """Snapshot /statusz from every rank whose endpoint still
        answers — called BEFORE the supervisor kills anything, so the
        survivors' live state (last steps, flight tails, in-flight
        depth) is preserved exactly as it was when a peer died.  Full
        bodies land in ``statusz-<R>.json`` next to the heartbeats;
        diagnose() echoes the one-line digest.  Both routes scrape all
        ranks CONCURRENTLY (_scrape_ranks): this runs on the teardown
        path, where several wedged ranks probed serially would delay
        SIGTERM by num_ranks x timeout right in the middle of the
        incident."""
        if self.dir is None:
            return
        healthz = _scrape_ranks(self.dir, self.num, "/healthz", timeout=1.0)
        statusz = _scrape_ranks(self.dir, self.num, "/statusz", timeout=1.0)
        for rank in range(self.num):
            status, text = healthz.get(rank, (None, "?"))
            if (status, text) != (None, "no metrics portfile"):
                # captured NOW, while an answer still means something —
                # by diagnose() time every rank is reaped and a live
                # probe can only say "endpoint unreachable"
                self._healthz.setdefault(
                    rank, self._render_verdict(status, text))
            status, text = statusz.get(rank, (None, ""))
            if status != 200:
                continue
            try:
                self._statusz[rank] = json.loads(text)
            except ValueError:
                continue
            try:
                with open(os.path.join(self.dir,
                                       f"statusz-{rank}.json"), "w") as f:
                    f.write(text)
            except OSError:
                pass

    def diagnose(self) -> None:
        """After a gang death: last heartbeat per rank + the live
        /healthz verdict + flight tail + the gang-wide trace report
        (straggler flags, step breakdown)."""
        if self.dir is None:
            return
        saw_events = False
        for rank in range(self.num):
            rec = self._read(rank)
            if rec is not None:
                age = time.time() - float(rec.get("time", 0.0))
                # prefer the verdict captured while the rank was alive
                # (poll's stale callout or the pre-teardown snapshot);
                # a live probe now only distinguishes "endpoint already
                # gone" from "endpoint outlived the process"
                verdict = self._healthz.get(rank) or \
                    self._healthz_verdict(rank)
                print(f"launch.py: rank {rank} last heartbeat {age:.1f}s "
                      f"ago at step {rec.get('step')}; /healthz: "
                      f"{verdict}", file=sys.stderr)
            snap = self._statusz.get(rank)
            if snap is not None:
                health = snap.get("health") or {}
                print(f"launch.py: rank {rank} pre-teardown /statusz "
                      f"(statusz-{rank}.json): step "
                      f"{health.get('last_step')}, inflight "
                      f"{health.get('inflight_depth')}, "
                      f"{len(snap.get('flight') or [])} flight events",
                      file=sys.stderr)
            tail = _flight_tail(self.dir, rank)
            if tail:
                saw_events = True
                print(f"launch.py: flight recorder tail (rank {rank}, "
                      f"last {len(tail)} events):", file=sys.stderr)
                for line in tail:
                    print(f"  {line}", file=sys.stderr)
                if any('"checkpoint_fallback"' in line for line in tail):
                    # a restore skipped a torn/corrupt step — point at
                    # the offline shard/digest audit for the WHY
                    print("launch.py: checkpoint fallback detected — "
                          "run tools/ckpt_report.py <ckpt-dir> to audit "
                          "shard files and digests", file=sys.stderr)
            # a rank that died on RESOURCE_EXHAUSTED left a memory
            # post-mortem — echo WHY next to the flight tail's WHERE
            oom = _oom_report(self.dir, rank)
            if oom is not None:
                _print_oom_report(oom, rank)
        if saw_events:
            _print_trace_report(self.dir)
            if _serving_streams_present(self.dir):
                # serving fleet post-mortem: the per-request view —
                # which requests never finished and inside which span
                # they died — is the serving analogue of the flight tail
                _print_serve_report(self.dir)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_gang(num_workers: int, command, env_extra, force_cpu: bool,
                port: int, restart_count: int):
    """Spawn the gang with piped stdout/stderr, teeing every line to our
    own streams under a `[rank N]` prefix.  Returns (procs, tee_threads).

    PYTHONUNBUFFERED keeps worker output line-granular through the pipe —
    a SIGKILLed rank must not take its last (block-buffered) lines of
    diagnosis down with it."""
    procs = []
    tees = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env["PYTHONUNBUFFERED"] = "1"
        env.update({
            "MX_COORDINATOR": f"127.0.0.1:{port}",
            "MX_NUM_PROCS": str(num_workers),
            "MX_PROC_ID": str(rank),
            # which gang incarnation this is (0 = first attempt) — read by
            # mxnet_tpu.fault's if-restart= qualifier and by worker logic
            # that must behave differently after a supervised restart
            "MX_RESTART_COUNT": str(restart_count),
            # reference spellings (kvstore rank/num_workers, user scripts)
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
        })
        if force_cpu:
            env["MX_FORCE_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # drop the axon sitecustomize so worker processes don't dial
            # the TPU relay at interpreter boot
            pp = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in pp.split(os.pathsep) if "axon" not in p)
        p = subprocess.Popen(command, env=env, stdout=subprocess.PIPE,
                             stderr=subprocess.PIPE, text=True,
                             errors="replace", bufsize=1)
        procs.append(p)
        for stream, sink in ((p.stdout, sys.stdout), (p.stderr, sys.stderr)):
            t = threading.Thread(target=_tee,
                                 args=(stream, sink, f"[rank {rank}] "),
                                 daemon=True)
            t.start()
            tees.append(t)
    return procs, tees


def _terminate_gang(procs, term_timeout: float = 10.0) -> None:
    """SIGTERM every live worker, wait up to term_timeout for the gang to
    exit (workers may be writing a final preemption checkpoint), then
    SIGKILL stragglers.  ALWAYS reaps — no zombies, whether we get here
    from a worker crash, restart teardown, or KeyboardInterrupt."""
    for p in procs:
        if p.poll() is None:
            try:
                p.send_signal(signal.SIGTERM)
            except OSError:
                pass
    deadline = time.monotonic() + term_timeout
    for p in procs:
        if p.poll() is not None:
            continue
        try:
            p.wait(timeout=max(0.0, deadline - time.monotonic()))
        except subprocess.TimeoutExpired:
            # a rank blocked in a native collective never sees SIGTERM's
            # python-level handler; escalate
            try:
                p.kill()
            except OSError:
                pass
    for p in procs:
        try:
            p.wait(timeout=10.0)
        except subprocess.TimeoutExpired:  # pragma: no cover — kill() sent
            pass


def _wait_gang(procs, term_timeout: float, monitor=None, regrow_after=None):
    """Poll ALL workers: a crash in any rank (not just the first) must fan
    out SIGTERM immediately, or the peers block forever in collectives
    waiting for the dead rank.  Returns ``(rc, planned)``: rc is the
    first non-zero exit code (the *cause*, not the exit of SIGTERMed
    peers), else 0; all procs reaped.  `monitor` (a _HeartbeatMonitor)
    is polled so a stale rank is called out while the gang still looks
    alive.

    ``regrow_after`` (seconds) is the elastic supervisor's planned-resize
    trigger: after that long of healthy running the gang is SIGTERMed
    (workers take their preemption checkpoints) and ``planned=True`` is
    returned — a regrow, not a failure.  With telemetry heartbeats
    available the countdown arms at the first beat of THIS incarnation
    (imports/rendezvous/restore must not eat the budget); without, it
    counts from spawn."""
    rc = 0
    deadline = None
    if regrow_after is not None and (monitor is None or monitor.dir is None):
        deadline = time.monotonic() + regrow_after
    alive = list(procs)
    while alive:
        if regrow_after is not None and deadline is None \
                and monitor.any_started():
            deadline = time.monotonic() + regrow_after
        if (deadline is not None and regrow_after is not None and rc == 0
                and len(alive) == len(procs)
                and time.monotonic() >= deadline):
            if monitor is not None:
                monitor.snapshot_statusz()
            _terminate_gang(alive, term_timeout)
            return 0, True
        for p in list(alive):
            r = p.poll()
            if r is None:
                continue
            alive.remove(p)
            if r != 0 and rc == 0:
                rc = r
                # survivors' live state BEFORE any kill: the statusz
                # snapshot is the only record of what the still-running
                # ranks were doing when the culprit died
                if monitor is not None:
                    monitor.snapshot_statusz()
                _terminate_gang(alive, term_timeout)
        if alive:
            if monitor is not None:
                monitor.poll()
            time.sleep(0.05)
    return rc, False


def _culprit_count(codes) -> int:
    """How many ranks of a dead gang look like the CAUSE rather than the
    teardown consequence: a SIGTERMed peer exits EXIT_PREEMPTED (handled
    preemption) or -SIGTERM/-SIGKILL (escalation), everything else —
    injected crashes (57), tracebacks (1), sys.exit(n) — is a culprit.
    At least 1: something killed the gang even if every exit looks like
    a consequence (e.g. a whole-gang preemption storm)."""
    culprits = sum(
        1 for c in codes
        if c not in (0, EXIT_PREEMPTED, -signal.SIGTERM, -signal.SIGKILL))
    return max(1, culprits)


def launch_local(num_workers: int, command, env_extra=None,
                 force_cpu: bool = False, max_restarts: int = 0,
                 term_timeout: float = 10.0, backoff: float = 1.0,
                 elastic: bool = False, min_workers: int = 1,
                 initial_workers=None, regrow_after: float = 0.0,
                 metrics_port=None) -> int:
    """Spawn num_workers processes of `command` on this host and supervise
    the gang: on any worker death the remaining ranks are torn down
    (SIGTERM, bounded wait, SIGKILL) and — up to max_restarts times — the
    whole gang is re-spawned on a FRESH coordinator port with exponential
    backoff, workers resuming from their latest valid checkpoint
    (docs/FAULT_TOLERANCE.md).  Returns 0, or the last failure's exit code
    after printing the per-rank exit history.

    Elastic mode (``elastic=True``, docs/FAULT_TOLERANCE.md §Elastic
    resize): ``num_workers`` becomes the TARGET world size and exhausting
    the restart budget no longer fails the job — the supervisor **shrinks**
    instead, re-rendezvousing the surviving ranks on a fresh port with a
    reduced ``MX_NUM_PROCS`` (one rank dropped per culprit of the last
    attempt, floor ``min_workers``) and a fresh restart budget.  The old
    world size is exported as ``MX_PREV_NUM_PROCS`` so workers know to
    rebuild their mesh/kvstore/step and reshard their checkpoint on
    restore.  ``initial_workers`` starts the gang below target (a fleet
    that came up degraded), and ``regrow_after > 0`` re-admits rank slots
    ONE at a time: after that many seconds of HEALTHY running below target
    the gang is deliberately preempted (SIGTERM → final checkpoints) and
    re-spawned one rank larger — a returned host joining back on
    probation.  The countdown re-arms at every world size below target,
    so growth steps +1 until the target is reached, and re-arms again
    whenever a LATER culprit shrinks the gang below target a second time
    (grow → shrink → grow cycles converge instead of sticking at the
    shrunken size).  A re-admitted rank that keeps dying simply shrinks
    the gang again (probation loop).  Only when the budget is exhausted
    AT ``min_workers`` does the job fail.

    ``metrics_port`` (``--metrics-port``; docs/OBSERVABILITY.md §Live
    metrics) serves a merged gang ``/metrics`` on that port (0 =
    ephemeral, logged): the supervisor discovers each rank's live
    endpoint via its ``metrics-port-<R>.json`` portfile under
    ``MX_TELEMETRY_DIR``, scrapes them on demand, and re-serves one
    exposition with per-rank ``up``/``mx_scrape_staleness_seconds``
    gauges; workers get ``MX_METRICS_PORT=0`` exported (ephemeral,
    unless the caller already pinned one)."""
    monitor = _HeartbeatMonitor(num_workers, env_extra)
    gang_metrics = None
    if metrics_port is not None:
        if monitor.dir is None:
            print("launch.py: --metrics-port needs MX_TELEMETRY_DIR (the "
                  "portfile home) — gang /metrics disabled", file=sys.stderr)
        else:
            try:
                gang_metrics = _GangMetricsServer(monitor.dir, num_workers,
                                                  metrics_port)
            except OSError as e:
                # observability must not take the launch down: same
                # policy as the per-rank endpoint's failed-bind warning
                print(f"launch.py: gang /metrics failed to bind port "
                      f"{metrics_port}: {e} — gang metrics disabled",
                      file=sys.stderr)
            else:
                print(f"launch.py: gang /metrics on "
                      f"http://127.0.0.1:{gang_metrics.port}/metrics "
                      "(merged per-rank scrape + up/staleness gauges)",
                      file=sys.stderr)
    try:
        return _supervise(num_workers, command, env_extra, force_cpu,
                          max_restarts, term_timeout, backoff, elastic,
                          min_workers, initial_workers, regrow_after,
                          monitor, gang_metrics)
    finally:
        if gang_metrics is not None:
            gang_metrics.close()


def _supervise(num_workers, command, env_extra, force_cpu, max_restarts,
               term_timeout, backoff, elastic, min_workers, initial_workers,
               regrow_after, monitor, gang_metrics):
    incarnation = 0      # cumulative MX_RESTART_COUNT across resizes
    attempt = 0          # restart budget used at the CURRENT world size
    target = num_workers
    world = min(target, max(1, int(initial_workers or target)))
    prev_world = None
    history = []  # (incarnation, world, [per-rank exit codes])
    while True:
        port = _free_port()
        monitor.num = world
        monitor.gang_started()
        if gang_metrics is not None:
            gang_metrics.num = world
        spawn_env = dict(env_extra or {})
        if gang_metrics is not None and "MX_METRICS_PORT" not in spawn_env \
                and not os.environ.get("MX_METRICS_PORT"):
            # workers bind ephemeral ports and advertise them via
            # portfiles; the supervisor's merged endpoint is the one
            # stable scrape target
            spawn_env["MX_METRICS_PORT"] = "0"
        if elastic:
            spawn_env["MX_ELASTIC"] = "1"
            if prev_world is not None and prev_world != world:
                # workers record the telemetry `resize` event and reshard
                # their restored checkpoints off this export
                spawn_env["MX_PREV_NUM_PROCS"] = str(prev_world)
        procs, tees = _spawn_gang(world, command, spawn_env, force_cpu,
                                  port, incarnation)
        # the resize export marks the FIRST incarnation after a resize
        # only — a later same-size restart is not a resize
        prev_world = None
        regrow = (regrow_after if (elastic and regrow_after > 0
                                   and world < target) else None)
        try:
            rc, planned = _wait_gang(procs, term_timeout, monitor,
                                     regrow_after=regrow)
        except KeyboardInterrupt:
            _terminate_gang(procs, term_timeout)
            return 130
        # drain the tee threads so every worker line lands BEFORE the
        # supervisor's own diagnosis/history output
        for t in tees:
            t.join(timeout=5.0)
        history.append((incarnation, world, [p.returncode for p in procs]))
        if planned:
            # regrow: the gang was healthy below target long enough —
            # preemption checkpoints are on disk, re-admit ONE rank slot
            # (not the full target in one jump: a partially-recovered
            # fleet re-checks stability at each size, and a re-admitted
            # host that is still bad costs one probation step, not a
            # full-gang thrash).  The countdown re-arms at the top of
            # the loop while world < target, so growth continues +1 at
            # a time — and re-starts from scratch whenever a later
            # culprit shrinks the gang below target again.
            prev_world, world = world, min(target, world + 1)
            incarnation += 1
            attempt = 0
            print(f"launch.py: growing gang {prev_world} -> {world} ranks "
                  f"(stable for {regrow_after:.1f}s below target "
                  f"{target}); re-rendezvous on a fresh port",
                  file=sys.stderr)
            continue
        if rc == 0:
            # every rank is reaped: the trace files are complete, so the
            # authoritative gang-wide merge happens HERE (rank 0's atexit
            # merge may have raced still-running peers)
            _reexport_trace(monitor.dir)
            return 0
        monitor.diagnose()
        if attempt >= max_restarts:
            if elastic and world > min_workers:
                codes = [p.returncode for p in procs]
                new_world = max(min_workers, world - _culprit_count(codes))
                prev_world, world = world, new_world
                incarnation += 1
                attempt = 0
                print(f"launch.py: restart budget exhausted at world size "
                      f"{prev_world}; shrinking gang {prev_world} -> "
                      f"{world} ranks (elastic resize), fresh restart "
                      f"budget, re-rendezvous in {backoff:.1f}s",
                      file=sys.stderr)
                time.sleep(backoff)
                continue
            _reexport_trace(monitor.dir)
            if max_restarts > 0 or elastic:
                print(f"launch.py: giving up after {len(history)} attempts; "
                      "per-rank exit history:", file=sys.stderr)
                for inc, w, codes in history:
                    print("  attempt %d (world %d): %s" % (inc, w, " ".join(
                        f"rank{i}={c}" + (
                            "(preempted)" if c == EXIT_PREEMPTED else "")
                        for i, c in enumerate(codes))), file=sys.stderr)
            return rc
        attempt += 1
        incarnation += 1
        delay = backoff * (2 ** (attempt - 1))
        cause = ("worker preempted" if rc == EXIT_PREEMPTED
                 else "worker died")
        print(f"launch.py: {cause} (exit {rc}); restarting gang "
              f"({attempt}/{max_restarts}) on a fresh port in {delay:.1f}s",
              file=sys.stderr)
        time.sleep(delay)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job.")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference CLI compat; ignored "
                         "(no parameter-server role in the SPMD design)")
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "mpi", "sge", "yarn"])
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin workers to the CPU backend (testing)")
    ap.add_argument("--max-restarts", type=int, default=0, metavar="N",
                    help="on any worker death, tear the gang down and "
                         "re-spawn it (fresh coordinator port, exponential "
                         "backoff) up to N times; workers resume from "
                         "their latest valid checkpoint")
    ap.add_argument("--term-timeout", type=float, default=10.0, metavar="S",
                    help="seconds to wait after SIGTERM before SIGKILL "
                         "when tearing down a gang (covers the final "
                         "preemption checkpoint)")
    ap.add_argument("--restart-backoff", type=float, default=1.0,
                    metavar="S", help="base of the exponential restart "
                                      "backoff (S, 2S, 4S, ...)")
    ap.add_argument("--elastic", action="store_true",
                    help="elastic gang resize: when the restart budget is "
                         "exhausted, SHRINK the gang to the surviving "
                         "ranks (reduced MX_NUM_PROCS, MX_PREV_NUM_PROCS "
                         "exported, fresh budget) instead of failing; "
                         "workers reshard their checkpoints on restore "
                         "(docs/FAULT_TOLERANCE.md §Elastic resize)")
    ap.add_argument("--min-workers", type=int, default=1, metavar="M",
                    help="elastic shrink floor: the job only fails once "
                         "the budget is exhausted at M ranks (default 1)")
    ap.add_argument("--initial-workers", type=int, default=None,
                    metavar="M", help="elastic: start the gang at M < N "
                                      "ranks (a fleet that came up "
                                      "degraded); pairs with "
                                      "--regrow-after to grow toward -n")
    ap.add_argument("--metrics-port", type=int, default=None, metavar="P",
                    help="serve a merged gang /metrics (+ /healthz) on "
                         "port P (0 = ephemeral, logged at startup): "
                         "per-rank live endpoints are discovered via "
                         "metrics-port-<R>.json portfiles under "
                         "MX_TELEMETRY_DIR and re-served as one "
                         "exposition with per-rank up/staleness gauges "
                         "(docs/OBSERVABILITY.md §Live metrics)")
    ap.add_argument("--serve-port", type=int, default=None, metavar="P",
                    help="export MX_SERVE_PORT=P to workers (0 = "
                         "ephemeral): serving replicas bind P+rank and "
                         "advertise serve-port-<R>.json portfiles under "
                         "MX_TELEMETRY_DIR for router discovery "
                         "(docs/SERVING.md §Front door)")
    ap.add_argument("--regrow-after", type=float, default=0.0, metavar="S",
                    help="elastic: after S seconds of healthy running "
                         "below the -n target, preempt the gang (final "
                         "checkpoints) and re-spawn ONE rank larger, "
                         "repeating (with a fresh countdown at each "
                         "size) until the target is reached; re-arms "
                         "after any later shrink — the grow half of the "
                         "resize (default 0 = never)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run on every worker")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    command = args.command[1:] if args.command[0] == "--" else args.command
    if args.launcher != "local":
        ap.error(f"launcher {args.launcher!r} is cluster bring-up; supply "
                 "MX_COORDINATOR/MX_NUM_PROCS/MX_PROC_ID via your scheduler "
                 "(pod spec) instead — see module docstring")
    if args.num_servers:
        print("launch.py: -s/--num-servers ignored (no PS role on TPU)",
              file=sys.stderr)
    if args.max_restarts < 0:
        ap.error("--max-restarts must be >= 0")
    if args.metrics_port is not None and args.metrics_port < 0:
        ap.error("--metrics-port must be >= 0 (0 = ephemeral)")
    if args.serve_port is not None and args.serve_port < 0:
        ap.error("--serve-port must be >= 0 (0 = ephemeral)")
    if args.min_workers < 1 or args.min_workers > args.num_workers:
        ap.error("--min-workers must be in [1, num-workers]")
    if args.initial_workers is not None and not (
            args.min_workers <= args.initial_workers <= args.num_workers):
        ap.error("--initial-workers must be in [min-workers, num-workers]")
    if (args.initial_workers is not None or args.regrow_after > 0) \
            and not args.elastic:
        ap.error("--initial-workers/--regrow-after require --elastic")
    env_extra = None
    if args.serve_port is not None:
        # workers read MX_SERVE_PORT at ReplicaServer construction;
        # N binds N+rank, 0 = ephemeral + portfile advertisement
        env_extra = {"MX_SERVE_PORT": str(args.serve_port)}
    return launch_local(args.num_workers, command, env_extra=env_extra,
                        force_cpu=args.force_cpu,
                        max_restarts=args.max_restarts,
                        term_timeout=args.term_timeout,
                        backoff=args.restart_backoff,
                        elastic=args.elastic,
                        min_workers=args.min_workers,
                        initial_workers=args.initial_workers,
                        regrow_after=args.regrow_after,
                        metrics_port=args.metrics_port)


if __name__ == "__main__":
    sys.exit(main())
