#!/usr/bin/env python
"""Distributed job launcher (reference: tools/launch.py ~L1-200 +
3rdparty/dmlc-core/tracker/dmlc_tracker — scheduler/server/worker spawn with
DMLC_* env).

TPU-native redesign: there is no parameter-server role — every process is a
worker; rendezvous is jax.distributed's coordination service (worker 0 hosts
it) and aggregation is compiled XLA collectives (mxnet_tpu/parallel/dist.py).
The reference CLI is kept so launch scripts port unchanged:

    python tools/launch.py -n 4 --launcher local python train.py --kv-store dist_sync

Launchers:
  local  N worker processes on this host (the reference's dmlc_tracker
         'local' mode, used by its nightly dist tests) — implemented.
  ssh/mpi/yarn/sge  cluster bring-up: out of scope here; on GKE/Cloud the
         per-host env is provided by the pod spec (MX_COORDINATOR etc.),
         so no tracker is needed (SURVEY §2.4 launcher row).

Both MX_* and DMLC_* env spellings are exported to workers.
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys
import time


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def launch_local(num_workers: int, command, env_extra=None,
                 force_cpu: bool = False) -> int:
    """Spawn num_workers processes of `command` on this host; returns the
    first non-zero exit code (killing the rest), else 0."""
    port = _free_port()
    procs = []
    for rank in range(num_workers):
        env = dict(os.environ)
        env.update(env_extra or {})
        env.update({
            "MX_COORDINATOR": f"127.0.0.1:{port}",
            "MX_NUM_PROCS": str(num_workers),
            "MX_PROC_ID": str(rank),
            # reference spellings (kvstore rank/num_workers, user scripts)
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(num_workers),
            "DMLC_NUM_SERVER": "0",
            "DMLC_WORKER_ID": str(rank),
        })
        if force_cpu:
            env["MX_FORCE_CPU"] = "1"
            env["JAX_PLATFORMS"] = "cpu"
            # drop the axon sitecustomize so worker processes don't dial
            # the TPU relay at interpreter boot
            pp = env.get("PYTHONPATH", "")
            env["PYTHONPATH"] = os.pathsep.join(
                p for p in pp.split(os.pathsep) if "axon" not in p)
        procs.append(subprocess.Popen(command, env=env))

    rc = 0
    try:
        # poll ALL workers: a crash in any rank (not just the first) must
        # fan out SIGTERM immediately, or the peers block forever in
        # collectives waiting for the dead rank
        alive = list(procs)
        while alive:
            for p in list(alive):
                r = p.poll()
                if r is None:
                    continue
                alive.remove(p)
                if r != 0 and rc == 0:
                    rc = r
                    for q in alive:
                        q.send_signal(signal.SIGTERM)
            if alive:
                time.sleep(0.05)
    except KeyboardInterrupt:
        for q in procs:
            if q.poll() is None:
                q.send_signal(signal.SIGTERM)
        rc = 130
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Launch a distributed mxnet_tpu job.")
    ap.add_argument("-n", "--num-workers", type=int, required=True,
                    help="number of worker processes")
    ap.add_argument("-s", "--num-servers", type=int, default=0,
                    help="accepted for reference CLI compat; ignored "
                         "(no parameter-server role in the SPMD design)")
    ap.add_argument("--launcher", default="local",
                    choices=["local", "ssh", "mpi", "sge", "yarn"])
    ap.add_argument("--force-cpu", action="store_true",
                    help="pin workers to the CPU backend (testing)")
    ap.add_argument("command", nargs=argparse.REMAINDER,
                    help="command to run on every worker")
    args = ap.parse_args(argv)
    if not args.command:
        ap.error("no command given")
    command = args.command[1:] if args.command[0] == "--" else args.command
    if args.launcher != "local":
        ap.error(f"launcher {args.launcher!r} is cluster bring-up; supply "
                 "MX_COORDINATOR/MX_NUM_PROCS/MX_PROC_ID via your scheduler "
                 "(pod spec) instead — see module docstring")
    if args.num_servers:
        print("launch.py: -s/--num-servers ignored (no PS role on TPU)",
              file=sys.stderr)
    return launch_local(args.num_workers, command, force_cpu=args.force_cpu)


if __name__ == "__main__":
    sys.exit(main())
