#!/usr/bin/env python3
"""mxlint — project-specific AST lint + lightweight race detector.

Six PRs of hard-won correctness rules live in comments and CHANGES.md;
this tool encodes them mechanically (the TVM/Relay move: check graph-
program invariants on every build instead of re-learning them in review).
Stdlib-only, like launch.py and trace_report.py.

Rules (each descends from a real bug — docs/STATIC_ANALYSIS.md has the
full catalog with provenance):

  hot-sync             host readback (np.asarray / .item() / float() /
                       jax.device_get / block_until_ready) or memory
                       polling (.memory_stats() / jax.live_arrays() /
                       .memory_analysis() — PR 8: sample via memwatch at
                       step boundaries) reachable from a per-step
                       dispatch body (PR 4: one stray sync stalls the
                       whole async pipeline)
  raw-shard-map        any shard_map import/call outside
                       parallel/sharding.py's shard_map_compat shim
                       (PR 2: raw jax.shard_map fails on the pinned jax)
  wall-clock-duration  subtracting two time.time() reads for a duration
                       (PR 2: wall-clock steps gave negative samples/sec)
  retrace-hazard       jax.jit constructed inside a per-step function, or
                       an unhashable literal passed in a static_argnums
                       position (retrace storm / TypeError at runtime)
  signal-unsafe        import / lock-acquire / open() lexically inside a
                       registered signal handler (PR 1/4: imports take
                       the import lock; a handler interrupting an import
                       deadlocks)
  thread-shared-write  an attribute assigned both from a thread worker
                       and from consumer methods with no common lock
  silent-except        broad `except: pass` with no telemetry record and
                       no justification comment
  env-unregistered     a quoted MX_*/MXNET_* use-site absent from
                       env_vars.ENV_VARS (registry drift guard)
  jax-in-handler       jax import/use reachable from a declared jax-free
                       handler entry point (PR 13: the metrics endpoint
                       serves from the telemetry recorder's locked
                       rollups on a daemon thread — touching jax there
                       can deadlock runtime init or force a device sync
                       under the training loop); these entries also get
                       the full hot-sync readback checks

Suppression: `# mxlint: disable=rule[,rule] <justification>` on the
flagged line (or alone on the line above) silences the finding; an
unknown rule name in a suppression is itself a finding (bad-suppression).
Accepted legacy findings live in tools/mxlint_baseline.json, each entry
carrying a one-line justification.

Exit codes: 0 clean, 2 usage error, 3 findings.
"""
from __future__ import annotations

import argparse
import ast
import io
import json
import os
import re
import sys
import time
import tokenize

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_BASELINE = os.path.join(REPO, "tools", "mxlint_baseline.json")
DEFAULT_PATHS = ("mxnet_tpu", "tools", "examples")

RULES = {
    "hot-sync": "host readback reachable from a per-step dispatch body",
    "raw-shard-map": "shard_map outside parallel/sharding.py's compat shim",
    "wall-clock-duration": "time.time() subtraction used as a duration",
    "retrace-hazard": "jax.jit built per step / unhashable static argument",
    "signal-unsafe": "import, lock acquire or open() inside a signal handler",
    "thread-shared-write": "attribute written by worker thread and consumer "
                           "without a common lock",
    "silent-except": "broad except:pass with no telemetry or justification",
    "env-unregistered": "quoted MX_*/MXNET_* use-site not in ENV_VARS",
    "jax-in-handler": "jax import/use reachable from a jax-free handler "
                      "entry point",
    "bad-suppression": "mxlint suppression naming an unknown rule",
    "stale-hot-entry": "configured hot-path entry point no longer resolves",
    "pass-outside-pipeline": "op-dispatch body consults module-global pass "
                             "state outside the pass-hook protocol",
    "syntax-error": "file failed to parse",
}

# per-step dispatch bodies: the hot-sync / retrace-hazard reachability
# analysis starts here (repo-relative path -> function qualnames)
HOT_PATH_ENTRIES = {
    "mxnet_tpu/parallel/data_parallel.py": (
        "DataParallelStep._step_impl", "DataParallelStep.stage",
        # superstep mode: the group dispatch body and the scan-body
        # builder (its nested lax.scan body is the hottest path in the
        # tree — K steps per dispatch ride through it)
        "DataParallelStep._superstep_impl", "DataParallelStep._super_fn",
        # the unified Plan dispatch body: EVERY compiled-step execution
        # (single step or superstep, any strategy Plan) funnels through
        # it — a host sync here would stall every strategy at once
        "DataParallelStep._plan_dispatch"),
    "mxnet_tpu/optimizer/fused.py": ("FusedUpdater._apply_impl",),
    # precision subsystem (docs/PRECISION.md): the fused overflow reduce
    # the eager loss-scale shim dispatches per step, and the int8
    # adapter's decode body (the trace body of the ONE quantized decode
    # executable — a host sync here would land inside engine tracing or
    # stall the serving pipeline)
    "mxnet_tpu/precision/loss_scale.py": ("overflow_flag",),
    "mxnet_tpu/precision/quantize.py": ("_RewriteAdapterBase.decode",),
    # the eager AMP compatibility shim: scale_loss/has_overflow run per
    # Trainer step — the PR 15 fix replaced its per-gradient asnumpy()
    # loop with ONE fused device reduce; these entries keep the old
    # readback pattern from creeping back in
    "mxnet_tpu/contrib/amp/amp.py": ("DynamicLossScaler.has_overflow",
                                     "unscale"),
    "mxnet_tpu/parallel/async_loss.py": (
        "InflightRing.make_room", "InflightRing.admit",
        "InflightRing.discard"),
    "mxnet_tpu/kvstore.py": ("KVStore.push_bucketed",),
    # serving engine: the per-step decode dispatch body — chains device
    # state through the compiled step and admits the lazy token handle;
    # a host sync here would serialize the whole serving pipeline.  The
    # front-door additions ride the same contract: the speculative
    # verify dispatch (_dispatch_spec) and the jitted trace bodies
    # (sampled decode, K-token verify, prefix ingest) are per-step code
    # — a readback inside any of them stalls every in-flight request
    "mxnet_tpu/serving/engine.py": (
        "ServingEngine._dispatch_step", "ServingEngine._dispatch_spec",
        "ServingEngine._decode_body", "ServingEngine._verify_body",
        "ServingEngine._ingest_body"),
}

# THE pass-pipeline consultation point (docs/PRECISION.md §Pass
# pipeline): repo-relative path -> the op-dispatch body, the hook-module
# alias it must consult, and the (module-alias, _attr) loads it is
# allowed.  Any OTHER `<module>._underscore` load inside the dispatch
# body is a graph pass smuggled around the pipeline — a module global
# the pipeline fingerprint cannot see, exactly the one-off pattern the
# pass registry absorbed.  Like HOT_PATH_ENTRIES, a stale entry (the
# body renamed away, or the hook consultation deleted) fails loudly
# instead of turning the rule into a silent no-op.
PASS_DISPATCH_ENTRIES = {
    "mxnet_tpu/ops/registry.py": {
        "function": "_invoke_impl",
        "hook_module": "_pass_hooks",
        "allowed": (("_pass_hooks", "_OP_HOOKS"),
                    # the row-sparse Embedding cotangent type — autograd
                    # tape plumbing, not trace-rewrite state
                    ("autograd", "_RowSparseCT")),
    },
}

# HTTP handler threads that must NEVER touch jax (repo-relative path ->
# function qualnames): the live metrics endpoint serves the telemetry
# recorder's locked rollups only — a jax import there can deadlock
# against runtime init, and any device readback stalls the training
# loop from a scrape.  Reachable functions get the hot-sync readback
# checks PLUS a lexical jax import/alias-use scan (jax-in-handler).
JAX_FREE_ENTRIES = {
    "mxnet_tpu/metrics_server.py": ("_Handler.do_GET",),
    # serving front door: replica + router HTTP handlers only build
    # Request objects, poll host-side stream flags and relay JSON — the
    # engine-driver thread owns the device.  A jax import here can
    # deadlock against runtime init; a readback stalls decode from an
    # HTTP request
    "mxnet_tpu/serving/router.py": (
        "_ReplicaHandler.do_GET", "_ReplicaHandler.do_POST",
        "_RouterHandler.do_GET", "_RouterHandler.do_POST"),
}

# the shard_map_compat shim's home — the ONLY file allowed to touch
# jax.shard_map directly
SHARD_MAP_HOME = "mxnet_tpu/parallel/sharding.py"

# env-unregistered applies where the registry contract always has:
# the package and the tools (examples set vars, they don't define knobs)
ENV_RULE_PREFIXES = ("mxnet_tpu", "tools")

_ENV_NAME = re.compile(r"^MX(?:NET)?_[A-Z][A-Z0-9_]*$")
_SUPPRESS = re.compile(r"#\s*mxlint:\s*disable=([A-Za-z0-9_,\- ]+)")

# attribute calls that force a device->host round-trip
SYNC_ATTRS = frozenset({"item", "asnumpy", "asscalar", "block_until_ready",
                        "device_get"})
# memory-introspection calls (PR 8): cheap-ish individually, but
# memory_stats() round-trips PjRt, live_arrays() walks every live buffer,
# and memory_analysis() XLA-compiles — none belong in a per-step dispatch
# body; sample at step boundaries via mxnet_tpu.memwatch instead
MEM_ATTRS = frozenset({"memory_stats", "memory_analysis", "live_arrays"})


class Finding:
    __slots__ = ("rule", "path", "line", "col", "context", "message")

    def __init__(self, rule, path, line, col, context, message):
        self.rule = rule
        self.path = path
        self.line = line
        self.col = col
        self.context = context
        self.message = message

    def as_dict(self):
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "context": self.context,
                "message": self.message}

    def render(self):
        loc = f"{self.path}:{self.line}:{self.col}"
        ctx = f" [{self.context}]" if self.context else ""
        return f"{loc}: {self.rule}: {self.message}{ctx}"


# ---------------------------------------------------------------------------
# scope / alias helpers
# ---------------------------------------------------------------------------
class _Scopes(ast.NodeVisitor):
    """Collect every function with a dotted qualname, its enclosing class,
    and module-level import aliases."""

    def __init__(self):
        self.functions = {}        # qualname -> FunctionDef
        self.func_class = {}       # qualname -> class name or None
        self.classes = {}          # class name -> ClassDef
        self.mod_aliases = {}      # local alias -> dotted module
        self.from_names = {}       # local name -> "module.attr"
        self._stack = []           # (kind, name)

    # -- imports ----------------------------------------------------------
    def visit_Import(self, node):
        for a in node.names:
            self.mod_aliases[a.asname or a.name.split(".")[0]] = a.name
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module:
            for a in node.names:
                self.from_names[a.asname or a.name] = \
                    f"{node.module}.{a.name}"
        self.generic_visit(node)

    # -- scopes -----------------------------------------------------------
    def _qual(self, name):
        return ".".join([n for _k, n in self._stack] + [name])

    def visit_ClassDef(self, node):
        self.classes.setdefault(node.name, node)
        self._stack.append(("class", node.name))
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node):
        qual = self._qual(node.name)
        self.functions.setdefault(qual, node)
        cls = None
        for kind, name in reversed(self._stack):
            if kind == "class":
                cls = name
                break
        self.func_class.setdefault(qual, cls)
        self._stack.append(("func", node.name))
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


def _call_name(node):
    """('name', n) for foo(...), ('self', m) for self.m(...), ('attr', m)
    for anything_else.m(...), else None."""
    if not isinstance(node, ast.Call):
        return None
    f = node.func
    if isinstance(f, ast.Name):
        return ("name", f.id)
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name) and f.value.id == "self":
            return ("self", f.attr)
        return ("attr", f.attr)
    return None


def _is_module_call(node, scopes, module, attr):
    """Is `node` a Call of <module>.<attr> under any local alias (including
    `from module import attr [as x]`)?"""
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == attr and \
            isinstance(f.value, ast.Name):
        mod = scopes.mod_aliases.get(f.value.id)
        return mod == module or (mod or "").startswith(module + ".")
    if isinstance(f, ast.Name):
        return scopes.from_names.get(f.id) == f"{module}.{attr}"
    return False


def _docstring_nodes(nodes):
    """The Constant nodes that are documentation, not use-sites."""
    out = set()
    for node in nodes:
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and \
                    isinstance(body[0].value, ast.Constant) and \
                    isinstance(body[0].value.value, str):
                out.add(id(body[0].value))
    return out


# ---------------------------------------------------------------------------
# per-file analysis
# ---------------------------------------------------------------------------
class FileLint:
    def __init__(self, abspath, relpath, text, env_registry, hot_entries,
                 active_rules, jax_free_entries=None, pass_entries=None):
        self.path = relpath
        self.text = text
        self.lines = text.splitlines()
        self.env_registry = env_registry
        self.hot_entries = hot_entries
        self.jax_free = (jax_free_entries if jax_free_entries is not None
                         else JAX_FREE_ENTRIES)
        self.pass_entries = (pass_entries if pass_entries is not None
                             else PASS_DISPATCH_ENTRIES)
        self.active = active_rules
        self.findings = []
        self.suppressed = 0
        self.tree = None
        self.comments = {}        # line -> comment text
        self.suppress_lines = {}  # line -> set of rule names
        try:
            self.tree = ast.parse(text, filename=relpath)
        except SyntaxError as e:
            self._emit("syntax-error", e.lineno or 1, 0, None,
                       f"does not parse: {e.msg}")
            return
        self._scan_comments()
        self.scopes = _Scopes()
        self.scopes.visit(self.tree)
        # one flat walk per file (and one per function, cached): the rule
        # passes share these instead of re-walking the tree ~7 times
        self.all_nodes = list(ast.walk(self.tree))
        self._fn_nodes = {}
        self.docstrings = _docstring_nodes(self.all_nodes)

    # -- plumbing ----------------------------------------------------------
    def _emit(self, rule, line, col, context, message):
        if rule not in self.active:
            return
        self.findings.append(
            Finding(rule, self.path, line, col, context or "", message))

    def _scan_comments(self):
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                line = tok.start[0]
                self.comments[line] = tok.string
                m = _SUPPRESS.search(tok.string)
                if not m:
                    continue
                rules = [r.strip() for r in m.group(1).split(",")]
                rules = [r for r in rules if r]
                # each piece's first word is a rule name; trailing words in
                # a piece start the justification, and once a justification
                # has started, later comma-separated fragments belong to it
                # ("disable=hot-sync, staged input path" must not read
                # 'staged' as a rule).  A lone unknown word IS a finding —
                # a typo'd suppression must not silently do nothing.
                names = set()
                for i, r in enumerate(rules):
                    words = r.split()
                    name = words[0] if words else r
                    if name in RULES:
                        names.add(name)
                        if len(words) > 1:
                            break  # justification text begins here
                    elif i > 0 and len(words) > 1:
                        break      # multi-word fragment = justification
                    else:
                        self._emit("bad-suppression", line, tok.start[1],
                                   None,
                                   f"suppression names unknown rule "
                                   f"{name!r} (known: "
                                   f"{', '.join(sorted(RULES))})")
                own_line = tok.string.strip() == \
                    self.lines[line - 1].strip() if line <= len(self.lines) \
                    else False
                if not own_line:     # trailing comment: covers its line
                    self.suppress_lines.setdefault(line, set()).update(names)
                    continue
                # own-line comment: attaches to the next CODE line, skipping
                # blank lines and the justification's continuation comments
                target = line + 1
                while target <= len(self.lines):
                    stripped = self.lines[target - 1].strip()
                    if stripped and not stripped.startswith("#"):
                        break
                    target += 1
                self.suppress_lines.setdefault(target, set()).update(names)
        except tokenize.TokenizeError:
            pass

    def _nodes_in(self, fn):
        nodes = self._fn_nodes.get(id(fn))
        if nodes is None:
            nodes = self._fn_nodes[id(fn)] = list(ast.walk(fn))
        return nodes

    def _apply_suppressions(self):
        # findings are reported at a node's first line; a suppression on
        # that line (trailing comment) or alone on the line above (mapped
        # to the next line by _scan_comments) matches.
        # Dedupe first: a nested function's body is walked both as part of
        # its enclosing function and as its own scope entry, so one defect
        # can be emitted twice with different contexts — keep the first
        # (outermost) so the baseline needs exactly one entry per site.
        seen, unique = set(), []
        for f in self.findings:
            key = (f.rule, f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                unique.append(f)
        kept = []
        for f in unique:
            if f.rule != "bad-suppression" and \
                    f.rule in self.suppress_lines.get(f.line, ()):
                self.suppressed += 1
            else:
                kept.append(f)
        self.findings = kept

    # -- driver ------------------------------------------------------------
    def run(self):
        if self.tree is None:
            return self.findings
        passes = (
            ("env-unregistered", self.rule_env_unregistered),
            ("raw-shard-map", self.rule_raw_shard_map),
            ("wall-clock-duration", self.rule_wall_clock_duration),
            ("silent-except", self.rule_silent_except),
            ("signal-unsafe", self.rule_signal_unsafe),
            ("thread-shared-write", self.rule_thread_shared_write),
            # hot-sync + retrace-hazard share the reachability pass
            ("hot-sync", self.rule_hot_path),
            ("retrace-hazard", self.rule_static_argnums),
            ("jax-in-handler", self.rule_jax_free),
            ("pass-outside-pipeline", self.rule_pass_pipeline),
        )
        for rule, fn in passes:
            if rule in self.active or (
                    rule == "hot-sync" and "retrace-hazard" in self.active):
                fn()
        self._apply_suppressions()
        return self.findings

    # -- env-unregistered --------------------------------------------------
    def rule_env_unregistered(self):
        if self.env_registry is None:
            return
        if not any(self.path == p or self.path.startswith(p + "/")
                   for p in ENV_RULE_PREFIXES):
            return
        for node in self.all_nodes:
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            if id(node) in self.docstrings:
                continue
            if _ENV_NAME.match(node.value) and \
                    node.value not in self.env_registry:
                self._emit(
                    "env-unregistered", node.lineno, node.col_offset, None,
                    f"env var {node.value!r} is read/exported here but not "
                    f"registered in mxnet_tpu/env_vars.py ENV_VARS (add an "
                    f"entry with disposition + use-site)")

    # -- raw-shard-map -----------------------------------------------------
    def rule_raw_shard_map(self):
        if self.path == SHARD_MAP_HOME:
            return
        for node in self.all_nodes:
            if isinstance(node, ast.ImportFrom) and node.module and \
                    "shard_map" in node.module:
                self._emit("raw-shard-map", node.lineno, node.col_offset,
                           None,
                           "import of jax shard_map outside "
                           f"{SHARD_MAP_HOME} — use shard_map_compat "
                           "(raw jax.shard_map breaks on the pinned jax)")
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    if a.name == "shard_map" and \
                            "sharding" not in node.module:
                        self._emit("raw-shard-map", node.lineno,
                                   node.col_offset, None,
                                   "import of shard_map outside "
                                   f"{SHARD_MAP_HOME} — use shard_map_compat")
            if isinstance(node, ast.Attribute) and node.attr == "shard_map":
                self._emit("raw-shard-map", node.lineno, node.col_offset,
                           None,
                           "direct jax.shard_map use — route through "
                           "parallel/sharding.py shard_map_compat")

    # -- wall-clock-duration ----------------------------------------------
    def _is_wall_call(self, node):
        return _is_module_call(node, self.scopes, "time", "time")

    def rule_wall_clock_duration(self):
        # class-level: attrs assigned self.X = time.time() anywhere in the
        # class taint `time.time() - self.X` in every method
        class_wall_attrs = {}
        for qual, fn in self.scopes.functions.items():
            cls = self.scopes.func_class.get(qual)
            if cls is None:
                continue
            for node in self._nodes_in(fn):
                if isinstance(node, ast.Assign) and \
                        self._is_wall_call(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Attribute) and \
                                isinstance(t.value, ast.Name) and \
                                t.value.id == "self":
                            class_wall_attrs.setdefault(cls, set()).add(
                                t.attr)

        for qual, fn in self.scopes.functions.items():
            cls = self.scopes.func_class.get(qual)
            tainted = set()
            for node in self._nodes_in(fn):
                if isinstance(node, ast.Assign) and \
                        self._is_wall_call(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            tainted.add(t.id)

            def _wall(expr):
                if self._is_wall_call(expr):
                    return True
                if isinstance(expr, ast.Name) and expr.id in tainted:
                    return True
                if isinstance(expr, ast.Attribute) and \
                        isinstance(expr.value, ast.Name) and \
                        expr.value.id == "self" and \
                        expr.attr in class_wall_attrs.get(cls, ()):
                    return True
                return False

            for node in self._nodes_in(fn):
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub) and \
                        _wall(node.left) and _wall(node.right):
                    self._emit(
                        "wall-clock-duration", node.lineno, node.col_offset,
                        qual,
                        "duration from two time.time() reads — wall clock "
                        "can step (NTP) and gave negative samples/sec; use "
                        "time.perf_counter() (keep time.time() only for "
                        "cross-process wall stamps)")

    # -- silent-except -----------------------------------------------------
    def _is_broad(self, handler):
        t = handler.type
        if t is None:
            return True
        names = []
        if isinstance(t, ast.Tuple):
            names = [getattr(e, "id", getattr(e, "attr", "")) for e
                     in t.elts]
        else:
            names = [getattr(t, "id", getattr(t, "attr", ""))]
        return any(n in ("Exception", "BaseException") for n in names)

    def rule_silent_except(self):
        for node in self.all_nodes:
            if not isinstance(node, ast.Try):
                continue
            for handler in node.handlers:
                if not all(isinstance(s, ast.Pass) for s in handler.body):
                    continue
                if not self._is_broad(handler):
                    continue
                last = max(s.lineno for s in handler.body)
                if any(ln in self.comments
                       for ln in range(handler.lineno, last + 1)):
                    continue  # justified in place
                self._emit(
                    "silent-except", handler.lineno, handler.col_offset,
                    None,
                    "broad except swallowed with bare pass — narrow the "
                    "exception type, record via telemetry, or add a "
                    "justification comment")

    # -- signal-unsafe -----------------------------------------------------
    def rule_signal_unsafe(self):
        handlers = []
        for node in self.all_nodes:
            if _is_module_call(node, self.scopes, "signal", "signal") and \
                    len(node.args) >= 2:
                h = node.args[1]
                if isinstance(h, ast.Name):
                    handlers.append(h.id)
        if not handlers:
            return
        for qual, fn in self.scopes.functions.items():
            if fn.name not in handlers:
                continue
            for node in self._nodes_in(fn):
                if isinstance(node, (ast.Import, ast.ImportFrom)):
                    self._emit(
                        "signal-unsafe", node.lineno, node.col_offset, qual,
                        "import inside a registered signal handler — the "
                        "import machinery takes a lock the interrupted "
                        "thread may hold; use sys.modules.get() for "
                        "already-imported modules")
                elif isinstance(node, ast.Call):
                    cn = _call_name(node)
                    if cn and cn[0] == "name" and cn[1] == "__import__":
                        self._emit("signal-unsafe", node.lineno,
                                   node.col_offset, qual,
                                   "__import__ inside a signal handler")
                    elif _is_module_call(node, self.scopes, "importlib",
                                         "import_module"):
                        self._emit("signal-unsafe", node.lineno,
                                   node.col_offset, qual,
                                   "importlib.import_module inside a "
                                   "signal handler")
                    elif cn and cn[0] == "name" and cn[1] == "open":
                        self._emit("signal-unsafe", node.lineno,
                                   node.col_offset, qual,
                                   "open() inside a signal handler — file "
                                   "IO can block/allocate at an arbitrary "
                                   "interruption point")
                    elif cn and cn[0] == "attr" and cn[1] == "acquire":
                        self._emit("signal-unsafe", node.lineno,
                                   node.col_offset, qual,
                                   "lock acquire inside a signal handler — "
                                   "deadlocks when the interrupted thread "
                                   "holds the lock")

    # -- thread-shared-write ----------------------------------------------
    def _lock_attrs(self, cls_node):
        locks = set()
        for node in ast.walk(cls_node):
            if isinstance(node, ast.Assign):
                val = node.value
                is_lock = (
                    isinstance(val, ast.Call)
                    and isinstance(val.func, ast.Attribute)
                    and val.func.attr in ("Lock", "RLock", "Condition"))
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        if is_lock or "lock" in t.attr.lower():
                            locks.add(t.attr)
        return locks

    def _self_writes(self, fn, lock_attrs):
        """[(attr, frozenset(held locks), lineno)] for self.X assignments
        lexically inside `fn` (nested defs included: closures over self)."""
        out = []

        def walk(node, held):
            if isinstance(node, ast.With):
                extra = set()
                for item in node.items:
                    ce = item.context_expr
                    if isinstance(ce, ast.Attribute) and \
                            isinstance(ce.value, ast.Name) and \
                            ce.value.id == "self" and ce.attr in lock_attrs:
                        extra.add(ce.attr)
                for child in node.body:
                    walk(child, held | extra)
                return
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        out.append((t.attr, frozenset(held), node.lineno))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for stmt in fn.body:
            walk(stmt, frozenset())
        return out

    def _worker_funcs(self, cls_name, cls_node, methods):
        """Qualnames of worker-side functions for a class: Thread targets
        plus `_produce` on _ThreadedIter subclasses, closed over self-call
        reachability within the class."""
        workers = set()
        bases = [getattr(b, "id", getattr(b, "attr", "")) for b
                 in cls_node.bases]
        if any("ThreadedIter" in b for b in bases) and \
                f"{cls_name}._produce" in methods:
            workers.add(f"{cls_name}._produce")
        for qual, fn in methods.items():
            for node in self._nodes_in(fn):
                if not (isinstance(node, ast.Call)
                        and _is_module_call(node, self.scopes, "threading",
                                            "Thread")):
                    continue
                for kw in node.keywords:
                    if kw.arg != "target":
                        continue
                    v = kw.value
                    if isinstance(v, ast.Attribute) and \
                            isinstance(v.value, ast.Name) and \
                            v.value.id == "self":
                        cand = f"{cls_name}.{v.attr}"
                        if cand in methods:
                            workers.add(cand)
                    elif isinstance(v, ast.Name):
                        # nested worker fn: its writes already count as
                        # part of the enclosing method's lexical extent,
                        # so mark the ENCLOSING method worker-side
                        workers.add(qual)
        # transitive: worker -> self.m() -> m is worker-side too
        changed = True
        while changed:
            changed = False
            for qual in list(workers):
                fn = methods.get(qual)
                if fn is None:
                    continue
                for node in self._nodes_in(fn):
                    cn = _call_name(node)
                    if cn and cn[0] == "self":
                        cand = f"{cls_name}.{cn[1]}"
                        if cand in methods and cand not in workers:
                            workers.add(cand)
                            changed = True
        return workers

    def rule_thread_shared_write(self):
        for cls_name, cls_node in self.scopes.classes.items():
            # direct methods only: a nested worker function's writes are
            # already covered by the lexical walk of its enclosing method —
            # listing it separately would count the same write on both
            # sides and fabricate a race with itself
            direct = {id(stmt) for stmt in cls_node.body
                      if isinstance(stmt, (ast.FunctionDef,
                                           ast.AsyncFunctionDef))}
            methods = {q: f for q, f in self.scopes.functions.items()
                       if self.scopes.func_class.get(q) == cls_name
                       and id(f) in direct}
            if not methods:
                continue
            workers = self._worker_funcs(cls_name, cls_node, methods)
            if not workers:
                continue
            lock_attrs = self._lock_attrs(cls_node)
            worker_writes = {}   # attr -> [(locks, line, qual)]
            consumer_writes = {}
            for qual, fn in methods.items():
                if qual.endswith(".__init__") and qual not in workers:
                    continue  # pre-thread-start writes are safe
                side = worker_writes if qual in workers else consumer_writes
                for attr, locks, line in self._self_writes(fn, lock_attrs):
                    side.setdefault(attr, []).append((locks, line, qual))
            for attr in sorted(set(worker_writes) & set(consumer_writes)):
                all_w = worker_writes[attr] + consumer_writes[attr]
                common = frozenset.intersection(
                    *[locks for locks, _l, _q in all_w]) if all_w else \
                    frozenset()
                if common:
                    continue  # every write holds a shared lock
                wl = worker_writes[attr][0]
                cl = consumer_writes[attr][0]
                self._emit(
                    "thread-shared-write", wl[1], 0, wl[2],
                    f"self.{attr} written by worker thread ({wl[2]} "
                    f"l.{wl[1]}) and consumer ({cl[2]} l.{cl[1]}) with no "
                    f"common lock — guard both writes with one lock or "
                    f"hand the value over a queue")

    # -- hot-path reachability (hot-sync + retrace-hazard part 1) ----------
    def _reachable_from(self, entries):
        seen = set()
        work = [q for q in entries if q in self.scopes.functions]
        while work:
            qual = work.pop()
            if qual in seen:
                continue
            seen.add(qual)
            fn = self.scopes.functions[qual]
            cls = self.scopes.func_class.get(qual)
            for node in self._nodes_in(fn):
                cn = _call_name(node)
                if not cn:
                    continue
                kind, name = cn
                cand = None
                if kind == "self" and cls:
                    cand = f"{cls}.{name}"
                elif kind == "name":
                    if f"{qual}.{name}" in self.scopes.functions:
                        cand = f"{qual}.{name}"      # nested helper
                    elif name in self.scopes.functions:
                        cand = name                  # module-level fn
                if cand in self.scopes.functions and cand not in seen:
                    work.append(cand)
        return seen

    def rule_hot_path(self):
        entries = self.hot_entries.get(self.path)
        if not entries:
            return
        for q in entries:
            if q not in self.scopes.functions:
                # a renamed/moved dispatch body must not silently turn the
                # flagship rule into a no-op for this file — fail loudly
                # so HOT_PATH_ENTRIES is updated alongside the refactor
                self._emit(
                    "stale-hot-entry", 1, 0, q,
                    f"hot-path entry point {q!r} (HOT_PATH_ENTRIES in "
                    f"tools/mxlint.py) does not resolve in this file — "
                    f"update the entry list to the renamed/moved per-step "
                    f"dispatch body")
        reach = self._reachable_from(entries)
        for qual in sorted(reach):
            fn = self.scopes.functions[qual]
            for node in self._nodes_in(fn):
                if not isinstance(node, ast.Call):
                    continue
                self._check_sync_call(node, qual)
                if _is_module_call(node, self.scopes, "jax", "jit"):
                    self._emit(
                        "retrace-hazard", node.lineno, node.col_offset,
                        qual,
                        "jax.jit constructed inside a per-step hot path — "
                        "every construction recompiles; hoist it or cache "
                        "the jitted callable by signature")

    def _check_sync_call(self, node, qual):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in SYNC_ATTRS:
            # np.asarray-style module funcs handled below; any-receiver
            # method syncs (x.item(), x.block_until_ready()) land here
            self._emit(
                "hot-sync", node.lineno, node.col_offset, qual,
                f".{f.attr}() forces a device->host sync inside the "
                f"per-step dispatch path — defer readback (AsyncLoss) or "
                f"move it off the hot path")
            return
        if isinstance(f, ast.Attribute) and f.attr in MEM_ATTRS:
            # any-receiver memory probes (dev.memory_stats(),
            # compiled.memory_analysis()) and jax.live_arrays()
            self._emit(
                "hot-sync", node.lineno, node.col_offset, qual,
                f".{f.attr}() polls memory inside the per-step dispatch "
                f"path — sample at step boundaries via mxnet_tpu.memwatch "
                f"(on_step/on_checkpoint) instead")
            return
        if _is_module_call(node, self.scopes, "jax", "live_arrays"):
            # from-import form: `from jax import live_arrays`
            self._emit(
                "hot-sync", node.lineno, node.col_offset, qual,
                "jax.live_arrays() walks every live buffer inside the "
                "per-step dispatch path — sample at step boundaries via "
                "mxnet_tpu.memwatch instead")
            return
        if _is_module_call(node, self.scopes, "numpy", "asarray"):
            arg = node.args[0] if node.args else None
            if isinstance(arg, (ast.List, ast.Tuple, ast.Dict, ast.ListComp,
                                ast.DictComp, ast.GeneratorExp,
                                ast.Constant)):
                return  # building from host literals, not reading a device
            self._emit(
                "hot-sync", node.lineno, node.col_offset, qual,
                "np.asarray() on a (possibly device) array inside the "
                "per-step dispatch path blocks until the value is on host")
            return
        if isinstance(f, ast.Name) and f.id == "float":
            arg = node.args[0] if node.args else None
            if arg is None or isinstance(arg, ast.Constant):
                return
            self._emit(
                "hot-sync", node.lineno, node.col_offset, qual,
                "float() inside the per-step dispatch path — on a device "
                "value this is a hidden blocking readback")

    # -- jax-in-handler: jax-free reachability ----------------------------
    def _is_jax_module(self, name) -> bool:
        return name == "jax" or (name or "").startswith("jax.")

    def rule_jax_free(self):
        entries = self.jax_free.get(self.path)
        if not entries:
            return
        for q in entries:
            if q not in self.scopes.functions:
                self._emit(
                    "stale-hot-entry", 1, 0, q,
                    f"jax-free entry point {q!r} (JAX_FREE_ENTRIES in "
                    f"tools/mxlint.py) does not resolve in this file — "
                    f"update the entry list to the renamed/moved handler")
        # aliases bound to the jax module anywhere in the file: a
        # module-level `import jax as j` used inside the handler is the
        # same defect as an inline import
        jax_aliases = {alias for alias, mod in self.scopes.mod_aliases.items()
                       if self._is_jax_module(mod)}
        jax_names = {name for name, target in self.scopes.from_names.items()
                     if self._is_jax_module(target.rsplit(".", 1)[0])
                     or target.startswith("jax.")}
        reach = self._reachable_from(entries)
        for qual in sorted(reach):
            fn = self.scopes.functions[qual]
            for node in self._nodes_in(fn):
                if isinstance(node, ast.Import):
                    for a in node.names:
                        if self._is_jax_module(a.name):
                            self._emit(
                                "jax-in-handler", node.lineno,
                                node.col_offset, qual,
                                "jax import inside a jax-free handler — "
                                "the metrics endpoint must serve the "
                                "recorder's rollups only (no runtime "
                                "init, no device sync, from a scrape)")
                elif isinstance(node, ast.ImportFrom):
                    if self._is_jax_module(node.module or ""):
                        self._emit(
                            "jax-in-handler", node.lineno, node.col_offset,
                            qual,
                            "jax import inside a jax-free handler — "
                            "serve the recorder's rollups only")
                elif isinstance(node, ast.Name) and \
                        (node.id in jax_aliases or node.id in jax_names):
                    self._emit(
                        "jax-in-handler", node.lineno, node.col_offset,
                        qual,
                        f"{node.id!r} resolves to jax — a jax-free "
                        "handler must not reach the runtime (serve the "
                        "recorder's rollups only)")
                elif isinstance(node, ast.Call):
                    # the handler also gets the full hot-sync readback
                    # checks: .item()/np.asarray()/memory_stats() from a
                    # scrape thread stalls the training loop just as a
                    # per-step sync would
                    self._check_sync_call(node, qual)

    # -- pass-outside-pipeline --------------------------------------------
    def rule_pass_pipeline(self):
        """The op-dispatch body may consult module-global trace-rewrite
        state ONLY through the pass-hook protocol: the one
        ``_pass_hooks._OP_HOOKS`` read (plus explicitly allowed
        non-pass plumbing).  Any other ``<module>._underscore`` load in
        the body is a pass smuggled around the pipeline — invisible to
        the pipeline fingerprint, so two different traced programs
        would collide on one AOT cache key."""
        cfg = self.pass_entries.get(self.path)
        if not cfg:
            return
        qual = cfg["function"]
        fn = self.scopes.functions.get(qual)
        if fn is None:
            # a renamed/moved dispatch body must not silently turn the
            # rule into a no-op — same contract as stale-hot-entry
            self._emit(
                "pass-outside-pipeline", 1, 0, qual,
                f"configured dispatch body {qual!r} (PASS_DISPATCH_ENTRIES "
                f"in tools/mxlint.py) does not resolve in this file — "
                f"update the entry to the renamed/moved dispatch point")
            return
        hook_mod = cfg.get("hook_module")
        allowed = {tuple(a) for a in cfg.get("allowed", ())}
        # names bound by ANY import in the file (incl. `from .. import
        # autograd` inside functions): only module aliases are candidate
        # global-state carriers — locals like `x._data` are not
        imported = set()
        for n in self.all_nodes:
            if isinstance(n, (ast.Import, ast.ImportFrom)):
                for a in n.names:
                    imported.add((a.asname or a.name).split(".")[0])
        saw_hook = False
        for node in self._nodes_in(fn):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.ctx, ast.Load)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in imported
                    and node.attr.startswith("_")):
                continue
            pair = (node.value.id, node.attr)
            if pair in allowed:
                if pair[0] == hook_mod:
                    saw_hook = True
                continue
            self._emit(
                "pass-outside-pipeline", node.lineno, node.col_offset,
                qual,
                f"dispatch body consults {pair[0]}.{pair[1]} — "
                f"module-global pass state outside the pass-hook "
                f"protocol; register a GraphPass (passes/pipeline.py) "
                f"whose scope pushes an OpHook, and let the one "
                f"{hook_mod}._OP_HOOKS read carry it")
        if hook_mod and not saw_hook:
            self._emit(
                "pass-outside-pipeline", fn.lineno, fn.col_offset, qual,
                f"dispatch body no longer consults "
                f"{hook_mod}._OP_HOOKS — the pass pipeline is "
                f"disconnected from dispatch (or the consultation moved: "
                f"update PASS_DISPATCH_ENTRIES in tools/mxlint.py)")

    # -- retrace-hazard part 2: unhashable static args --------------------
    def rule_static_argnums(self):
        jitted = {}  # name -> static positions
        for node in self.all_nodes:
            if isinstance(node, ast.Assign) and \
                    _is_module_call(node.value, self.scopes, "jax", "jit"):
                positions = []
                for kw in node.value.keywords:
                    if kw.arg != "static_argnums":
                        continue
                    v = kw.value
                    elts = v.elts if isinstance(v, ast.Tuple) else [v]
                    for e in elts:
                        if isinstance(e, ast.Constant) and \
                                isinstance(e.value, int):
                            positions.append(e.value)
                if positions:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            jitted[t.id] = positions
        if not jitted:
            return
        for node in self.all_nodes:
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in jitted):
                continue
            for pos in jitted[node.func.id]:
                if pos < len(node.args) and isinstance(
                        node.args[pos], (ast.List, ast.Dict, ast.Set,
                                         ast.ListComp, ast.DictComp,
                                         ast.SetComp)):
                    self._emit(
                        "retrace-hazard", node.lineno, node.col_offset,
                        None,
                        f"unhashable literal passed in static_argnums "
                        f"position {pos} of jitted "
                        f"{node.func.id!r} — static arguments must be "
                        f"hashable (tuple, not list/dict/set)")


# ---------------------------------------------------------------------------
# project driver
# ---------------------------------------------------------------------------
def load_env_registry(root):
    """ENV_VARS keys, parsed statically from mxnet_tpu/env_vars.py (mxlint
    never imports the package — stdlib-only, importable-tree-independent)."""
    path = os.path.join(root, "mxnet_tpu", "env_vars.py")
    try:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read())
    except (OSError, SyntaxError):
        return None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "ENV_VARS" and \
                        isinstance(node.value, ast.Dict):
                    return {k.value for k in node.value.keys
                            if isinstance(k, ast.Constant)
                            and isinstance(k.value, str)}
        if isinstance(node, ast.AnnAssign) and \
                isinstance(node.target, ast.Name) and \
                node.target.id == "ENV_VARS" and \
                isinstance(node.value, ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return None


def iter_py_files(paths, root):
    for p in paths:
        ap = p if os.path.isabs(p) else os.path.join(root, p)
        if not os.path.exists(ap):
            raise ValueError(f"no such file or directory: {p}")
        if os.path.isfile(ap) and ap.endswith(".py"):
            yield ap
        elif os.path.isdir(ap):
            for dirpath, dirnames, filenames in os.walk(ap):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"
                               and not d.startswith(".")]
                for fname in sorted(filenames):
                    if fname.endswith(".py"):
                        yield os.path.join(dirpath, fname)


def _rel(path, root):
    ap = os.path.abspath(path)
    r = os.path.abspath(root)
    if ap.startswith(r + os.sep):
        return os.path.relpath(ap, r).replace(os.sep, "/")
    return ap.replace(os.sep, "/")


def run_lint(paths=None, root=None, rules=None, hot_entries=None,
             env_registry=None, jax_free_entries=None, pass_entries=None):
    """Analyze `paths` (files or dirs); returns (findings, stats).

    `rules`: iterable restricting which rules run (default: all).
    `hot_entries`/`env_registry`/`jax_free_entries`/`pass_entries`:
    overrides for tests/fixtures.
    """
    root = root or REPO
    paths = list(paths) if paths else list(DEFAULT_PATHS)
    active = set(rules) if rules else set(RULES)
    unknown = active - set(RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
    # meta rules always run: suppressions must be spellable, files
    # parsable, configured entry points resolvable
    active |= {"bad-suppression", "syntax-error", "stale-hot-entry"}
    registry_missing = False
    if env_registry is None:
        env_registry = load_env_registry(root)
        registry_missing = env_registry is None and \
            "env-unregistered" in active
    entries = hot_entries if hot_entries is not None else HOT_PATH_ENTRIES
    jax_free = (jax_free_entries if jax_free_entries is not None
                else JAX_FREE_ENTRIES)
    findings, nfiles, suppressed = [], 0, 0
    for ap in iter_py_files(paths, root):
        rel = _rel(ap, root)
        try:
            with open(ap, encoding="utf-8", errors="replace") as f:
                text = f.read()
        except OSError as e:
            raise ValueError(f"cannot read {ap}: {e}")
        nfiles += 1
        fl = FileLint(ap, rel, text, env_registry, entries, active,
                      jax_free_entries=jax_free, pass_entries=pass_entries)
        findings.extend(fl.run())
        suppressed += fl.suppressed
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, {"files": nfiles, "suppressed": suppressed,
                      "active_rules": sorted(active),
                      "env_registry_missing": registry_missing}


# ---------------------------------------------------------------------------
# baseline
# ---------------------------------------------------------------------------
def _fingerprint(finding, root):
    """Line-number-independent identity: rule + path + context + the
    stripped source line (survives unrelated edits above the site)."""
    text = ""
    ap = os.path.join(root, finding.path)
    try:
        with open(ap, encoding="utf-8", errors="replace") as f:
            lines = f.read().splitlines()
        if 0 < finding.line <= len(lines):
            text = lines[finding.line - 1].strip()
    except OSError:
        pass
    return {"rule": finding.rule, "path": finding.path,
            "context": finding.context, "line_text": text}


def load_baseline(path):
    try:
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
    except FileNotFoundError:
        return []
    except (OSError, ValueError) as e:
        raise ValueError(f"baseline {path} unreadable: {e}")
    entries = data.get("entries", []) if isinstance(data, dict) else data
    for e in entries:
        if not isinstance(e, dict) or "rule" not in e or "path" not in e:
            raise ValueError(f"baseline {path}: malformed entry {e!r}")
    return entries


def apply_baseline(findings, entries, root):
    """Split findings into (new, baselined); also returns stale baseline
    entries that matched nothing (candidates for removal)."""
    remaining = list(entries)
    new, baselined = [], []
    for f in findings:
        fp = _fingerprint(f, root)
        hit = None
        for e in remaining:
            if (e["rule"] == fp["rule"] and e["path"] == fp["path"]
                    and e.get("context", "") == fp["context"]
                    and e.get("line_text", "").strip() == fp["line_text"]):
                hit = e
                break
        if hit is not None:
            remaining.remove(hit)
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined, remaining


def write_baseline(path, findings, root, old_entries, extra_entries=()):
    """Regenerate the baseline from current findings, carrying forward
    justifications for entries that still match; new entries are marked
    UNREVIEWED and must be justified by hand before review.
    `extra_entries` pass through verbatim (entries of rules the current
    invocation didn't run and therefore cannot re-derive)."""
    old = {(e["rule"], e["path"], e.get("context", ""),
            e.get("line_text", "").strip()): e.get("justification", "")
           for e in old_entries}
    entries = list(extra_entries)
    for f in findings:
        fp = _fingerprint(f, root)
        key = (fp["rule"], fp["path"], fp["context"], fp["line_text"])
        fp["justification"] = old.get(key) or f"UNREVIEWED: {f.message}"
        entries.append(fp)
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "entries": entries}, f, indent=1)
        f.write("\n")
    return entries


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def main(argv=None):
    ap = argparse.ArgumentParser(
        prog="mxlint",
        description="project AST lint + lightweight race detector "
                    "(exit 0 clean / 2 usage / 3 findings)")
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to scan (default: "
                         f"{' '.join(DEFAULT_PATHS)} under the repo root)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset of rules to run")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report legacy findings too)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(carries forward existing justifications)")
    ap.add_argument("--root", default=REPO, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name:22s} {RULES[name]}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    t0 = time.perf_counter()
    try:
        findings, stats = run_lint(args.paths or None, root=args.root,
                                   rules=rules)
    except ValueError as e:
        print(f"mxlint: {e}", file=sys.stderr)
        return 2

    baseline_path = args.baseline or DEFAULT_BASELINE
    baselined, stale = [], []
    if args.write_baseline:
        try:
            # a malformed baseline must be a loud usage error here too —
            # silently regenerating would discard every reviewed
            # justification in the file being "recovered"
            old = load_baseline(baseline_path)
        except ValueError as e:
            print(f"mxlint: {e}", file=sys.stderr)
            return 2
        # entries for rules that did NOT run this invocation (--rules
        # subset) are out of scope: carry them through untouched instead
        # of deleting them along with their justifications
        keep = [e for e in old if e["rule"] not in stats["active_rules"]]
        entries = write_baseline(baseline_path, findings, args.root, old,
                                 extra_entries=keep)
        print(f"mxlint: wrote {len(entries)} baseline entries to "
              f"{baseline_path}", file=sys.stderr)
        return 0
    if not args.no_baseline:
        try:
            entries = load_baseline(baseline_path)
        except ValueError as e:
            print(f"mxlint: {e}", file=sys.stderr)
            return 2
        findings, baselined, stale = apply_baseline(findings, entries,
                                                    args.root)
        # an entry whose rule didn't run this invocation can't be judged
        # stale — only report entries the active rules had a shot at
        stale = [e for e in stale if e["rule"] in stats["active_rules"]]

    elapsed = time.perf_counter() - t0
    if stats.get("env_registry_missing"):
        print("mxlint: mxnet_tpu/env_vars.py not found/parsable under "
              f"{args.root} — env-unregistered rule skipped",
              file=sys.stderr)
    counts = {}
    for f in findings:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    if args.as_json:
        print(json.dumps({
            "version": 1,
            "root": args.root,
            "files_scanned": stats["files"],
            "elapsed_s": round(elapsed, 3),
            "counts": counts,
            "findings": [f.as_dict() for f in findings],
            "suppressed": stats["suppressed"],
            "baselined": len(baselined),
            "stale_baseline": stale,
        }, indent=1))
    else:
        for f in findings:
            print(f.render())
        for e in stale:
            print(f"mxlint: stale baseline entry (no longer fires): "
                  f"{e['rule']} {e['path']} [{e.get('context', '')}]",
                  file=sys.stderr)
        print(f"mxlint: {len(findings)} finding(s) in "
              f"{stats['files']} files ({elapsed:.2f}s; "
              f"{stats['suppressed']} suppressed inline, "
              f"{len(baselined)} baselined)", file=sys.stderr)
    return 3 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
