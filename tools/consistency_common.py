"""Shared case evaluation for the cpu-vs-tpu consistency oracle.

Replays tests/test_op_sweep.py's registry-wide cases on a given context;
both halves of tools/check_consistency.py (the CPU parent and the TPU
subprocess) import this so the evaluation is bit-identical code.

Reference: tests/python/gpu/test_operator_gpu.py check_consistency ~L1300 —
the framework's main correctness oracle for a new backend (SURVEY §4.4).
"""
from __future__ import annotations

import os
import sys

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_cases():
    """Import the sweep cases without pytest collecting anything."""
    for p in (os.path.join(_REPO, "tests"), _REPO):
        if p not in sys.path:
            sys.path.insert(0, p)
    import test_op_sweep as sweep

    return sweep


def eval_case(case, ctx, with_grad=True):
    """Deterministic forward (+ analytic gradient) of one sweep case on ctx.

    Returns (list_of_forward_arrays, list_of_grad_arrays_or_None).
    Inputs are seeded identically on every platform; gradients go through
    the autograd tape (jax.vjp), i.e. the exact path training uses.
    """
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd

    sweep = load_cases()
    mx.random.seed(0)
    rng = np.random.RandomState(11)
    arrs = sweep._inputs_np(case, rng)
    inputs = [nd.array(a, ctx=ctx) for a in arrs]

    out = case.fn(*inputs)
    outs = out if isinstance(out, (list, tuple)) else [out]
    fwd = [np.asarray(o.asnumpy(), dtype=np.float64) for o in outs]

    grads = None
    if with_grad and case.grad:
        inputs = [nd.array(a, ctx=ctx) for a in arrs]
        for i, x in enumerate(inputs):
            if i not in case.int_inputs:
                x.attach_grad()
        with autograd.record():
            loss = sweep._sum_all(case.fn(*inputs))
        loss.backward()
        grads = [
            (None if i in case.int_inputs or inputs[i].grad is None
             else np.asarray(inputs[i].grad.asnumpy(), dtype=np.float64))
            for i in range(len(inputs))
        ]
    return fwd, grads


def compare(case, got, want, rtol, atol, kind):
    """Compare one case's arrays; returns None on match, message on drift."""
    for k, (a, b) in enumerate(zip(got, want)):
        if a is None or b is None:
            continue
        scale = max(1.0, float(np.abs(np.asarray(b)).max()))
        try:
            np.testing.assert_allclose(a, b, rtol=rtol, atol=atol * scale)
        except AssertionError as e:
            return (f"{case.id} {kind}[{k}]: "
                    + str(e).strip().splitlines()[0]
                    + f" (max|Δ|={float(np.abs(np.asarray(a) - np.asarray(b)).max()):.3g})")
    return None
