#!/usr/bin/env python
"""Dump the (optimized, when possible) HLO of the fused ResNet-50 train
step, plus an mx.profiler aggregate table — the committed perf evidence the
r3 verdict asked for (analog of inspecting the reference's cuDNN algo
choices / kernel schedule).

    python tools/dump_hlo.py [--layout NHWC] [--batch 256] [--platform auto]

Artifacts land in docs/artifacts/:
    resnet50_step_{layout}_bs{batch}.hlo.txt   (compiler output)
    resnet50_step_{layout}_bs{batch}.profile.txt (per-op aggregate table)

On the TPU platform this is the real XLA:TPU optimized module (layout
assignment, fusion decisions, MXU conv configs all visible); on CPU it
still shows GSPMD partitioning + fusion structure and proves the recipe.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

ART = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "docs", "artifacts")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--layout", default="NHWC", choices=["NHWC", "NCHW"])
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--res", type=int, default=224)
    ap.add_argument("--platform", default="auto", choices=["auto", "cpu", "tpu"])
    ap.add_argument("--profile-steps", type=int, default=0,
                    help="also run N profiled steps for the aggregate table")
    args = ap.parse_args()

    import jax

    if args.platform == "auto":
        # the relay can hang on first backend touch — probe via bench.py's
        # subprocess-with-timeout machinery instead of trusting the env
        import bench as _bench

        args.platform = "tpu" if _bench._probe_tpu([]) else "cpu"
    if args.platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    else:
        platform = "tpu"
        # refuse to write a .tpu artifact from a silent CPU fallback (no
        # axon env → JAX quietly uses the host backend)
        measured = jax.devices()[0].platform
        if measured == "cpu":
            print(f"ERROR: requested tpu but measured backend is cpu",
                  file=sys.stderr)
            return 1

    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1b
    from mxnet_tpu.parallel import DataParallelStep, local_mesh

    on_tpu = platform == "tpu"
    ctx = mx.tpu() if on_tpu else mx.cpu()
    mx.context.Context._default_ctx.value = ctx
    mx.random.seed(0)

    net = resnet50_v1b(layout=args.layout)
    net.initialize(mx.init.Xavier())
    if on_tpu:
        net.cast("bfloat16")
    step = DataParallelStep(
        net, gluon.loss.SoftmaxCrossEntropyLoss(),
        mesh=local_mesh(devices=[ctx.jax_device]), optimizer="sgd",
        optimizer_params={"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4})

    shape = ((args.batch, 3, args.res, args.res) if args.layout == "NCHW"
             else (args.batch, args.res, args.res, 3))
    x = np.random.rand(*shape).astype(np.float32)
    if on_tpu:
        import ml_dtypes

        x = x.astype(ml_dtypes.bfloat16)
    y = np.random.randint(0, 1000, args.batch).astype(np.float32)
    xb = nd.array(x, ctx=ctx, dtype=x.dtype)
    yb = nd.array(y, ctx=ctx)

    # one step builds + compiles the jitted function
    t0 = time.perf_counter()
    loss = step.step(xb, yb)
    float(np.asarray(loss))
    compile_s = time.perf_counter() - t0

    os.makedirs(ART, exist_ok=True)
    tag = f"resnet50_step_{args.layout.lower()}_bs{args.batch}"

    texts = []
    try:
        # re-lower with the same arg structure to get a compilable module
        traced = step._jitted.lower(
            step.params, step.opt_state,
            jax.random.PRNGKey(0), xb._data, yb._data)
        compiled = traced.compile()
        texts.append(("optimized", compiled.as_text()))
    except Exception as e:  # fall back to pre-optimization stablehlo
        try:
            texts.append(("stablehlo", traced.as_text()))
        except Exception:
            texts.append(("error", f"lowering failed: {e}"))

    hlo_path = os.path.join(ART, tag + f".{platform}.hlo.txt")
    with open(hlo_path, "w") as f:
        f.write(f"# platform={platform} layout={args.layout} "
                f"batch={args.batch} res={args.res} "
                f"first-step(incl compile)={compile_s:.1f}s\n")
        for kind, text in texts:
            f.write(f"\n### {kind}\n{text}\n")
    # quick signal: count layout-change ops (transpose/copy) in the module
    ntrans = sum(t.count("transpose(") for _, t in texts)
    print(f"wrote {hlo_path} ({sum(len(t) for _, t in texts)} bytes, "
          f"{ntrans} transpose sites)")

    if args.profile_steps:
        from mxnet_tpu import profiler

        profiler.set_config(profile_all=True)
        profiler.start()
        for _ in range(args.profile_steps):
            loss = step.step(xb, yb)
        float(np.asarray(loss))
        profiler.stop()
        table = profiler.dumps(reset=True)
        ppath = os.path.join(ART, tag + f".{platform}.profile.txt")
        with open(ppath, "w") as f:
            f.write(table)
        print(f"wrote {ppath}")


if __name__ == "__main__":
    sys.exit(main() or 0)
