// libmxio: native RecordIO image pipeline.
//
// Reference parity: src/io/iter_image_recordio_2.cc (ImageRecordIOParser2 —
// chunked .rec read, per-thread JPEG decode + augmentation, batch assembly,
// ~L400), src/io/image_aug_default.cc (DefaultImageAugmenter ~L200),
// iter_prefetcher.h (double-buffered batch queue), and dmlc-core recordio.h
// (magic 0xced7230a framing).
//
// TPU-native design: the output is a host-side float32/uint8 NCHW batch the
// Python layer hands to jax.device_put (async H2D on the PjRt stream) — the
// TPU analog of the reference's cpu_pinned staging.  Decode/augment runs on
// a std::thread pool with a per-batch completion barrier and a bounded
// prefetch queue, so Python never blocks on image work unless it outruns
// the pipeline.
//
// Build: make -C src   (links OpenCV core/imgproc/imgcodecs)
// C ABI only — loaded from Python with ctypes (no pybind11 in this image).

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fstream>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <opencv2/core.hpp>
#include <opencv2/imgcodecs.hpp>
#include <opencv2/imgproc.hpp>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLRecMask = (1u << 29) - 1;

struct Record {
  uint64_t offset;  // payload offset in file
  uint32_t length;  // payload length
};

// IRHeader: [flag u32][label f32][id u64][id2 u64] then flag extra float
// labels, then image bytes (reference: python/mxnet/recordio.py IRHeader).
#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)

struct IterParams {
  int batch_size = 1;
  int channels = 3;
  int height = 224;
  int width = 224;
  int threads = 4;
  int shuffle = 0;
  unsigned seed = 0;
  int resize_short = 0;   // resize shorter side to this before crop (0: off)
  int rand_crop = 0;
  int rand_mirror = 0;
  float scale = 1.0f;
  float mean[3] = {0.f, 0.f, 0.f};
  float std_[3] = {1.f, 1.f, 1.f};
  int label_width = 1;
  int prefetch = 2;
  float brightness = 0.f;  // random jitter ranges (0: off)
  float contrast = 0.f;
  float saturation = 0.f;
  float hue = 0.f;         // max hue shift in OpenCV H units (0-90)
  float pca_noise = 0.f;   // PCA lighting alpha stddev (image_aug_default.cc)
  uint64_t shuffle_chunk_bytes = 0;  // 0: full random shuffle
};

// ImageNet RGB PCA eigen decomposition on the 0-255 scale (reference:
// src/io/image_aug_default.cc DefaultImageAugmenter pca_noise ~L200,
// the AlexNet lighting values).
constexpr float kEigval[3] = {55.46f, 4.794f, 1.148f};
constexpr float kEigvec[3][3] = {{-0.5675f, 0.7192f, 0.4009f},
                                 {-0.5808f, -0.0045f, -0.8140f},
                                 {-0.5836f, -0.6948f, 0.4203f}};

struct Batch {
  std::vector<float> data;
  std::vector<float> label;
  int n = 0;  // valid rows
};

class ImageRecordIter {
 public:
  ImageRecordIter(const std::string& path, const IterParams& p)
      : p_(p), file_(path, std::ios::binary) {
    if (!file_) throw std::runtime_error("cannot open " + path);
    IndexRecords();
    order_.resize(records_.size());
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    Reset();
  }

  ~ImageRecordIter() { StopWorkers(); }

  int64_t NumRecords() const { return static_cast<int64_t>(records_.size()); }

  void Reset() {
    StopWorkers();
    epoch_++;
    if (p_.shuffle) {
      std::mt19937 rng(p_.seed + epoch_);
      if (p_.shuffle_chunk_bytes == 0) {
        std::shuffle(order_.begin(), order_.end(), rng);
      } else {
        // chunked shuffle (reference: shuffle_chunk_size — bounded-memory
        // shuffling for .rec files larger than RAM): partition the
        // SEQUENTIAL record order into byte-bounded chunks, shuffle the
        // chunk order, then shuffle within each chunk.  Disk reads stay
        // chunk-local while the stream is still well mixed.
        for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
        std::vector<std::pair<size_t, size_t>> chunks;  // [begin, end)
        size_t begin = 0;
        uint64_t acc = 0;
        for (size_t i = 0; i < order_.size(); ++i) {
          acc += records_[i].length + 8;
          if (acc >= p_.shuffle_chunk_bytes || i + 1 == order_.size()) {
            chunks.emplace_back(begin, i + 1);
            begin = i + 1;
            acc = 0;
          }
        }
        std::shuffle(chunks.begin(), chunks.end(), rng);
        std::vector<size_t> shuffled;
        shuffled.reserve(order_.size());
        for (auto& ch : chunks) {
          size_t lo = shuffled.size();
          for (size_t i = ch.first; i < ch.second; ++i)
            shuffled.push_back(i);
          std::shuffle(shuffled.begin() + lo, shuffled.end(), rng);
        }
        order_ = std::move(shuffled);
      }
    }
    cursor_ = 0;
    done_ = false;
    stop_ = false;
    producer_ = std::thread([this] { ProducerLoop(); });
  }

  // returns 1 and fills data/label, or 0 at epoch end
  int Next(float* data, float* label) {
    std::unique_lock<std::mutex> lk(mu_);
    cv_pop_.wait(lk, [this] { return !queue_.empty() || done_; });
    if (queue_.empty()) return 0;
    Batch b = std::move(queue_.front());
    queue_.pop_front();
    cv_push_.notify_one();
    lk.unlock();
    std::memcpy(data, b.data.data(), b.data.size() * sizeof(float));
    std::memcpy(label, b.label.data(), b.label.size() * sizeof(float));
    return 1;
  }

 private:
  void IndexRecords() {
    file_.seekg(0, std::ios::end);
    uint64_t fsize = static_cast<uint64_t>(file_.tellg());
    file_.seekg(0);
    uint64_t pos = 0;
    while (pos + 8 <= fsize) {
      uint32_t hdr[2];
      file_.seekg(pos);
      file_.read(reinterpret_cast<char*>(hdr), 8);
      if (!file_ || hdr[0] != kMagic) break;
      uint32_t len = hdr[1] & kLRecMask;
      records_.push_back({pos + 8, len});
      uint64_t padded = (len + 3u) & ~3u;  // 4-byte alignment
      pos += 8 + padded;
    }
    file_.clear();
  }

  void ProducerLoop() {
    const size_t n = order_.size();
    const int bs = p_.batch_size;
    while (!stop_) {
      size_t start = cursor_;
      if (start >= n) break;
      size_t count = std::min<size_t>(bs, n - start);
      cursor_ += count;

      Batch batch;
      batch.n = static_cast<int>(count);
      batch.data.assign(
          static_cast<size_t>(bs) * p_.channels * p_.height * p_.width, 0.f);
      batch.label.assign(static_cast<size_t>(bs) * p_.label_width, 0.f);

      // parallel decode of this batch (the reference's OMP parallel-for)
      std::atomic<size_t> next_slot{0};
      auto worker = [&] {
        for (;;) {
          size_t slot = next_slot.fetch_add(1);
          if (slot >= count || stop_) return;
          DecodeOne(order_[start + slot], slot, &batch);
        }
      };
      int nthreads = std::min<int>(p_.threads, static_cast<int>(count));
      std::vector<std::thread> pool;
      for (int t = 1; t < nthreads; ++t) pool.emplace_back(worker);
      worker();
      for (auto& t : pool) t.join();
      if (stop_) return;

      std::unique_lock<std::mutex> lk(mu_);
      cv_push_.wait(lk, [this] {
        return static_cast<int>(queue_.size()) < p_.prefetch || stop_;
      });
      if (stop_) return;
      queue_.push_back(std::move(batch));
      cv_pop_.notify_one();
    }
    std::lock_guard<std::mutex> lk(mu_);
    done_ = true;
    cv_pop_.notify_all();
  }

  void DecodeOne(size_t rec_idx, size_t slot, Batch* batch) {
    const Record& rec = records_[rec_idx];
    std::vector<unsigned char> buf(rec.length);
    {
      std::lock_guard<std::mutex> lk(file_mu_);
      file_.seekg(rec.offset);
      file_.read(reinterpret_cast<char*>(buf.data()), rec.length);
    }
    if (buf.size() < sizeof(IRHeader)) return;
    IRHeader hdr;
    std::memcpy(&hdr, buf.data(), sizeof(IRHeader));
    size_t label_bytes = hdr.flag * sizeof(float);
    size_t img_off = sizeof(IRHeader) + label_bytes;
    if (buf.size() < img_off) return;

    // labels
    float* lab = batch->label.data() + slot * p_.label_width;
    if (hdr.flag == 0) {
      lab[0] = hdr.label;
    } else {
      const float* extra =
          reinterpret_cast<const float*>(buf.data() + sizeof(IRHeader));
      for (int i = 0; i < p_.label_width && i < static_cast<int>(hdr.flag);
           ++i)
        lab[i] = extra[i];
    }

    cv::Mat raw(1, static_cast<int>(buf.size() - img_off), CV_8UC1,
                buf.data() + img_off);
    cv::Mat img = cv::imdecode(raw, cv::IMREAD_COLOR);  // BGR
    if (img.empty()) return;

    // per-record deterministic RNG (reference: with_seed discipline)
    std::mt19937 rng(p_.seed * 2654435761u + rec_idx * 97u + epoch_);

    // resize shorter side
    if (p_.resize_short > 0) {
      int shorter = std::min(img.rows, img.cols);
      double s = static_cast<double>(p_.resize_short) / shorter;
      cv::resize(img, img, cv::Size(), s, s,
                 s < 1 ? cv::INTER_AREA : cv::INTER_LINEAR);
    }
    // crop to target (random or center), resizing up if needed
    if (img.rows < p_.height || img.cols < p_.width) {
      cv::resize(img, img, cv::Size(std::max(img.cols, p_.width),
                                    std::max(img.rows, p_.height)));
    }
    int y0, x0;
    if (p_.rand_crop) {
      std::uniform_int_distribution<int> dy(0, img.rows - p_.height);
      std::uniform_int_distribution<int> dx(0, img.cols - p_.width);
      y0 = dy(rng);
      x0 = dx(rng);
    } else {
      y0 = (img.rows - p_.height) / 2;
      x0 = (img.cols - p_.width) / 2;
    }
    img = img(cv::Rect(x0, y0, p_.width, p_.height));

    if (p_.rand_mirror) {
      std::bernoulli_distribution flip(0.5);
      if (flip(rng)) cv::flip(img, img, 1);
    }
    // color jitter (reference: DefaultImageAugmenter HSL jitter ~L200)
    if (p_.brightness > 0.f || p_.contrast > 0.f) {
      std::uniform_real_distribution<float> db(-p_.brightness, p_.brightness);
      std::uniform_real_distribution<float> dc(-p_.contrast, p_.contrast);
      float alpha = 1.f + (p_.contrast > 0 ? dc(rng) : 0.f);
      float beta = 255.f * (p_.brightness > 0 ? db(rng) : 0.f);
      img.convertTo(img, -1, alpha, beta);
    }
    if (p_.saturation > 0.f) {
      // blend with per-pixel gray: out = (1+ds)*img - ds*gray
      std::uniform_real_distribution<float> ds(-p_.saturation, p_.saturation);
      float d = ds(rng);
      cv::Mat gray, gray3;
      cv::cvtColor(img, gray, cv::COLOR_BGR2GRAY);
      cv::cvtColor(gray, gray3, cv::COLOR_GRAY2BGR);
      cv::addWeighted(img, 1.f + d, gray3, -d, 0.0, img);
    }
    if (p_.hue > 0.f) {
      std::uniform_real_distribution<float> dh(-p_.hue, p_.hue);
      int shift = static_cast<int>(dh(rng));
      if (shift != 0) {
        cv::Mat hsv;
        cv::cvtColor(img, hsv, cv::COLOR_BGR2HSV);
        for (int y = 0; y < hsv.rows; ++y) {
          unsigned char* row = hsv.ptr<unsigned char>(y);
          for (int x = 0; x < hsv.cols; ++x) {
            int h = row[x * 3] + shift;
            row[x * 3] = static_cast<unsigned char>((h % 180 + 180) % 180);
          }
        }
        cv::cvtColor(hsv, img, cv::COLOR_HSV2BGR);
      }
    }
    // PCA lighting: per-image RGB offset along ImageNet eigenvectors
    float pca[3] = {0.f, 0.f, 0.f};  // indexed by RGB channel
    if (p_.pca_noise > 0.f) {
      std::normal_distribution<float> na(0.f, p_.pca_noise);
      float a0 = na(rng), a1 = na(rng), a2 = na(rng);
      for (int c = 0; c < 3; ++c)
        pca[c] = kEigvec[c][0] * kEigval[0] * a0 +
                 kEigvec[c][1] * kEigval[1] * a1 +
                 kEigvec[c][2] * kEigval[2] * a2;
    }

    // BGR u8 HWC -> RGB f32 CHW with lighting/mean/std/scale
    float* dst = batch->data.data() +
                 slot * p_.channels * p_.height * p_.width;
    const int hw = p_.height * p_.width;
    for (int y = 0; y < p_.height; ++y) {
      const unsigned char* row = img.ptr<unsigned char>(y);
      for (int x = 0; x < p_.width; ++x) {
        for (int c = 0; c < p_.channels; ++c) {
          // OpenCV BGR -> RGB channel order
          float v = static_cast<float>(row[x * 3 + (2 - c)]) + pca[c];
          dst[c * hw + y * p_.width + x] =
              (v - p_.mean[c]) / p_.std_[c] * p_.scale;
        }
      }
    }
  }

  void StopWorkers() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
      cv_push_.notify_all();
      cv_pop_.notify_all();
    }
    if (producer_.joinable()) producer_.join();
    queue_.clear();
  }

  IterParams p_;
  std::ifstream file_;
  std::mutex file_mu_;
  std::vector<Record> records_;
  std::vector<size_t> order_;
  size_t cursor_ = 0;
  int epoch_ = -1;

  std::thread producer_;
  std::deque<Batch> queue_;
  std::mutex mu_;
  std::condition_variable cv_push_, cv_pop_;
  bool done_ = false;
  std::atomic<bool> stop_{false};
};

}  // namespace

extern "C" {

void* MXIOImageIterCreate2(const char* rec_path, int batch_size, int channels,
                           int height, int width, int threads, int shuffle,
                           unsigned seed, int resize_short, int rand_crop,
                           int rand_mirror, float scale, const float* mean,
                           const float* std_, int label_width, int prefetch,
                           float brightness, float contrast, float saturation,
                           float hue, float pca_noise,
                           float shuffle_chunk_mb) {
  try {
    IterParams p;
    p.batch_size = batch_size;
    p.channels = channels;
    p.height = height;
    p.width = width;
    p.threads = threads > 0 ? threads : 4;
    p.shuffle = shuffle;
    p.seed = seed;
    p.resize_short = resize_short;
    p.rand_crop = rand_crop;
    p.rand_mirror = rand_mirror;
    p.scale = scale;
    for (int i = 0; i < 3; ++i) {
      p.mean[i] = mean ? mean[i] : 0.f;
      p.std_[i] = std_ ? std_[i] : 1.f;
    }
    p.label_width = label_width;
    p.prefetch = prefetch > 0 ? prefetch : 2;
    p.brightness = brightness;
    p.contrast = contrast;
    p.saturation = saturation;
    p.hue = hue;
    p.pca_noise = pca_noise;
    p.shuffle_chunk_bytes =
        static_cast<uint64_t>(shuffle_chunk_mb * (1 << 20));
    return new ImageRecordIter(rec_path, p);
  } catch (...) {
    return nullptr;
  }
}

void* MXIOImageIterCreate(const char* rec_path, int batch_size, int channels,
                          int height, int width, int threads, int shuffle,
                          unsigned seed, int resize_short, int rand_crop,
                          int rand_mirror, float scale, const float* mean,
                          const float* std_, int label_width, int prefetch,
                          float brightness, float contrast, float saturation) {
  return MXIOImageIterCreate2(rec_path, batch_size, channels, height, width,
                              threads, shuffle, seed, resize_short, rand_crop,
                              rand_mirror, scale, mean, std_, label_width,
                              prefetch, brightness, contrast, saturation,
                              0.f, 0.f, 0.f);
}

int MXIOImageIterNext(void* handle, float* data, float* label) {
  return static_cast<ImageRecordIter*>(handle)->Next(data, label);
}

void MXIOImageIterReset(void* handle) {
  static_cast<ImageRecordIter*>(handle)->Reset();
}

long long MXIOImageIterNumRecords(void* handle) {
  return static_cast<ImageRecordIter*>(handle)->NumRecords();
}

void MXIOImageIterDestroy(void* handle) {
  delete static_cast<ImageRecordIter*>(handle);
}

// JPEG encode helper for the im2rec tool.  Returns encoded size or -1.
int MXIOEncodeJpeg(const unsigned char* rgb, int height, int width,
                   int quality, unsigned char* out, int out_capacity) {
  try {
    cv::Mat img(height, width, CV_8UC3, const_cast<unsigned char*>(rgb));
    cv::Mat bgr;
    cv::cvtColor(img, bgr, cv::COLOR_RGB2BGR);
    std::vector<unsigned char> buf;
    cv::imencode(".jpg", bgr, buf, {cv::IMWRITE_JPEG_QUALITY, quality});
    if (static_cast<int>(buf.size()) > out_capacity) return -1;
    std::memcpy(out, buf.data(), buf.size());
    return static_cast<int>(buf.size());
  } catch (...) {
    return -1;
  }
}

}  // extern "C"
